"""Binary wire metas (frame version 3, FLAGS_wire_binary_meta).

The contract:
- the tag codec (bm_dumps/bm_loads) round-trips everything
  json.dumps(meta) can carry — with JSON's semantics (dict keys
  stringified) — plus raw bytes, and rejects corrupt buffers with the
  framing's typed FrameCorruptError
- version-3 frames carry the same payloads as version 2; readers
  (read_msg AND the journal scanner) accept both unconditionally, so
  a journal interleaving both versions replays fine
- the upgrade is NEGOTIATED per connection: a flag-on sender keeps
  emitting version-2 JSON metas (with a one-key 'bmeta' capability
  advert) until the peer proves it speaks v3 — an old peer that never
  adverts keeps the connection on JSON forever, and a flag-off sender
  never adverts at all
"""
import socket

import numpy as np
import pytest

from paddle_tpu import flags
from paddle_tpu.distributed import wire


@pytest.fixture
def bmeta_flag():
    old = flags.get_flag('wire_binary_meta')
    yield
    flags.set_flags({'FLAGS_wire_binary_meta': old})


def _version_byte(sock):
    # u32 crc | u32 body_len | u8 version — peek, don't consume
    raw = sock.recv(9, socket.MSG_PEEK)
    assert len(raw) == 9
    return raw[8]


def test_bm_codec_json_semantics_round_trip():
    meta = {'seq': 7, 'name': 'w@2', 'ok': True, 'off': False,
            'none': None, 'f': -1.5, 'big': 2 ** 40, 'neg': -3,
            'list': [1, 'two', [3.0, False, None]],
            'nested': {'a': {'b': 'c'}, 'd': [1, 2]},
            'uni': 'héllo ✓'}
    assert wire.bm_loads(wire.bm_dumps(meta)) == meta
    # JSON key semantics: non-string keys are stringified
    assert wire.bm_loads(wire.bm_dumps({1: 'x'})) == {'1': 'x'}
    # beyond JSON: raw bytes survive (digest metas need this)
    out = wire.bm_loads(wire.bm_dumps({'dig': b'\x00\xff\x01'}))
    assert out['dig'] == b'\x00\xff\x01'


def test_bm_codec_rejects_corrupt_buffers():
    with pytest.raises(wire.FrameCorruptError):
        wire.bm_loads(b'\xee\x00\x00\x00\x00')       # unknown tag
    with pytest.raises(wire.FrameCorruptError):
        wire.bm_loads(wire.bm_dumps({'a': 1}) + b'\x01')  # trailing


def test_v3_frames_round_trip_and_mix_with_v2_in_one_buffer():
    val = np.arange(6, dtype='f4').reshape(2, 3)
    buf = (wire.pack_msg(wire.REPLY_OK, {'seq': 1})
           + wire.pack_msg(wire.REPLY_VAR, {'seq': 2, 'name': 'w'},
                           value=val,
                           version=wire.WIRE_VERSION_BMETA)
           + wire.pack_msg(wire.REPLY_OK, {'seq': 3}))
    msgs = list(wire.unpack_msgs(buf))
    assert [m[0] for m in msgs] == [wire.REPLY_OK, wire.REPLY_VAR,
                                    wire.REPLY_OK]
    assert [m[1]['seq'] for m in msgs] == [1, 2, 3]
    assert msgs[1][1]['name'] == 'w'
    assert np.array_equal(msgs[1][2], val)


def test_negotiated_upgrade_and_flag_off_default(bmeta_flag):
    flags.set_flags({'FLAGS_wire_binary_meta': False})
    a, b = socket.socketpair()
    try:
        # flag off: plain v2, no capability advert
        wire.write_msg(a, wire.REPLY_OK, {'seq': 0})
        assert _version_byte(b) == wire.WIRE_VERSION
        _t, meta, _v = wire.read_msg(b)
        assert 'bmeta' not in meta

        flags.set_flags({'FLAGS_wire_binary_meta': True})
        # first flag-on send: peer unproven -> still v2, adverts
        wire.write_msg(a, wire.REPLY_OK, {'seq': 1})
        assert _version_byte(b) == wire.WIRE_VERSION
        _t, meta, _v = wire.read_msg(b)
        assert meta['seq'] == 1 and meta.get('bmeta') == 1
        # b saw the advert: its reply upgrades to v3
        wire.write_msg(b, wire.REPLY_OK, {'seq': 2})
        assert _version_byte(a) == wire.WIRE_VERSION_BMETA
        _t, meta, _v = wire.read_msg(a)
        assert meta == {'seq': 2}
        # a saw a v3 frame: the connection is now v3 both ways
        wire.write_msg(a, wire.REPLY_OK, {'seq': 3})
        assert _version_byte(b) == wire.WIRE_VERSION_BMETA
        assert wire.read_msg(b)[1] == {'seq': 3}
    finally:
        a.close()
        b.close()


def test_old_peer_keeps_connection_on_json(bmeta_flag):
    flags.set_flags({'FLAGS_wire_binary_meta': True})
    a, b = socket.socketpair()
    try:
        wire.write_msg(a, wire.REPLY_OK, {'seq': 1})
        _t, meta, _v = wire.read_msg(b)
        assert meta.get('bmeta') == 1
        # an old peer ignores the advert and answers plain v2 (raw
        # pack_msg, the pre-v3 binary's only wire format)
        b.sendall(wire.pack_msg(wire.REPLY_OK, {'seq': 2}))
        _t, meta, _v = wire.read_msg(a)
        assert meta == {'seq': 2}
        # no proof the peer speaks v3 -> a stays on JSON + advert
        wire.write_msg(a, wire.REPLY_OK, {'seq': 3})
        assert _version_byte(b) == wire.WIRE_VERSION
        assert wire.read_msg(b)[1].get('bmeta') == 1
    finally:
        a.close()
        b.close()
