"""NHWC (channels-last) layout parity: the TPU-native layout must be
numerically identical to the reference NCHW contract.

Reference analog: conv_op.cc / batch_norm_op.cc accept a data_format /
data_layout attribute (cuDNN path uses it for tensor descriptors); here
NHWC additionally puts channels on the TPU lane dimension end to end.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.models import resnet


def _build(nhwc):
    main, startup = fluid.Program(), fluid.Program()
    with unique_name.guard():
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name='image', shape=[3, 32, 32],
                                    dtype='float32')
            lbl = fluid.layers.data(name='label', shape=[1], dtype='int64')
            _, cost, _ = resnet.train_network(img, lbl, class_dim=10,
                                              depth=18, nhwc=nhwc)
            fluid.optimizer.Momentum(0.001, 0.9).minimize(cost)
    return main, startup, cost


class TestNHWCParity:
    @pytest.mark.slow
    def test_resnet18_training_parity(self):
        """Same weights -> identical losses across 4 training steps in
        either layout (fwd, backward, and optimizer all agree)."""
        rng = np.random.RandomState(0)
        xb = rng.rand(8, 3, 32, 32).astype('f4')
        yb = rng.randint(0, 10, (8, 1)).astype('int64')
        snap = {}

        def run(nhwc, seed_params):
            main, startup, cost = _build(nhwc)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                names = [v.name for v in main.list_vars() if v.persistable]
                for n in names:
                    if seed_params:
                        snap[n] = np.array(np.asarray(scope.find_var(n)))
                    elif n in snap:
                        scope.set_var(n, snap[n])
                out = []
                for _ in range(4):
                    l, = exe.run(main, feed={'image': xb, 'label': yb},
                                 fetch_list=[cost])
                    out.append(float(l))
            return out

        a = run(False, True)
        b = run(True, False)
        np.testing.assert_allclose(a, b, rtol=3e-3, atol=1e-3)

    @pytest.mark.parametrize('case', ['conv', 'conv_bias', 'pool_max',
                                      'pool_avg_global', 'depthwise'])
    def test_op_level_parity(self, case):
        rng = np.random.RandomState(1)
        x_np = rng.rand(2, 6, 9, 9).astype('f4')

        def net(fmt):
            main, startup = fluid.Program(), fluid.Program()
            with unique_name.guard():
                with fluid.program_guard(main, startup):
                    x = fluid.layers.data(name='x', shape=[6, 9, 9],
                                          dtype='float32')
                    x.stop_gradient = False
                    if fmt == 'NHWC':
                        x = fluid.layers.transpose(x, perm=[0, 2, 3, 1])
                    if case == 'conv':
                        y = fluid.layers.conv2d(
                            x, 8, 3, padding=1, stride=2, bias_attr=False,
                            data_format=fmt)
                    elif case == 'conv_bias':
                        y = fluid.layers.conv2d(
                            x, 8, 3, padding=1, data_format=fmt)
                    elif case == 'depthwise':
                        y = fluid.layers.conv2d(
                            x, 6, 3, padding=1, groups=6, bias_attr=False,
                            data_format=fmt)
                    elif case == 'pool_max':
                        y = fluid.layers.pool2d(
                            x, pool_size=3, pool_type='max', pool_stride=2,
                            pool_padding=1, data_format=fmt)
                    else:
                        y = fluid.layers.pool2d(
                            x, pool_type='avg', global_pooling=True,
                            data_format=fmt)
                    if fmt == 'NHWC':
                        y = fluid.layers.transpose(y, perm=[0, 3, 1, 2])
                    loss = fluid.layers.reduce_mean(y)
                    fluid.backward.append_backward(loss)
                    g = fluid.framework.grad_var_name('x')
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                names = [v.name for v in main.list_vars() if v.persistable]
                for n in names:
                    arr = np.array(np.asarray(scope.find_var(n)))
                    seeded = getattr(net, '_snap', {})
                    if n in seeded:
                        scope.set_var(n, seeded[n])
                    else:
                        seeded[n] = arr
                        net._snap = seeded
                y_v, g_v = exe.run(main, feed={'x': x_np},
                                   fetch_list=[y, g])
            return np.asarray(y_v), np.asarray(g_v)

        net._snap = {}
        y_a, g_a = net('NCHW')
        y_b, g_b = net('NHWC')
        np.testing.assert_allclose(y_a, y_b, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(g_a, g_b, rtol=1e-5, atol=1e-5)
