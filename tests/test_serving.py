"""Serving subsystem: KV-cached decode parity + continuous batching.

The contract under test (ISSUE 6 acceptance):
- greedy decode over the ring caches is BIT-EXACT against the
  full-recompute predictor (same weights, same ops, same reduction
  lengths — np.array_equal, not allclose)
- each of the two serving programs compiles exactly once across a
  whole generation loop (executor jit_cache_stats)
- a request admitted mid-stream into a running pool produces exactly
  the tokens it would have produced alone (lane isolation)
- clone()d workers share weights but never cross-talk
plus unit tests for the ring/mask ops and the Predictor dict-input
validation satellite.
"""
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import unique_name
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.models.transformer import (TransformerConfig,
                                           language_model_logits)
from op_test import OpTest

CFG = TransformerConfig(vocab=64, dim=32, heads=2, layers=2, ffn=64,
                        max_len=16, use_tp=False, use_sp=False)


# --------------------------------------------------------------------------
# ring / mask / gather op units (ops/attention_ops.py)
# --------------------------------------------------------------------------

class TestKVCacheWrite(OpTest):
    def test_whole_row_overwrite(self):
        rng = np.random.RandomState(0)
        cache = rng.rand(4, 6, 2, 3).astype('f4')     # stale contents
        x = rng.rand(2, 6, 2, 3).astype('f4')
        slots = np.array([3, 1], 'int32')
        expect = cache.copy()
        expect[3], expect[1] = x[0], x[1]
        self.op_type = 'kv_cache_write'
        self.inputs = {'Cache': cache, 'X': x, 'Slots': slots}
        self.outputs = {'Out': expect}
        self.check_output()


class TestKVCacheAppend(OpTest):
    def test_ring_wrap(self):
        rng = np.random.RandomState(1)
        cache = rng.rand(3, 4, 2, 2).astype('f4')
        x = rng.rand(3, 1, 2, 2).astype('f4')
        step = np.array([0, 5, 3], 'int32')           # 5 % 4 wraps to 1
        expect = cache.copy()
        expect[0, 0], expect[1, 1], expect[2, 3] = x[0, 0], x[1, 0], x[2, 0]
        self.op_type = 'kv_cache_append'
        self.inputs = {'Cache': cache, 'X': x, 'StepIdx': step}
        self.outputs = {'Out': expect}
        self.check_output()


class TestDecodeMask(OpTest):
    def test_pre_and_post_wrap_validity(self):
        T = 4
        x = np.zeros((2, 2, 1, T), 'f4')
        step = np.array([2, 5], 'int32')
        expect = np.full_like(x, -1e9)
        # s=2 (< T): ring positions 0..2 hold real history
        expect[0, :, :, :3] = 0.0
        # s=5 (wrapped): every ring position holds one of the last T
        # tokens — all valid
        expect[1] = 0.0
        self.op_type = 'decode_mask'
        self.inputs = {'X': x, 'StepIdx': step}
        self.outputs = {'Out': expect}
        self.check_output()


class TestPositionEmbeddingAt(OpTest):
    def test_gather_and_wrap(self):
        pos = np.arange(20, dtype='f4').reshape(5, 4)
        idx = np.array([0, 3, 7], 'int32')            # 7 % 5 wraps to 2
        self.op_type = 'position_embedding_at'
        self.inputs = {'Pos': pos, 'Index': idx}
        self.outputs = {'Out': pos[[0, 3, 2]][:, None, :]}
        self.check_output()


class TestGatherTime(OpTest):
    def test_per_row_time_gather(self):
        rng = np.random.RandomState(2)
        x = rng.rand(3, 5, 4).astype('f4')
        idx = np.array([0, 4, 2], 'int32')
        self.op_type = 'gather_time'
        self.inputs = {'X': x, 'Index': idx}
        self.outputs = {'Out': x[[0, 1, 2], [0, 4, 2]]}
        self.check_output()


# --------------------------------------------------------------------------
# shared tiny-LM predictor
# --------------------------------------------------------------------------

@pytest.fixture(scope='module')
def lm_predictor(tmp_path_factory):
    tmp = tmp_path_factory.mktemp('serving_lm')
    prog, startup = Program(), Program()
    prog.random_seed = startup.random_seed = 7
    with unique_name.guard(), program_guard(prog, startup):
        toks = fluid.layers.data(name='tokens',
                                 shape=[1, CFG.max_len, 1],
                                 dtype='int64', append_batch_size=False)
        logits = language_model_logits(toks, CFG)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp), ['tokens'], [logits],
                                      exe, main_program=prog)
    from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
    return AnalysisPredictor(AnalysisConfig(str(tmp),
                                            place=fluid.CPUPlace()))


def _ref_step(pred, toks):
    """Full-recompute next-token logits for a token list (len <= T)."""
    feed = np.zeros((1, CFG.max_len, 1), np.int64)
    feed[0, :len(toks), 0] = toks
    lg = pred.run({'tokens': feed})[0]
    return lg[0, len(toks) - 1]


def _ref_generate(pred, prompt, n):
    toks, out = list(prompt), []
    for _ in range(n):
        t = int(np.argmax(_ref_step(pred, toks)))
        out.append(t)
        toks.append(t)
    return out


# --------------------------------------------------------------------------
# transpiler
# --------------------------------------------------------------------------

def test_extract_decode_spec(lm_predictor):
    from paddle_tpu.transpiler import extract_decode_spec
    spec = extract_decode_spec(lm_predictor._program)
    assert (spec.vocab, spec.dim, spec.heads, spec.layers, spec.ffn,
            spec.max_len) == (CFG.vocab, CFG.dim, CFG.heads, CFG.layers,
                              CFG.ffn, CFG.max_len)
    assert len(spec.blocks) == CFG.layers
    assert spec.cache_shape(4) == (4, CFG.max_len, CFG.heads,
                                   CFG.dim // CFG.heads)


def test_transpile_rejects_non_lm():
    from paddle_tpu.transpiler import (DecodeTranspiler,
                                       DecodeTranspileError)
    prog, startup = Program(), Program()
    with unique_name.guard(), program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[8], dtype='float32')
        fluid.layers.fc(input=x, size=4)
    with pytest.raises(DecodeTranspileError, match='cannot transpile'):
        DecodeTranspiler().transpile(prog)


# --------------------------------------------------------------------------
# cached decode: bit-exact parity + compile-once
# --------------------------------------------------------------------------

def test_greedy_parity_bit_exact_and_compiles_once(lm_predictor):
    dec = lm_predictor.prepare_decoding(slots=3, prefill_batch=1)
    prompt = [3, 1, 4, 1, 5]
    ids, logits = dec.prefill([prompt], [1], return_logits=True)
    ref = _ref_step(lm_predictor, prompt)
    assert np.array_equal(logits[0], ref), \
        'prefill logits diverge from full recompute'
    tok, pos = int(ids[0]), len(prompt)
    toks = np.zeros((3,), np.int64)
    poss = np.zeros((3,), np.int32)
    stream = [tok]
    for _ in range(CFG.max_len - len(prompt)):
        toks[1], poss[1] = tok, pos
        nxt, lg = dec.decode_step(toks, poss, return_logits=True)
        ref = _ref_step(lm_predictor, prompt + stream)
        assert np.array_equal(lg[1], ref), \
            'decode step %d logits diverge (pos %d)' % (len(stream), pos)
        tok = int(nxt[1])
        stream.append(tok)
        pos += 1
    assert stream == _ref_generate(lm_predictor, prompt,
                                   CFG.max_len - len(prompt) + 1)
    # the whole loop compiled exactly two programs: prefill + decode;
    # every further dispatch was a jit-cache hit
    stats = dec.jit_cache_stats()
    assert stats['prepared_programs'] == 2
    assert stats['compiled_segments'] == 2
    assert stats['segment_misses'] == 2
    assert stats['segment_hits'] >= 1


def test_generate_past_max_len_slides_window(lm_predictor):
    # beyond T the ring overwrites the oldest row — a sliding-window
    # divergence from full recompute (documented in README); it must
    # keep producing in-vocab tokens without error
    dec = lm_predictor.prepare_decoding(slots=1, prefill_batch=1)
    out = dec.generate([5, 9, 2], CFG.max_len + 6)
    assert len(out) == CFG.max_len + 6
    assert all(0 <= t < CFG.vocab for t in out)


def test_prefill_validation(lm_predictor):
    dec = lm_predictor.prepare_decoding(slots=2, prefill_batch=1)
    with pytest.raises(ValueError, match='max_len'):
        dec.prefill([list(range(CFG.max_len + 1))], [0])
    with pytest.raises(ValueError, match='slot'):
        dec.prefill([[1, 2]], [2])
    with pytest.raises(ValueError, match='prompts'):
        dec.prefill([[1], [2]], [0, 1])   # prefill_batch is 1


# --------------------------------------------------------------------------
# continuous batching
# --------------------------------------------------------------------------

def test_midstream_admission_matches_solo(lm_predictor):
    """A request admitted while another stream is mid-decode produces
    exactly its solo token stream — first at the predictor level
    (deterministic interleaving), then through the engine."""
    solo_a = _ref_generate(lm_predictor, [3, 1, 4], 8)
    solo_b = _ref_generate(lm_predictor, [2, 7], 6)

    dec = lm_predictor.prepare_decoding(slots=2, prefill_batch=1)
    ids = dec.prefill([[3, 1, 4]], [0])
    a, pos_a = [int(ids[0])], 3
    toks = np.zeros((2,), np.int64)
    poss = np.zeros((2,), np.int32)
    b, pos_b = [], None
    for step in range(10):
        if step == 3:                      # admit B mid-stream
            ids = dec.prefill([[2, 7]], [1])
            b, pos_b = [int(ids[0])], 2
        toks[0], poss[0] = a[-1], pos_a
        if b:
            toks[1], poss[1] = b[-1], pos_b
        nxt = dec.decode_step(toks, poss)
        if len(a) < 8:
            a.append(int(nxt[0]))
            pos_a += 1
        if b and len(b) < 6:
            b.append(int(nxt[1]))
            pos_b += 1
    assert a == solo_a, 'running stream disturbed by admission'
    assert b == solo_b, 'admitted stream differs from its solo run'


def test_engine_concurrent_requests_match_solo(lm_predictor):
    from paddle_tpu.serving import ServingEngine
    prompts = [[3, 1, 4], [2, 7], [9, 9, 1, 5], [6]]
    budgets = [8, 6, 5, 7]
    solo = [_ref_generate(lm_predictor, p, n)
            for p, n in zip(prompts, budgets)]
    dec = lm_predictor.prepare_decoding(slots=2, prefill_batch=1)
    with ServingEngine(dec) as eng:       # 4 requests over 2 slots
        reqs = [eng.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, budgets)]
        outs = [r.result(120) for r in reqs]
    assert outs == solo
    assert all(r.state == 'DONE' for r in reqs)


def test_engine_cancel_and_queue_drain(lm_predictor):
    from paddle_tpu.serving import ServingEngine
    dec = lm_predictor.prepare_decoding(slots=1, prefill_batch=1)
    eng = ServingEngine(dec)              # not started: both stay queued
    keep = eng.submit([3, 1, 4], max_new_tokens=4)
    drop = eng.submit([2, 7], max_new_tokens=4)
    eng.cancel(drop)
    eng.start()
    assert keep.result(120) == _ref_generate(lm_predictor, [3, 1, 4], 4)
    assert drop.wait(120) and drop.state == 'CANCELLED'
    assert drop.result(1) == []           # partial stream, no raise
    eng.stop()


def test_clone_workers_no_crosstalk(lm_predictor):
    """Two clone()d decode workers generating different prompts in
    parallel threads agree with their solo streams, and share the
    weight scope (one HBM copy) while owning private cache scopes."""
    prompts = [[3, 1, 4, 1], [11, 2]]
    solo = [_ref_generate(lm_predictor, p, 7) for p in prompts]
    base = lm_predictor.prepare_decoding(slots=2, prefill_batch=1)
    workers = [base, base.clone()]
    assert workers[1]._weight_scope is base._weight_scope
    assert workers[1]._scope is not base._scope

    results, errors = [None, None], []
    gate = threading.Barrier(2)

    def run(i):
        try:
            gate.wait(timeout=30)
            results[i] = workers[i].generate(prompts[i], 7)
        except Exception as e:            # surface, don't hang
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), 'decode worker thread hung'
    assert not errors, errors
    assert results == solo


# --------------------------------------------------------------------------
# LMServer + telemetry + Predictor.run validation satellite
# --------------------------------------------------------------------------

def test_lmserver_api_surface(lm_predictor):
    from paddle_tpu.serving import LMServer
    solo = _ref_generate(lm_predictor, [3, 1, 4], 5)
    dec = lm_predictor.prepare_decoding(slots=2, prefill_batch=1)
    with LMServer(dec) as srv:
        assert srv.generate([3, 1, 4], max_new_tokens=5) == solo
        h = srv.submit([3, 1, 4], max_new_tokens=5)
        assert srv.result(h, timeout=120) == solo
        snap = srv.poll(h)
        assert snap['state'] == 'DONE' and snap['tokens'] == solo
        stats = srv.stats()
        assert stats['slots_per_worker'] == 2
        assert stats['jit']['compiled_segments'] == 2
        with pytest.raises(KeyError):
            srv.poll('nope')
        with pytest.raises(ValueError, match='max_len'):
            srv.submit(list(range(CFG.max_len + 1)))


def test_serving_metrics_flow_into_rollup(lm_predictor):
    from paddle_tpu.obs import telemetry
    from paddle_tpu.obs.report import rollup
    from paddle_tpu.serving import ServingEngine
    dec = lm_predictor.prepare_decoding(slots=2, prefill_batch=1)
    telemetry.enable()
    try:
        telemetry.reset()
        with ServingEngine(dec) as eng:
            eng.generate([3, 1, 4], max_new_tokens=4)
        snap = telemetry.snapshot()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert snap['counters']['serving.requests.submitted'] == 1
    assert snap['counters']['serving.requests.completed'] == 1
    assert snap['counters']['serving.tokens_generated'] == 4
    assert snap['counters']['serving.decode_steps'] >= 3
    assert snap['hists']['serving.ttft']['count'] == 1
    assert snap['hists']['serving.token_latency']['count'] >= 3
    # the name-agnostic obs rollup picks the series up unchanged
    snap['role'] = 'server'
    ru = rollup([snap])
    assert ru['totals']['serving.requests.completed'] == 1
    assert 'serving.ttft' in ru['roles']['server']['hists']


@pytest.mark.slow
def test_serve_bench_quick_smoke():
    """tools/serve_bench.py --quick runs end to end and emits the
    acceptance summary row (the leg tools/bench_suite.py shells out
    to for the transformer local-mode decode_speedup stamp)."""
    import json
    import os
    import subprocess
    import sys
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'tools', 'serve_bench.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    out = subprocess.run([sys.executable, tool, '--quick'],
                         capture_output=True, text=True, timeout=600,
                         env=env)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith('{')]
    summary = [r for r in rows if r.get('summary') == 'acceptance']
    assert summary, rows
    assert summary[0]['infer_decode_cached_tokens_per_sec'] > 0
    assert {'recompute', 'cached', 'engine'} <= \
        {r.get('mode') for r in rows}


def test_predictor_run_dict_validation(lm_predictor):
    good = np.zeros((1, CFG.max_len, 1), np.int64)
    with pytest.raises(ValueError) as ei:
        lm_predictor.run({'bogus': good})
    msg = str(ei.value)
    assert 'bogus' in msg and 'tokens' in msg
    assert 'get_input_names' in msg
    with pytest.raises(ValueError, match='missing input'):
        lm_predictor.run({})
    with pytest.raises(ValueError, match='unknown input'):
        lm_predictor.run({'tokens': good, 'extra': good})
