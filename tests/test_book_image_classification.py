"""Book chapter 3: image_classification (reference tests/book/
test_image_classification.py) -- ResNet and VGG on cifar-shaped data,
trained UNTIL the loss crosses the chapter threshold (bounded steps,
the reference book contract: test_fit_a_line.py:40-55 trains to a
target, not to 'smaller than before'), then save/load inference model.
The ResNet chapter feeds through py_reader + double_buffer — the
reference book's reader stack — not direct feeds."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.models import resnet, vgg

LOSS_THRESHOLD = 0.1


def _train(net_fn, max_steps, lr, use_py_reader=False):
    prog, startup = Program(), Program()
    prog.random_seed = startup.random_seed = 42
    with program_guard(prog, startup):
        if use_py_reader:
            rdr = fluid.layers.py_reader(
                capacity=4, shapes=[(-1, 3, 32, 32), (-1, 1)],
                dtypes=['float32', 'int64'], name='book_img_reader',
                use_double_buffer=True)
            images, label = fluid.layers.read_file(rdr)
        else:
            rdr = None
            images = fluid.layers.data(name='pixel', shape=[3, 32, 32],
                                       dtype='float32')
            label = fluid.layers.data(name='label', shape=[1],
                                      dtype='int64')
        predict = net_fn(images)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=predict, label=label)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    # one fixed batch: the book trains to a loss threshold; we overfit
    xb = rng.rand(8, 3, 32, 32).astype('float32')
    yb = rng.randint(0, 10, (8, 1)).astype('int64')
    if rdr is not None:
        rdr.decorate_tensor_provider(lambda: iter(lambda: [xb, yb],
                                                  None))
        rdr.start()
    last = None
    for step in range(max_steps):
        feed = None if rdr is not None else {'pixel': xb, 'label': yb}
        l, a = exe.run(prog, feed=feed, fetch_list=[avg_cost, acc])
        last = float(np.asarray(l))
        if last < LOSS_THRESHOLD:
            break
    if rdr is not None:
        rdr.reset()
    assert np.isfinite(last)
    assert last < LOSS_THRESHOLD, (
        'loss %.4f never crossed the chapter threshold %.2f in %d steps'
        % (last, LOSS_THRESHOLD, max_steps))
    return prog, predict, exe


def test_resnet_cifar10_trains_to_threshold(tmp_path):
    prog, predict, exe = _train(
        lambda img: resnet.resnet_cifar10(img, class_dim=10, depth=8),
        max_steps=60, lr=0.01, use_py_reader=True)
    # the image var comes from the reader; feed it by its real name
    image_name = [op for op in prog.global_block().ops
                  if op.type == 'read'][0].output('Out')[0]
    fluid.io.save_inference_model(str(tmp_path), [image_name],
                                  [predict], exe, main_program=prog)
    infer_prog, feed_names, fetch_vars = \
        fluid.io.load_inference_model(str(tmp_path), exe)
    out, = exe.run(infer_prog,
                   feed={feed_names[0]:
                         np.zeros((2, 3, 32, 32), 'float32')},
                   fetch_list=fetch_vars)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


@pytest.mark.slow
def test_vgg_trains_to_threshold():
    def small_vgg(img):
        return vgg.vgg16(img, class_dim=10)
    _train(small_vgg, max_steps=90, lr=0.001)
