"""Book chapter 3: image_classification (reference tests/book/
test_image_classification.py) -- ResNet and VGG on cifar-shaped data,
train until the loss drops, then save/load inference model."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.models import resnet, vgg


def _train(net_fn, steps=25, lr=0.01):
    prog, startup = Program(), Program()
    # seeded: with random init the 12-step loss-drops assert is flaky
    prog.random_seed = startup.random_seed = 42
    with program_guard(prog, startup):
        images = fluid.layers.data(name='pixel', shape=[3, 32, 32],
                                   dtype='float32')
        label = fluid.layers.data(name='label', shape=[1], dtype='int64')
        predict = net_fn(images)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=predict, label=label)
        fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    # one fixed batch: the book trains to a loss threshold; we overfit
    xb = rng.rand(8, 3, 32, 32).astype('float32')
    yb = rng.randint(0, 10, (8, 1)).astype('int64')
    first = last = None
    for _ in range(steps):
        l, a = exe.run(prog, feed={'pixel': xb, 'label': yb},
                       fetch_list=[avg_cost, acc])
        if first is None:
            first = float(l)
        last = float(l)
    assert np.isfinite(last)
    assert last < first, (first, last)
    return prog, predict, exe


def test_resnet_cifar10_trains(tmp_path):
    prog, predict, exe = _train(
        lambda img: resnet.resnet_cifar10(img, class_dim=10, depth=8))
    fluid.io.save_inference_model(str(tmp_path), ['pixel'], [predict], exe,
                                  main_program=prog)
    infer_prog, feed_names, fetch_vars = \
        fluid.io.load_inference_model(str(tmp_path), exe)
    out, = exe.run(infer_prog,
                   feed={feed_names[0]:
                         np.zeros((2, 3, 32, 32), 'float32')},
                   fetch_list=fetch_vars)
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)


def test_vgg_trains():
    def small_vgg(img):
        return vgg.vgg16(img, class_dim=10)
    _train(small_vgg, steps=12, lr=0.003)
