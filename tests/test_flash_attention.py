"""Flash attention kernel (paddle_tpu/pallas/flash_attention.py):
numerics vs the naive contraction in interpreter mode (CPU CI), plus
the op/layer path through the executor."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.pallas.flash_attention import _flash, _naive


INTERPRET = jax.default_backend() != 'tpu'


@pytest.mark.parametrize('causal', [False, True])
def test_kernel_matches_naive(causal):
    rng = np.random.RandomState(0)
    BH, T, d = 3, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    o_k = _flash(q, k, v, causal, scale, INTERPRET)
    o_n = _naive(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_n),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize('causal', [False, True])
def test_kernel_grads_match_naive(causal):
    rng = np.random.RandomState(1)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5

    def loss_k(q, k, v):
        return jnp.sum(_flash(q, k, v, causal, scale, INTERPRET) ** 2)

    def loss_n(q, k, v):
        return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)


@pytest.mark.parametrize('causal', [False, True])
def test_kernel_grads_match_naive_asymmetric_blocks(causal):
    """The tuned-table shape: bk > bq (the round-5 autotune winner at
    T=8192 is (512, 1024)). Exercised at a CI-size T with the same
    bq < bk asymmetry and a q-block that spans multiple k-blocks."""
    from paddle_tpu import flags
    rng = np.random.RandomState(2)
    BH, T, d = 2, 512, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    flags.set_flags({'FLAGS_flash_block_q': 128,
                     'FLAGS_flash_block_k': 256})
    try:
        from paddle_tpu.pallas import flash_attention as fa
        fa._fwd.clear_cache()
        fa._bwd.clear_cache()

        def loss_k(q, k, v):
            return jnp.sum(_flash(q, k, v, causal, scale, INTERPRET) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        o_k = _flash(q, k, v, causal, scale, INTERPRET)
        np.testing.assert_allclose(
            np.asarray(o_k), np.asarray(_naive(q, k, v, causal, scale)),
            rtol=2e-2, atol=2e-2)
        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip('qkv', gk, gn):
            scale_ref = float(jnp.abs(b).max()) + 1e-9
            rel = float(jnp.abs(a - b).max()) / scale_ref
            assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)
    finally:
        flags.set_flags({'FLAGS_flash_block_q': 0,
                         'FLAGS_flash_block_k': 0})
        from paddle_tpu.pallas import flash_attention as fa
        fa._fwd.clear_cache()
        fa._bwd.clear_cache()


def test_flash_attention_op_through_executor():
    fluid.set_flags({'pallas_interpret': True})
    try:
        rng = np.random.RandomState(2)
        B, H, T, d = 2, 2, 256, 128
        qv = rng.randn(B, H, T, d).astype('float32') * 0.3
        kv = rng.randn(B, H, T, d).astype('float32') * 0.3
        vv = rng.randn(B, H, T, d).astype('float32')

        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            q = fluid.layers.data(name='q', shape=[H, T, d],
                                  dtype='float32')
            k = fluid.layers.data(name='k', shape=[H, T, d],
                                  dtype='float32')
            v = fluid.layers.data(name='v', shape=[H, T, d],
                                  dtype='float32')
            out = fluid.layers.flash_attention(q, k, v, causal=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(prog, feed={'q': qv, 'k': kv, 'v': vv},
                       fetch_list=[out])
        want = _naive(jnp.asarray(qv.reshape(B * H, T, d)),
                      jnp.asarray(kv.reshape(B * H, T, d)),
                      jnp.asarray(vv.reshape(B * H, T, d)),
                      True, d ** -0.5).reshape(B, H, T, d)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)
    finally:
        fluid.set_flags({'pallas_interpret': False})


def test_unsupported_shape_falls_back():
    # T=100 not lane-aligned: wrapper must fall back to naive, same
    # numbers, no error
    from paddle_tpu.pallas.flash_attention import flash_attention
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 100, 64).astype('float32'))
    k = jnp.asarray(rng.randn(2, 100, 64).astype('float32'))
    v = jnp.asarray(rng.randn(2, 100, 64).astype('float32'))
    out = flash_attention(q, k, v, causal=True)
    want = _naive(q, k, v, True, 64 ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_transformer_model_flash_config_trains():
    from paddle_tpu.models.transformer import TransformerConfig, \
        train_network
    fluid.set_flags({'pallas_interpret': True})
    try:
        cfg = TransformerConfig(vocab=64, dim=128, heads=1, layers=1,
                                ffn=128, max_len=128, use_tp=False,
                                use_sp=False, flash_attention=True)
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            tokens = fluid.layers.data(name='tokens', shape=[128, 1],
                                       dtype='int64')
            labels = fluid.layers.data(name='labels', shape=[128, 1],
                                       dtype='int64')
            _probs, loss = train_network(tokens, labels, cfg)
            fluid.optimizer.Adam(1e-3).minimize(loss)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (2, 128, 1)).astype('int64')
        labs = rng.randint(0, 64, (2, 128, 1)).astype('int64')
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            first = None
            for i in range(12):
                l, = exe.run(prog, feed={'tokens': ids, 'labels': labs},
                             fetch_list=[loss])
                if first is None:
                    first = float(np.asarray(l))
            assert float(np.asarray(l)) < first
    finally:
        fluid.set_flags({'pallas_interpret': False})


@pytest.mark.parametrize('arm,T,bq,bk',
                         [('split', 896, 128, 128),
                          ('split', 897, 128, 128),
                          ('onepass', 640, 128, 128),
                          ('onepass', 641, 128, 128),
                          ('kvmajor', 768, 128, 128),
                          ('kvmajor', 769, 128, 128),
                          # the tuned-table shape class (bk > bq, cf.
                          # _BLOCK_TABLE's (512, 1024)): pins kvmajor's
                          # causal qmap clamp + first_qi arithmetic
                          ('kvmajor', 1024, 128, 256)])
@pytest.mark.parametrize('causal', [False, True])
def test_alt_backward_arms_grads_match_naive(causal, arm, T, bq, bk):
    """The kv-major backward is the measured-default arm (covered by
    every other grad test); split and one-pass stay available via
    PADDLE_FLASH_BWD (split is also the automatic fallback when the
    kv-major dq accumulator would not fit) — force each via the
    _FORCE_ARM hook so all arms keep grad parity coverage. A UNIQUE T
    per arm is used because _bwd's jit cache keys on shapes+static
    args, not on the hook/flag state at trace time (the odd-T cases
    fall back to the naive path end to end, pinning that the hook does
    not break unsupported shapes)."""
    import paddle_tpu as fluid
    from paddle_tpu.pallas import flash_attention as fa
    from paddle_tpu.pallas.flash_attention import flash_attention
    rng = np.random.RandomState(2)
    BH, d = 2, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    fluid.set_flags({'flash_block_q': bq, 'flash_block_k': bk,
                     'pallas_interpret': INTERPRET})
    fa._FORCE_ARM = arm
    try:
        def loss_k(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, scale) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa._FORCE_ARM = ''
        fluid.set_flags({'flash_block_q': 0, 'flash_block_k': 0,
                         'pallas_interpret': False})
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)


@pytest.mark.parametrize('causal', [False, True])
def test_per_direction_block_tables_independent(causal):
    """The fwd and bwd kernels share only (o, lse), which are
    block-size independent — so each direction keeps its own tuned
    table (_BLOCK_TABLE_FWD vs _BLOCK_TABLE; at T=8192 they differ in
    production). Pin the mixed-table contract at a CI size by forcing
    DIFFERENT fwd/bwd blocks through the tables (the flag override
    path binds both directions, so it cannot cover this)."""
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(3)
    BH, T, d = 2, 384, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    fa._BLOCK_TABLE_FWD[(T, d)] = (384, 192)
    fa._BLOCK_TABLE[(T, d)] = (128, 384)
    fa._fwd.clear_cache()
    fa._bwd.clear_cache()
    try:
        def loss_k(q, k, v):
            return jnp.sum(_flash(q, k, v, causal, scale,
                                  INTERPRET) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        o_k = _flash(q, k, v, causal, scale, INTERPRET)
        np.testing.assert_allclose(
            np.asarray(o_k), np.asarray(_naive(q, k, v, causal, scale)),
            rtol=2e-2, atol=2e-2)
        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    finally:
        del fa._BLOCK_TABLE_FWD[(T, d)]
        del fa._BLOCK_TABLE[(T, d)]
        fa._fwd.clear_cache()
        fa._bwd.clear_cache()
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)
