"""Flash attention kernel (paddle_tpu/pallas/flash_attention.py):
numerics vs the naive contraction in interpreter mode (CPU CI), plus
the op/layer path through the executor."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.pallas.flash_attention import _flash, _naive


INTERPRET = jax.default_backend() != 'tpu'


@pytest.mark.parametrize('causal', [False, True])
def test_kernel_matches_naive(causal):
    rng = np.random.RandomState(0)
    BH, T, d = 3, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    o_k = _flash(q, k, v, causal, scale, INTERPRET)
    o_n = _naive(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_n),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize('causal', [False, True])
def test_kernel_grads_match_naive(causal):
    rng = np.random.RandomState(1)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5

    def loss_k(q, k, v):
        return jnp.sum(_flash(q, k, v, causal, scale, INTERPRET) ** 2)

    def loss_n(q, k, v):
        return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)


@pytest.mark.parametrize('causal', [False, True])
def test_kernel_grads_match_naive_asymmetric_blocks(causal):
    """The tuned-table shape: bk > bq (the round-5 autotune winner at
    T=8192 is (512, 1024)). Exercised at a CI-size T with the same
    bq < bk asymmetry and a q-block that spans multiple k-blocks."""
    from paddle_tpu import flags
    rng = np.random.RandomState(2)
    BH, T, d = 2, 512, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    flags.set_flags({'FLAGS_flash_block_q': 128,
                     'FLAGS_flash_block_k': 256})
    try:
        from paddle_tpu.pallas import flash_attention as fa
        fa._fwd.clear_cache()
        fa._bwd.clear_cache()

        def loss_k(q, k, v):
            return jnp.sum(_flash(q, k, v, causal, scale, INTERPRET) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        o_k = _flash(q, k, v, causal, scale, INTERPRET)
        np.testing.assert_allclose(
            np.asarray(o_k), np.asarray(_naive(q, k, v, causal, scale)),
            rtol=2e-2, atol=2e-2)
        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip('qkv', gk, gn):
            scale_ref = float(jnp.abs(b).max()) + 1e-9
            rel = float(jnp.abs(a - b).max()) / scale_ref
            assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)
    finally:
        flags.set_flags({'FLAGS_flash_block_q': 0,
                         'FLAGS_flash_block_k': 0})
        from paddle_tpu.pallas import flash_attention as fa
        fa._fwd.clear_cache()
        fa._bwd.clear_cache()


def test_flash_attention_op_through_executor():
    fluid.set_flags({'pallas_interpret': True})
    try:
        rng = np.random.RandomState(2)
        B, H, T, d = 2, 2, 256, 128
        qv = rng.randn(B, H, T, d).astype('float32') * 0.3
        kv = rng.randn(B, H, T, d).astype('float32') * 0.3
        vv = rng.randn(B, H, T, d).astype('float32')

        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            q = fluid.layers.data(name='q', shape=[H, T, d],
                                  dtype='float32')
            k = fluid.layers.data(name='k', shape=[H, T, d],
                                  dtype='float32')
            v = fluid.layers.data(name='v', shape=[H, T, d],
                                  dtype='float32')
            out = fluid.layers.flash_attention(q, k, v, causal=True)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        got, = exe.run(prog, feed={'q': qv, 'k': kv, 'v': vv},
                       fetch_list=[out])
        want = _naive(jnp.asarray(qv.reshape(B * H, T, d)),
                      jnp.asarray(kv.reshape(B * H, T, d)),
                      jnp.asarray(vv.reshape(B * H, T, d)),
                      True, d ** -0.5).reshape(B, H, T, d)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)
    finally:
        fluid.set_flags({'pallas_interpret': False})


def test_unsupported_shape_falls_back():
    # T=100 not lane-aligned: wrapper must fall back to naive, same
    # numbers, no error
    from paddle_tpu.pallas.flash_attention import flash_attention
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 100, 64).astype('float32'))
    k = jnp.asarray(rng.randn(2, 100, 64).astype('float32'))
    v = jnp.asarray(rng.randn(2, 100, 64).astype('float32'))
    out = flash_attention(q, k, v, causal=True)
    want = _naive(q, k, v, True, 64 ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_transformer_model_flash_config_trains():
    from paddle_tpu.models.transformer import TransformerConfig, \
        train_network
    fluid.set_flags({'pallas_interpret': True})
    try:
        cfg = TransformerConfig(vocab=64, dim=128, heads=1, layers=1,
                                ffn=128, max_len=128, use_tp=False,
                                use_sp=False, flash_attention=True)
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            tokens = fluid.layers.data(name='tokens', shape=[128, 1],
                                       dtype='int64')
            labels = fluid.layers.data(name='labels', shape=[128, 1],
                                       dtype='int64')
            _probs, loss = train_network(tokens, labels, cfg)
            fluid.optimizer.Adam(1e-3).minimize(loss)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (2, 128, 1)).astype('int64')
        labs = rng.randint(0, 64, (2, 128, 1)).astype('int64')
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            first = None
            for i in range(12):
                l, = exe.run(prog, feed={'tokens': ids, 'labels': labs},
                             fetch_list=[loss])
                if first is None:
                    first = float(np.asarray(l))
            assert float(np.asarray(l)) < first
    finally:
        fluid.set_flags({'pallas_interpret': False})


@pytest.mark.parametrize('arm,T,bq,bk',
                         [('split', 896, 128, 128),
                          ('split', 897, 128, 128),
                          ('onepass', 640, 128, 128),
                          ('onepass', 641, 128, 128),
                          ('kvmajor', 768, 128, 128),
                          ('kvmajor', 769, 128, 128),
                          # the tuned-table shape class (bk > bq, cf.
                          # _BLOCK_TABLE's (512, 1024)): pins kvmajor's
                          # causal qmap clamp + first_qi arithmetic
                          ('kvmajor', 1024, 128, 256)])
@pytest.mark.parametrize('causal', [False, True])
def test_alt_backward_arms_grads_match_naive(causal, arm, T, bq, bk):
    """The kv-major backward is the measured-default arm (covered by
    every other grad test); split and one-pass stay available via
    PADDLE_FLASH_BWD (split is also the automatic fallback when the
    kv-major dq accumulator would not fit) — force each via the
    _FORCE_ARM hook so all arms keep grad parity coverage. A UNIQUE T
    per arm is used because _bwd's jit cache keys on shapes+static
    args, not on the hook/flag state at trace time (the odd-T cases
    fall back to the naive path end to end, pinning that the hook does
    not break unsupported shapes)."""
    import paddle_tpu as fluid
    from paddle_tpu.pallas import flash_attention as fa
    from paddle_tpu.pallas.flash_attention import flash_attention
    rng = np.random.RandomState(2)
    BH, d = 2, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    fluid.set_flags({'flash_block_q': bq, 'flash_block_k': bk,
                     'pallas_interpret': INTERPRET})
    fa._FORCE_ARM = arm
    try:
        def loss_k(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, scale) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa._FORCE_ARM = ''
        fluid.set_flags({'flash_block_q': 0, 'flash_block_k': 0,
                         'pallas_interpret': False})
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)


# --- forward arms (online vs stored-lse twopass) --------------------

def _force_fwd_arm(fa, arm):
    """Force a forward arm AND drop stale traces: the arm binds at
    trace time, and _fwd's jit cache keys on shapes+static args, not
    on the hook state."""
    fa._FORCE_FWD_ARM = arm
    fa._fwd.clear_cache()


@pytest.mark.parametrize('arm', ['online', 'twopass'])
@pytest.mark.parametrize('causal', [False, True])
def test_fwd_arms_output_and_lse_match_naive(causal, arm):
    """Both forward arms must honor the exact (o, lse) contract: o vs
    the naive contraction, lse vs a directly-computed logsumexp of the
    masked scores (the backward arms and ring attention's global-lse
    merge both consume lse, so output parity alone is not enough)."""
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(4)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    _force_fwd_arm(fa, arm)
    try:
        o, lse = fa._fwd(q, k, v, causal, scale, INTERPRET)
        assert fa._RESOLVED_FWD_ARM == arm
    finally:
        _force_fwd_arm(fa, '')
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(_naive(q, k, v, causal, scale)),
        rtol=2e-2, atol=2e-2)
    s = jnp.einsum('bqd,bkd->bqk', q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    want_lse = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse[..., 0]),
                               np.asarray(want_lse),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize('causal', [False, True])
def test_fwd_arms_agree_bitwise_on_lse(causal):
    """lse is a pure function of (q, k, mask); both arms compute it
    with the same running-max recurrence, so it must agree to fp32
    rounding — an lse drift here would silently skew every backward."""
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(5)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    out = {}
    try:
        for arm in ('online', 'twopass'):
            _force_fwd_arm(fa, arm)
            out[arm] = fa._fwd(q, k, v, causal, d ** -0.5, INTERPRET)
    finally:
        _force_fwd_arm(fa, '')
    np.testing.assert_allclose(np.asarray(out['online'][1]),
                               np.asarray(out['twopass'][1]),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out['online'][0]),
                               np.asarray(out['twopass'][0]),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize('bwd_arm', ['split', 'onepass', 'kvmajor'])
@pytest.mark.parametrize('fwd_arm', ['online', 'twopass'])
@pytest.mark.parametrize('causal', [False, True])
def test_fwd_bwd_arm_matrix_grads_match_naive(causal, fwd_arm,
                                              bwd_arm):
    """Full 2 fwd x 3 bwd arm matrix: every backward consumes (o, lse)
    from either forward unchanged. Blocks forced to (64, 128) so the
    bk > bq tuned-table shape class (kvmajor lesson) and causal
    diagonal-straddling q-blocks are both in play at CI size."""
    import paddle_tpu as fluid
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(6)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    fluid.set_flags({'flash_block_q': 64, 'flash_block_k': 128})
    fa._FORCE_ARM = bwd_arm
    _force_fwd_arm(fa, fwd_arm)
    fa._bwd.clear_cache()
    try:
        def loss_k(q, k, v):
            return jnp.sum(_flash(q, k, v, causal, scale,
                                  INTERPRET) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        assert fa._RESOLVED_FWD_ARM == fwd_arm
        assert fa._RESOLVED_ARM == bwd_arm
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa._FORCE_ARM = ''
        _force_fwd_arm(fa, '')
        fa._bwd.clear_cache()
        fluid.set_flags({'flash_block_q': 0, 'flash_block_k': 0})
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)


def test_twopass_vmem_guard_falls_back_to_online():
    """A forced twopass whose residency estimate exceeds the ceiling
    must silently dispatch online — introspectable via
    _RESOLVED_FWD_ARM (the A/B tools cross-check exactly this), with
    the numbers still correct."""
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(7)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    saved = fa._TWOPASS_VMEM_CEILING
    fa._TWOPASS_VMEM_CEILING = 1   # every estimate exceeds this
    _force_fwd_arm(fa, 'twopass')
    try:
        o, lse = fa._fwd(q, k, v, True, d ** -0.5, INTERPRET)
        assert fa._RESOLVED_FWD_ARM == 'online'
    finally:
        fa._TWOPASS_VMEM_CEILING = saved
        _force_fwd_arm(fa, '')
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(_naive(q, k, v, True, d ** -0.5)),
        rtol=2e-2, atol=2e-2)


def test_twopass_vmem_estimate_sane():
    """The residency estimate must include the 6 MB Mosaic stack
    margin (the round-5 OOM lesson) and grow with the block sizes."""
    from paddle_tpu.pallas import flash_attention as fa
    small = fa._twopass_vmem_bytes(8192, 128, 256, 256, 2)
    big = fa._twopass_vmem_bytes(8192, 128, 1024, 1024, 2)
    assert small > 6 * 1024 * 1024
    assert big > small
    assert big <= fa._TWOPASS_VMEM_CEILING   # tuned sizes stay legal


def test_unknown_fwd_arm_env_raises_at_import():
    """Loud-config hygiene: a typo'd PADDLE_FLASH_FWD must fail the
    import, not silently benchmark the default arm (mirrors
    PADDLE_FLASH_BWD). A valid value must bind the forcing hook."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PADDLE_FLASH_FWD='twopas')
    r = subprocess.run(
        [sys.executable, '-c',
         'import paddle_tpu.pallas.flash_attention'],
        capture_output=True, text=True, env=env)
    assert r.returncode != 0
    assert 'PADDLE_FLASH_FWD' in (r.stderr or '')
    env['PADDLE_FLASH_FWD'] = 'twopass'
    r = subprocess.run(
        [sys.executable, '-c',
         'from paddle_tpu.pallas import flash_attention as fa; '
         'assert fa._FORCE_FWD_ARM == "twopass"'],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize('causal', [False, True])
def test_twopass_extra_flops_noted_for_work_model(causal):
    """The twopass forward executes a second QK sweep that the
    2-matmul cost model (and XLA's cost analysis, blind inside the
    custom call) cannot see; the arm notes it at trace time and
    obs/perf drains it so live MFU divides by work that actually ran.
    Exact bookkeeping: 2*BH*visited_blocks*bq*bk*d, visited stopping
    at the diagonal under causal."""
    from paddle_tpu.obs import perf as obsperf
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(8)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    try:
        _force_fwd_arm(fa, 'online')
        fa.take_extra_flops()   # discard notes from earlier tests
        fa._fwd(q, k, v, causal, d ** -0.5, INTERPRET)
        assert fa.take_extra_flops() == 0.0   # online = the model
        _force_fwd_arm(fa, 'twopass')
        fa._fwd(q, k, v, causal, d ** -0.5, INTERPRET)
        bq, bk = fa._block_sizes(T, d, fwd=True, arm='twopass')
        nq, nk = T // bq, T // bk
        if causal:
            visited = sum(((i + 1) * bq - 1) // bk + 1
                          for i in range(nq))
        else:
            visited = nq * nk
        want = 2.0 * BH * visited * bq * bk * d
        # drained through the obs/perf hook the executor uses
        assert obsperf.pallas_extra_flops() == want
        assert obsperf.pallas_extra_flops() == 0.0   # destructive
        # a second call with the same shapes hits the jit cache: no
        # re-trace, no double-count
        fa._fwd(q, k, v, causal, d ** -0.5, INTERPRET)
        assert fa.take_extra_flops() == 0.0
    finally:
        _force_fwd_arm(fa, '')


@pytest.mark.parametrize('causal', [False, True])
def test_twopass_block_table_is_per_arm(causal):
    """The lane-parallel bk sweep tunes the twopass arm separately:
    an entry in _BLOCK_TABLE_FWD_TWOPASS must bind ONLY the twopass
    dispatch (online keeps _BLOCK_TABLE_FWD), and the twopass kernels
    must stay correct under the re-tabled (bk > bq) blocks."""
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(9)
    BH, T, d = 2, 256, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    fa._BLOCK_TABLE_FWD_TWOPASS[(T, d)] = (64, 256)
    try:
        assert fa._block_sizes(T, d, fwd=True, arm='twopass') \
            == (64, 256)
        assert fa._block_sizes(T, d, fwd=True, arm='online') \
            != (64, 256)
        _force_fwd_arm(fa, 'twopass')
        o, _lse = fa._fwd(q, k, v, causal, d ** -0.5, INTERPRET)
        assert fa._RESOLVED_FWD_ARM == 'twopass'
    finally:
        del fa._BLOCK_TABLE_FWD_TWOPASS[(T, d)]
        _force_fwd_arm(fa, '')
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(_naive(q, k, v, causal, d ** -0.5)),
        rtol=2e-2, atol=2e-2)


def test_flash_fwd_arms_quick_smoke():
    """tools/flash_fwd_arms.py --quick is the tier-1 wiring for the
    A/B harness: forcing, cache-clearing, resolved-arm cross-check and
    ranking all run end to end on the interpret backend."""
    import os
    import sys
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'tools')
    sys.path.insert(0, tools)
    try:
        import flash_fwd_arms
        flash_fwd_arms.main(['--quick'])
    finally:
        sys.path.remove(tools)


@pytest.mark.parametrize('causal', [False, True])
def test_per_direction_block_tables_independent(causal):
    """The fwd and bwd kernels share only (o, lse), which are
    block-size independent — so each direction keeps its own tuned
    table (_BLOCK_TABLE_FWD vs _BLOCK_TABLE; at T=8192 they differ in
    production). Pin the mixed-table contract at a CI size by forcing
    DIFFERENT fwd/bwd blocks through the tables (the flag override
    path binds both directions, so it cannot cover this)."""
    from paddle_tpu.pallas import flash_attention as fa
    rng = np.random.RandomState(3)
    BH, T, d = 2, 384, 128
    q = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    k = jnp.asarray(rng.randn(BH, T, d).astype('float32')) * 0.3
    v = jnp.asarray(rng.randn(BH, T, d).astype('float32'))
    scale = d ** -0.5
    fa._BLOCK_TABLE_FWD[(T, d)] = (384, 192)
    fa._BLOCK_TABLE[(T, d)] = (128, 384)
    fa._fwd.clear_cache()
    fa._bwd.clear_cache()
    try:
        def loss_k(q, k, v):
            return jnp.sum(_flash(q, k, v, causal, scale,
                                  INTERPRET) ** 2)

        def loss_n(q, k, v):
            return jnp.sum(_naive(q, k, v, causal, scale) ** 2)

        o_k = _flash(q, k, v, causal, scale, INTERPRET)
        np.testing.assert_allclose(
            np.asarray(o_k), np.asarray(_naive(q, k, v, causal, scale)),
            rtol=2e-2, atol=2e-2)
        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_n, argnums=(0, 1, 2))(q, k, v)
    finally:
        del fa._BLOCK_TABLE_FWD[(T, d)]
        del fa._BLOCK_TABLE[(T, d)]
        fa._fwd.clear_cache()
        fa._bwd.clear_cache()
    for name, a, b in zip('qkv', gk, gn):
        scale_ref = float(jnp.abs(b).max()) + 1e-9
        rel = float(jnp.abs(a - b).max()) / scale_ref
        assert rel < 5e-2, 'd%s rel err %.3e' % (name, rel)
