"""Round-3 module-parity fills: average, evaluator, inferencer,
inference_transpiler (BN folding), memory_optimization_transpiler,
memory_usage_calc, default_scope_funcs, concurrency, op factory,
net_drawer/graphviz (reference python/paddle/fluid/*.py misc table,
SURVEY §2.6)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard


def test_weighted_average():
    avg = fluid.average.WeightedAverage()
    avg.add(value=2.0, weight=1)
    avg.add(value=4.0, weight=2)
    assert abs(avg.eval() - 10.0 / 3) < 1e-9
    avg.reset()
    with pytest.raises(ValueError):
        avg.eval()


def test_accuracy_evaluator_accumulates():
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        lab = fluid.layers.data(name='lab', shape=[1], dtype='int64')
        acc_ev = fluid.evaluator.Accuracy(input=x, label=lab)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        # batch 1: 2/3 correct; batch 2: 1/3 correct -> 3/6 total
        b1 = np.eye(4)[[0, 1, 2]].astype('float32')
        exe.run(prog, feed={'x': b1,
                            'lab': np.array([[0], [1], [0]], 'int64')},
                fetch_list=acc_ev.metrics)
        exe.run(prog, feed={'x': b1,
                            'lab': np.array([[0], [2], [3]], 'int64')},
                fetch_list=acc_ev.metrics)
        total_acc = acc_ev.eval(exe)
        assert abs(float(total_acc) - 0.5) < 1e-6
        # reset zeroes the states
        acc_ev.reset(exe)
        exe.run(prog, feed={'x': b1,
                            'lab': np.array([[0], [1], [2]], 'int64')},
                fetch_list=acc_ev.metrics)
        assert abs(float(acc_ev.eval(exe)) - 1.0) < 1e-6


def test_chunk_evaluator_graph_state():
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        inf = fluid.layers.data(name='inf', shape=[1, 5], dtype='int64',
                                append_batch_size=False)
        lab = fluid.layers.data(name='lab', shape=[1, 5], dtype='int64',
                                append_batch_size=False)
        ev = fluid.evaluator.ChunkEvaluator(
            input=inf, label=lab, chunk_scheme='IOB', num_chunk_types=1)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        feed = {'inf': np.array([[0, 1, 2, 0, 2]], 'int64'),
                'lab': np.array([[0, 1, 2, 2, 2]], 'int64')}
        exe.run(prog, feed=feed, fetch_list=ev.metrics)
        exe.run(prog, feed=feed, fetch_list=ev.metrics)
        p, r, f1 = ev.eval(exe)
        assert abs(p - 0.5) < 1e-6 and abs(r - 1.0) < 1e-6


def test_inference_transpiler_folds_bn():
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[3, 8, 8], dtype='float32')
        c = fluid.layers.conv2d(x, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        bn = fluid.layers.batch_norm(c, is_test=True)
        out = fluid.layers.reduce_sum(bn)
    exe = fluid.Executor(fluid.CPUPlace())
    xb = np.random.rand(2, 3, 8, 8).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        # give BN non-trivial statistics
        import paddle_tpu.executor as pexec
        scope = pexec.global_scope()
        for v in prog.global_block().vars.values():
            if 'batch_norm' in v.name and v.persistable:
                arr = np.asarray(scope.find_var(v.name))
                scope.set_var(v.name,
                              (arr + np.random.rand(*arr.shape) * 0.5 + .5)
                              .astype('float32'))
        before, = exe.run(prog, feed={'x': xb}, fetch_list=[out])

        infer_prog = prog.clone(for_test=True)
        n_ops_before = len(infer_prog.global_block().ops)
        t = fluid.InferenceTranspiler()
        t.transpile(infer_prog, fluid.CPUPlace())
        types = [op.type for op in infer_prog.global_block().ops]
        assert 'batch_norm' not in types
        assert len(infer_prog.global_block().ops) <= n_ops_before
        after, = exe.run(infer_prog, feed={'x': xb}, fetch_list=[out])
        np.testing.assert_allclose(np.asarray(after),
                                   np.asarray(before), rtol=2e-4,
                                   atol=2e-4)


def test_memory_optimize_plan_and_usage():
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        h1 = fluid.layers.fc(input=x, size=16, act='relu')
        h2 = fluid.layers.fc(input=h1, size=16, act='relu')
        h3 = fluid.layers.fc(input=h2, size=16, act='relu')
        loss = fluid.layers.mean(h3)
    plan = fluid.memory_optimize(prog)
    assert isinstance(plan, dict)
    assert prog._memory_reuse_plan is plan
    # same-shape dead activations exist -> at least one reuse found
    assert len(plan) >= 1
    usage = fluid.contrib.memory_usage(prog, batch_size=32)
    assert usage > 0


def test_release_memory_inserts_delete_vars():
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        h = fluid.layers.fc(input=x, size=4)
        loss = fluid.layers.mean(h)
    n_before = len(prog.global_block().ops)
    fluid.release_memory(prog)
    types = [op.type for op in prog.global_block().ops]
    assert types.count('delete_var') >= 1
    assert len(prog.global_block().ops) > n_before


def test_inferencer_roundtrip(tmp_path):
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    model_dir = str(tmp_path / 'model')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        xb = np.random.rand(3, 4).astype('float32')
        want, = exe.run(prog, feed={'x': xb}, fetch_list=[y])
        fluid.io.save_inference_model(model_dir, ['x'], [y], exe,
                                      main_program=prog)
    inferencer = fluid.Inferencer(param_path=model_dir)
    got = inferencer.infer({'x': xb})
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want),
                               rtol=1e-5)


def test_default_scope_funcs():
    from paddle_tpu import default_scope_funcs as dsf
    dsf.var('a')
    dsf.get_cur_scope().set_var('a', 1)
    assert dsf.has_var('a') and dsf.find_var('a') == 1
    dsf.enter_local_scope()
    dsf.var('b')
    assert dsf.find_var('a') == 1            # parent lookup
    assert dsf.has_var('b')
    dsf.leave_local_scope()
    assert not dsf.has_var('b')

    ran = []
    dsf.scoped_function(lambda: ran.append(dsf.var('c')))
    assert len(ran) == 1


def test_concurrency_go_channels():
    from paddle_tpu import concurrency as conc
    ch = conc.make_channel(capacity=2)
    results = []

    def producer():
        for i in range(5):
            conc.channel_send(ch, i)
        conc.channel_close(ch)

    conc.go(producer)
    while True:
        v, ok = conc.channel_recv(ch)
        if not ok:
            break
        results.append(v)
    assert results == [0, 1, 2, 3, 4]


def test_op_factory():
    from paddle_tpu.op import Operator
    spec = Operator('scale', X='x', Out='out', scale=2.0)
    assert spec['type'] == 'scale'
    assert spec['inputs'] == {'X': ['x']}
    assert spec['outputs'] == {'Out': ['out']}
    assert spec['attrs'] == {'scale': 2.0}
    assert 'conv2d' in Operator.types()
    with pytest.raises(ValueError):
        Operator('not_a_real_op')


def test_net_drawer_and_graphviz(tmp_path):
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.fc(input=x, size=2)
    from paddle_tpu.net_drawer import draw_graph
    path = str(tmp_path / 'net.dot')
    out = draw_graph(startup, prog, path)
    src = open(out).read()
    assert 'digraph' in src and 'mul' in src


def test_detection_map_metric():
    m = fluid.metrics.DetectionMAP()
    m.update(np.array([0.5], 'float32'), weight=2)
    m.update(np.array([1.0], 'float32'), weight=2)
    assert abs(m.eval() - 0.75) < 1e-9
    m.reset()
    with pytest.raises(ValueError):
        m.eval()


def test_analysis_predictor_folds_bn(tmp_path):
    from paddle_tpu.inference import (AnalysisConfig, AnalysisPredictor,
                                      create_analysis_predictor)
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[3, 8, 8], dtype='float32')
        c = fluid.layers.conv2d(x, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        bn = fluid.layers.batch_norm(c, is_test=True)
        out = fluid.layers.reduce_sum(bn, dim=[1, 2, 3])
    exe = fluid.Executor(fluid.CPUPlace())
    model_dir = str(tmp_path / 'model')
    xb = np.random.rand(2, 3, 8, 8).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        want, = exe.run(prog, feed={'x': xb}, fetch_list=[out])
        fluid.io.save_inference_model(model_dir, ['x'], [out], exe,
                                      main_program=prog)

    pred = create_analysis_predictor(
        AnalysisConfig(model_dir, place=fluid.CPUPlace()))
    # the loaded+optimized program must not contain batch_norm anymore
    types = [op.type for op in pred._program.global_block().ops]
    assert 'batch_norm' not in types
    got = pred.run({'x': xb})[0]
    np.testing.assert_allclose(got, np.asarray(want), rtol=2e-4,
                               atol=2e-4)
    # clone serves the same fused program from shared weights
    got2 = pred.clone().run({'x': xb})[0]
    np.testing.assert_allclose(got2, got, rtol=1e-6)


def test_timeline_tool(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        'timeline', os.path.join(os.path.dirname(__file__), '..',
                                 'tools', 'timeline.py'))
    timeline = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(timeline)
    import json as _json
    raw = [{'name': 'mul', 'pid': 0, 'tid': 0, 'ts': 10, 'dur': 5},
           {'name': 'relu', 'pid': 0, 'tid': 0, 'ts': 16, 'dur': 2}]
    p_in = str(tmp_path / 'prof.json')
    p_out = str(tmp_path / 'tl.json')
    with open(p_in, 'w') as f:
        _json.dump(raw, f)
    timeline.convert(p_in, p_out)
    trace = _json.load(open(p_out))
    names = [e.get('name') for e in trace['traceEvents']]
    assert 'mul' in names and 'relu' in names
