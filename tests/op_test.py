"""OpTest: declarative per-op test harness with numeric gradient checking
(re-design of reference python/paddle/fluid/tests/unittests/op_test.py:131).

Subclasses set:
    self.op_type  - registered op type
    self.inputs   - {slot: np.ndarray | [(name, np.ndarray), ...]}
    self.outputs  - {slot: expected np.ndarray | [(name, expected), ...]}
    self.attrs    - op attrs (optional)

check_output() builds a one-op Program, runs it through the real Executor
(whole-block XLA compile, same path as training), and compares.
check_grad() compares the registered grad path against central-difference
numeric gradients (reference op_test.py:43 get_numeric_gradient).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard


def _as_pairs(slot, value):
    """Normalise an input/output spec to [(var_name, array), ...]."""
    if isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], (list, tuple)):
        return [(name, np.asarray(arr)) for name, arr in value]
    return [(slot, np.asarray(value))]


class OpTest(object):
    atol = 1e-5
    rtol = 1e-5

    def _build(self):
        prog, startup = Program(), Program()
        feed = {}
        with program_guard(prog, startup):
            block = prog.global_block()
            op_inputs, op_outputs = {}, {}
            for slot, value in getattr(self, 'inputs', {}).items():
                names = []
                for name, arr in _as_pairs(slot, value):
                    block.create_var(name=name, shape=arr.shape,
                                     dtype=str(arr.dtype), is_data=True)
                    feed[name] = arr
                    names.append(name)
                op_inputs[slot] = names
            for slot, value in getattr(self, 'outputs', {}).items():
                names = []
                for name, _arr in _as_pairs(slot, value):
                    block.create_var(name=name, dtype=None)
                    names.append(name)
                op_outputs[slot] = names
            block.append_op(type=self.op_type, inputs=op_inputs,
                            outputs=op_outputs,
                            attrs=getattr(self, 'attrs', {}))
        return prog, startup, feed, op_inputs, op_outputs

    def check_output(self, atol=None, no_check_set=()):
        atol = atol if atol is not None else self.atol
        prog, startup, feed, _op_in, op_outputs = self._build()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            fetch_names, expects = [], []
            for slot, value in self.outputs.items():
                for name, arr in _as_pairs(slot, value):
                    if name in no_check_set or slot in no_check_set:
                        continue
                    fetch_names.append(name)
                    expects.append(np.asarray(arr))
            results = exe.run(prog, feed=feed, fetch_list=fetch_names)
            for name, got, want in zip(fetch_names, results, expects):
                np.testing.assert_allclose(
                    got.astype(np.float64) if got.dtype != np.bool_ else got,
                    want.astype(np.float64) if want.dtype != np.bool_ else want,
                    atol=atol, rtol=self.rtol,
                    err_msg='output %r of op %s mismatch'
                            % (name, self.op_type))

    def check_grad(self, inputs_to_check, output_names=None,
                   max_relative_error=0.005, numeric_delta=5e-3,
                   no_grad_set=None, objective='sum'):
        """Analytic grads (via backward ops) vs central finite differences
        of a scalar objective over the outputs. objective='sum' (default)
        or 'sumsq' — sumsq for ops whose output-sum is degenerate (batch
        norm: the normalized values sum to a constant)."""
        if output_names is None:
            output_names = []
            for slot, value in self.outputs.items():
                output_names.extend(n for n, _ in _as_pairs(slot, value))
        elif isinstance(output_names, str):
            output_names = [output_names]

        assert objective in ('sum', 'sumsq'), objective
        prog, startup, feed, op_in, _op_out = self._build()
        with program_guard(prog, startup):
            block = prog.global_block()
            # scalar objective: sum over every checked output
            partials = []
            for n in output_names:
                src_name = n
                if objective == 'sumsq':
                    block.create_var(name=n + '@SQ', dtype='float32')
                    block.append_op(
                        type='elementwise_mul',
                        inputs={'X': [n], 'Y': [n]},
                        outputs={'Out': [n + '@SQ']},
                        attrs={'axis': -1})
                    src_name = n + '@SQ'
                block.create_var(name=n + '@SUM', dtype='float32')
                block.append_op(type='reduce_sum',
                                inputs={'X': [src_name]},
                                outputs={'Out': [n + '@SUM']},
                                attrs={'reduce_all': True, 'dim': [0],
                                       'keep_dim': False})
                partials.append(n + '@SUM')
            obj = block.create_var(name='grad_objective', dtype='float32')
            block.append_op(type='sum', inputs={'X': partials},
                            outputs={'Out': ['grad_objective']})
            obj_var = block.var('grad_objective')
            in_vars = [block.var(n) for n in inputs_to_check]
            grads = fluid.calc_gradient(obj_var, in_vars,
                                        no_grad_set=no_grad_set)

        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            analytic = exe.run(prog, feed=feed,
                               fetch_list=[g for g in grads])

            # numeric: central differences through the same executor
            for name, got in zip(inputs_to_check, analytic):
                base = feed[name].astype(np.float64)
                num = np.zeros_like(base, dtype=np.float64)
                flat = base.reshape(-1)
                for i in range(flat.size):
                    orig = flat[i]
                    for sign in (+1, -1):
                        flat[i] = orig + sign * numeric_delta
                        feed[name] = base.reshape(feed[name].shape) \
                            .astype(feed[name].dtype)
                        val, = exe.run(prog, feed=feed,
                                       fetch_list=['grad_objective'])
                        num.reshape(-1)[i] += sign * float(val)
                    flat[i] = orig
                feed[name] = base.reshape(feed[name].shape) \
                    .astype(feed[name].dtype)
                num /= (2.0 * numeric_delta)
                got = np.asarray(got, dtype=np.float64)
                denom = np.maximum(np.maximum(np.abs(num), np.abs(got)), 1e-3)
                diff = np.abs(num - got)
                rel = diff / denom
                # differences below fp32 finite-difference noise are a match
                rel = np.where(diff < 1e-4, 0.0, rel)
                assert rel.max() <= max_relative_error, (
                    'grad of %r for op %s: max rel err %.5f > %.5f\n'
                    'numeric=%s\nanalytic=%s'
                    % (name, self.op_type, rel.max(), max_relative_error,
                       num.reshape(-1)[:8], got.reshape(-1)[:8]))
