"""High-level Trainer: events, checkpoint cadence + pruning, and
kill-and-restart EXACT-step resume (reference trainer.py:169 Trainer,
:100 CheckpointConfig, :558-641 save/load checkpoint)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _train_func():
    x = fluid.layers.data(name='x', shape=[4], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    pred = fluid.layers.fc(input=x, size=1,
                           param_attr=fluid.ParamAttr(
                               name='tw',
                               initializer=fluid.initializer.Normal(
                                   scale=0.1, seed=3)))
    return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))


def _optimizer_func():
    return fluid.optimizer.Adam(0.02)


def _reader():
    rng = np.random.RandomState(7)
    w = np.linspace(-1, 1, 4).astype('float32')[:, None]
    for _ in range(10):
        x = rng.randn(8, 4).astype('float32')
        yield [x, x @ w]


class _Abort(Exception):
    pass


def _run(ckpt_dir, epochs=2, abort_at=None, max_num_checkpoints=2):
    """One Trainer life; abort_at=(epoch, step) simulates a kill. Each
    life gets a fresh name generator, as a real process restart would."""
    from paddle_tpu import unique_name
    unique_name.switch()
    trainer = fluid.Trainer(
        _train_func, _optimizer_func, place=fluid.CPUPlace(),
        checkpoint_config=fluid.CheckpointConfig(
            checkpoint_dir=ckpt_dir,
            max_num_checkpoints=max_num_checkpoints,
            step_interval=3))
    seen = []

    def handler(event):
        if isinstance(event, fluid.EndStepEvent):
            seen.append((event.epoch, event.step,
                         float(np.asarray(event.metrics[0]))))
            if abort_at is not None and \
                    (event.epoch, event.step) == abort_at:
                raise _Abort()
    try:
        trainer.train(num_epochs=epochs, event_handler=handler,
                      reader=_reader, feed_order=['x', 'y'])
    except _Abort:
        pass
    return seen, trainer


def test_trainer_trains_and_checkpoints(tmp_path):
    ckpt = str(tmp_path / 'ck')
    seen, trainer = _run(ckpt, epochs=1)
    assert len(seen) == 10
    assert seen[-1][2] < seen[0][2]
    # step_interval=3 over 10 steps + epoch end -> pruned to the last 2
    dirs = sorted(d for d in os.listdir(ckpt) if d.startswith('checkpoint'))
    assert len(dirs) == 2, dirs


def test_trainer_kill_and_exact_resume(tmp_path):
    """Kill mid-epoch after a checkpoint; a fresh Trainer resumes at the
    exact next step with IDENTICAL losses to an uninterrupted run."""
    full, _ = _run(str(tmp_path / 'full'), epochs=2)

    ckpt = str(tmp_path / 'ck')
    part, _ = _run(ckpt, epochs=2, abort_at=(0, 7))   # ckpt at step 5
    resumed, trainer2 = _run(ckpt, epochs=2)

    # the resumed run starts where the newest checkpoint left off (step 6)
    assert resumed[0][:2] == (0, 6)
    # and every (epoch, step) it replays matches the uninterrupted run
    # bit-for-bit: params, Adam moments AND the executor RNG stream were
    # all restored
    full_by_key = {(e, s): v for e, s, v in full}
    for e, s, v in resumed:
        np.testing.assert_allclose(v, full_by_key[(e, s)], rtol=1e-6,
                                   err_msg='step (%d, %d)' % (e, s))
    assert resumed[-1][:2] == (1, 9)


def test_trainer_test_mode(tmp_path):
    _, trainer = _run(str(tmp_path / 'ck2'), epochs=1)
    metrics = trainer.test(reader=_reader, feed_order=['x', 'y'])
    assert len(metrics) == 1 and np.isfinite(metrics[0])


def test_trainer_refuses_partial_checkpoint(tmp_path):
    """A checkpoint dir without the SUCCESS marker (killed mid-write) is
    ignored on resume."""
    ckpt = str(tmp_path / 'ck3')
    _run(ckpt, epochs=1)
    dirs = sorted(d for d in os.listdir(ckpt)
                  if d.startswith('checkpoint'))
    # corrupt the newest: drop its success marker
    newest = os.path.join(ckpt, dirs[-1])
    os.remove(os.path.join(newest, '_SUCCESS'))
    from paddle_tpu import unique_name
    unique_name.switch()
    t = fluid.Trainer(
        _train_func, _optimizer_func, place=fluid.CPUPlace(),
        checkpoint_config=fluid.CheckpointConfig(checkpoint_dir=ckpt))
    # resumed from the OLDER complete checkpoint, not the corrupt one
    assert t._resumed
    with open(os.path.join(ckpt, dirs[-2], 'TRAINER_METADATA')) as f:
        import json
        assert t.step_id == json.load(f)['step_id'] + 1


def test_trainer_resume_skips_all_unusable_checkpoints(tmp_path):
    """Resume walks newest->oldest past EVERY unusable checkpoint — one
    missing its SUCCESS marker (killed mid-write) AND one with corrupted
    metadata (torn disk write) — and restores the newest VALID one."""
    import json
    ckpt = str(tmp_path / 'ck4')
    _run(ckpt, epochs=1, max_num_checkpoints=3)
    dirs = sorted(d for d in os.listdir(ckpt)
                  if d.startswith('checkpoint'))
    assert len(dirs) == 3, dirs
    # newest: no SUCCESS marker; 2nd-newest: garbage metadata
    os.remove(os.path.join(ckpt, dirs[-1], '_SUCCESS'))
    with open(os.path.join(ckpt, dirs[-2], 'TRAINER_METADATA'), 'w') as f:
        f.write('{not json')
    from paddle_tpu import unique_name
    unique_name.switch()
    t = fluid.Trainer(
        _train_func, _optimizer_func, place=fluid.CPUPlace(),
        checkpoint_config=fluid.CheckpointConfig(checkpoint_dir=ckpt))
    assert t._resumed
    with open(os.path.join(ckpt, dirs[-3], 'TRAINER_METADATA')) as f:
        assert t.step_id == json.load(f)['step_id'] + 1


def test_trainer_no_valid_checkpoint_starts_fresh(tmp_path):
    """When every checkpoint is unusable, training starts from scratch
    instead of crashing on the corrupt state."""
    ckpt = str(tmp_path / 'ck5')
    _run(ckpt, epochs=1)
    for d in os.listdir(ckpt):
        if d.startswith('checkpoint'):
            os.remove(os.path.join(ckpt, d, '_SUCCESS'))
    from paddle_tpu import unique_name
    unique_name.switch()
    t = fluid.Trainer(
        _train_func, _optimizer_func, place=fluid.CPUPlace(),
        checkpoint_config=fluid.CheckpointConfig(checkpoint_dir=ckpt))
    assert not t._resumed
    assert t.epoch_id == 0 and t.step_id == 0
