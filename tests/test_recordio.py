"""RecordIO chunk engine (native/recordio.cc via ctypes) + tensor serde
(reference recordio/{writer,scanner,chunk}, recordio_writer.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import recordio


def _samples(n=25):
    rng = np.random.RandomState(0)
    for i in range(n):
        yield (rng.rand(4, 3).astype('float32'),
               np.asarray([i], 'int64'))


def test_write_scan_roundtrip(tmp_path):
    path = str(tmp_path / 'data.recordio')
    n = recordio.convert_reader_to_recordio_file(
        path, lambda: _samples(), max_num_records=10)  # several chunks
    assert n == 25
    got = list(recordio.reader(path)())
    want = list(_samples())
    assert len(got) == 25
    for (gx, gi), (wx, wi) in zip(got, want):
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gi, wi)
        assert gx.dtype == wx.dtype and gi.dtype == wi.dtype


def test_no_compress_and_deflate_agree(tmp_path):
    p0 = str(tmp_path / 'raw.recordio')
    p1 = str(tmp_path / 'defl.recordio')
    recordio.convert_reader_to_recordio_file(
        p0, lambda: _samples(8), compressor=recordio.Compressor.NoCompress)
    recordio.convert_reader_to_recordio_file(
        p1, lambda: _samples(8), compressor=recordio.Compressor.Deflate)
    for a, b in zip(recordio.reader(p0)(), recordio.reader(p1)()):
        np.testing.assert_array_equal(a[0], b[0])


def test_compression_shrinks_compressible_data(tmp_path):
    p0 = str(tmp_path / 'raw.recordio')
    p1 = str(tmp_path / 'defl.recordio')

    def zeros():
        for _ in range(20):
            yield (np.zeros((64, 64), 'float32'),)
    recordio.convert_reader_to_recordio_file(
        p0, zeros, compressor=recordio.Compressor.NoCompress)
    recordio.convert_reader_to_recordio_file(
        p1, zeros, compressor=recordio.Compressor.Deflate)
    assert os.path.getsize(p1) < os.path.getsize(p0) / 10


def test_corruption_detected(tmp_path):
    path = str(tmp_path / 'data.recordio')
    recordio.convert_reader_to_recordio_file(
        path, lambda: _samples(5),
        compressor=recordio.Compressor.NoCompress)
    blob = bytearray(open(path, 'rb').read())
    blob[40] ^= 0xFF            # flip a payload byte past the header
    open(path, 'wb').write(bytes(blob))
    with pytest.raises(IOError, match='crc|corrupt|inflate'):
        list(recordio.reader(path)())


def test_not_a_recordio_file(tmp_path):
    path = str(tmp_path / 'junk')
    open(path, 'wb').write(b'this is not a recordio file at all')
    with pytest.raises(IOError, match='magic'):
        list(recordio.reader(path)())


def test_sharded_files_and_glob(tmp_path):
    base = str(tmp_path / 'shard.recordio')
    counts = recordio.convert_reader_to_recordio_files(
        base, 10, lambda: _samples(25))
    assert counts == [10, 10, 5]
    got = list(recordio.reader(base + '-*')())
    assert len(got) == 25
    idx = [int(s[1][0]) for s in got]
    assert idx == list(range(25))     # order preserved across shards


def test_recordio_feeds_py_reader_training(tmp_path):
    """The full loop: dataset -> recordio file -> reader -> py_reader
    double-buffer -> train. The recordio reader is a first-class member
    of the data stack."""
    from paddle_tpu.framework import Program, program_guard
    path = str(tmp_path / 'train.recordio')
    rng = np.random.RandomState(1)
    w = rng.randn(8, 1).astype('float32')

    def samples():
        for _ in range(48):
            x = rng.randn(8).astype('float32')
            yield (x, (x @ w).astype('float32'))
    recordio.convert_reader_to_recordio_file(path, samples)

    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        rd = fluid.layers.py_reader(capacity=4, shapes=[[-1, 8], [-1, 1]],
                                    dtypes=['float32', 'float32'],
                                    name='rio_r', use_double_buffer=True)
        x, y = fluid.layers.read_file(rd)
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    batched = fluid.batch(recordio.reader(path), batch_size=16)
    rd.decorate_paddle_reader(batched)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for _ in range(12):
        rd.start()
        while True:
            try:
                l, = exe.run(prog, fetch_list=[loss])
                losses.append(float(np.asarray(l)))
            except fluid.reader.pipeline.EOFException:
                rd.reset()
                break
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])


def test_parallel_scanner_reads_all_shards(tmp_path):
    """ParallelRecordIOScanner (native/prefetcher.cc): C++ worker
    threads scan many files concurrently (GIL-free CRC+inflate) into
    one bounded queue; the record MULTISET must equal the files'
    contents, with per-file order preserved within each file."""
    import collections
    from paddle_tpu import recordio

    rng = np.random.RandomState(0)
    expected = collections.Counter()
    paths = []
    for i in range(6):
        p = str(tmp_path / ('shard-%d' % i))
        paths.append(p)
        with recordio.RecordIOWriter(p, max_num_records=7) as w:
            for r in range(23):
                rec = ('f%d-r%03d-' % (i, r)).encode() + \
                    rng.bytes(rng.randint(1, 200))
                w.append_record(rec)
                expected[rec] += 1

    got = collections.Counter()
    per_file_order = collections.defaultdict(list)
    with recordio.ParallelRecordIOScanner(paths, n_threads=3) as sc:
        for rec in sc:
            got[rec] += 1
            tag = rec.split(b'-')[0]
            per_file_order[tag].append(rec[:8])
    assert got == expected
    # within each file, records arrive in write order
    for i in range(6):
        tags = per_file_order[('f%d' % i).encode()]
        assert tags == sorted(tags), tags[:5]


def test_parallel_reader_decodes_samples(tmp_path):
    from paddle_tpu import recordio

    path = str(tmp_path / 'samples')
    rng = np.random.RandomState(1)
    samples = [(rng.rand(3, 4).astype('f4'),
                np.array([i], 'int64')) for i in range(10)]
    recordio.convert_reader_to_recordio_file(
        path, lambda: iter(samples))
    seen = {}
    for x, y in recordio.parallel_reader([path], n_threads=2)():
        seen[int(y[0])] = x
    assert len(seen) == 10
    for i, (x, y) in enumerate(samples):
        np.testing.assert_allclose(seen[i], x)


def test_parallel_scanner_error_paths(tmp_path):
    from paddle_tpu import recordio
    with pytest.raises(IOError):
        with recordio.ParallelRecordIOScanner(
                [str(tmp_path / 'nope')]) as sc:
            next(iter(sc))
    # corrupt file: bad magic surfaces as an error, not a hang
    bad = tmp_path / 'bad'
    bad.write_bytes(b'Z' * 64)
    with pytest.raises(IOError):
        with recordio.ParallelRecordIOScanner([str(bad)]) as sc:
            for _ in sc:
                pass


def test_parallel_scanner_loop_mode_continues_past_one_epoch(tmp_path):
    """loop=True must keep producing across epoch boundaries (the
    reset-the-cursor CAS design deadlocked after exactly one epoch —
    modulo indexing now wraps the atomic cursor)."""
    from paddle_tpu import recordio
    p = str(tmp_path / 'loop-shard')
    with recordio.RecordIOWriter(p, max_num_records=4) as w:
        for r in range(10):
            w.append_record(b'rec-%03d' % r)
    sc = recordio.ParallelRecordIOScanner([p], n_threads=2, loop=True)
    got = [next(sc) for _ in range(35)]      # 3.5 epochs
    sc.close()
    assert sum(1 for g in got if g == b'rec-000') >= 3


class TestNativeImageDecode:
    """Round-5 native decode stage: C++ workers parse (u8 image, i64
    label) records and emit normalized float32 chunks."""

    def _write_shards(self, tmp_path, n_files=2, n_rec=64, shape=(3, 8, 8)):
        import numpy as np
        from paddle_tpu.recordio import RecordIOWriter
        rng = np.random.RandomState(0)
        paths, all_imgs, all_labels = [], {}, {}
        for f in range(n_files):
            p = str(tmp_path / ('img%d.recordio' % f))
            with RecordIOWriter(p, max_num_records=16) as w:
                imgs = rng.randint(0, 256, (n_rec,) + shape, dtype='uint8')
                # unique across files: labels double as record ids
                labels = (np.arange(n_rec) + f * n_rec).astype('int64')
                for i in range(n_rec):
                    w.append_sample([imgs[i], labels[i:i + 1]])
            paths.append(p)
            all_imgs[p] = imgs
            all_labels[p] = labels
        return paths, all_imgs, all_labels

    def test_decode_matches_python_normalize(self, tmp_path):
        import numpy as np
        from paddle_tpu.recordio import ParallelImageScanner
        shape = (3, 8, 8)
        mean = [0.4, 0.5, 0.6]
        std = [0.2, 0.25, 0.3]
        paths, all_imgs, all_labels = self._write_shards(tmp_path,
                                                         shape=shape)
        got = {}
        with ParallelImageScanner(paths, shape, mean=mean, std=std,
                                  n_threads=2, capacity=4) as sc:
            for imgs, labels in sc:
                for i in range(imgs.shape[0]):
                    got[int(labels[i])] = imgs[i].copy()
        n_total = sum(len(v) for v in all_labels.values())
        assert len(got) == len({int(l) for ls in all_labels.values()
                                for l in ls})
        # spot-check numerics against the python-side normalize
        m = np.asarray(mean, 'f4').reshape(3, 1, 1)
        s = np.asarray(std, 'f4').reshape(3, 1, 1)
        for p in paths:
            for i in range(0, 64, 17):
                ref = (all_imgs[p][i].astype('f4') / 255.0 - m) / s
                np.testing.assert_allclose(
                    got[int(all_labels[p][i])], ref, rtol=1e-5,
                    atol=1e-6)

    def test_decode_error_on_wrong_record_format(self, tmp_path):
        import numpy as np
        import pytest
        from paddle_tpu.recordio import (ParallelImageScanner,
                                         RecordIOWriter)
        p = str(tmp_path / 'bad.recordio')
        with RecordIOWriter(p) as w:
            # float32 image slot: not the u8 contract
            w.append_sample([np.zeros((3, 4, 4), 'f4'),
                             np.zeros((1,), 'int64')])
        with pytest.raises(IOError):
            with ParallelImageScanner([p], (3, 4, 4)) as sc:
                list(sc)

    def test_open_files_image_norm_trains(self, tmp_path):
        import numpy as np
        import paddle_tpu as fluid
        shape = (3, 8, 8)
        paths, _, _ = self._write_shards(tmp_path, shape=shape)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            rdr = fluid.layers.open_files(
                paths, shapes=[(-1,) + shape, (-1, 1)],
                dtypes=['float32', 'int64'], thread_num=2, pass_num=2,
                image_norm=dict(mean=[0.5, 0.5, 0.5],
                                std=[0.25, 0.25, 0.25]))
            rdr = fluid.layers.batch(rdr, batch_size=16)
            img, label = fluid.layers.read_file(rdr)
            c = fluid.layers.conv2d(img, 4, 3, padding=1)
            pool = fluid.layers.pool2d(c, pool_type='avg',
                                       global_pooling=True)
            pred = fluid.layers.fc(pool, size=100, act='softmax')
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.SGD(0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rdr.start()
        losses = []
        for _ in range(6):
            l, = exe.run(prog, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
        rdr.reset()
        assert np.isfinite(losses).all()
