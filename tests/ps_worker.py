"""Subprocess worker for parameter-server tests (sync + async modes).

Spawned by test_dist_pserver.py with roles via env vars; the model
builders here are also imported by the test process to run the local
(non-distributed) parity baseline. Pattern of the reference's
test_dist_base.py runtime_main().
"""
import json
import os
import sys

import jax

jax.config.update('jax_platforms', 'cpu')

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np                     # noqa: E402
import paddle_tpu as fluid             # noqa: E402

BATCH_PER_TRAINER = 16
VOCAB = 512
EMB_DIM = 16


def build_mlp():
    """Dense MLP: the fc weight (64x256 = 16384 elems) splits across two
    pservers; biases stay whole."""
    x = fluid.layers.data(name='x', shape=[64], dtype='float32')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    h = fluid.layers.fc(input=x, size=256, act='relu',
                        param_attr=fluid.ParamAttr(
                            name='w1',
                            initializer=fluid.initializer.Normal(
                                scale=0.1, seed=7)),
                        bias_attr=fluid.ParamAttr(
                            name='b1',
                            initializer=fluid.initializer.Constant(0.1)))
    pred = fluid.layers.fc(input=h, size=1,
                           param_attr=fluid.ParamAttr(
                               name='w2',
                               initializer=fluid.initializer.Normal(
                                   scale=0.1, seed=11)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return loss, ['x', 'y'], ['w1', 'b1', 'w2']


def build_sparse(distributed_table=False):
    """Sparse embedding (SelectedRows grads). VOCAB*EMB_DIM=8192 elems:
    the table splits row-wise across pservers in plain sparse mode, or is
    mod-sharded + prefetched when distributed_table=True."""
    ids = fluid.layers.data(name='ids', shape=[4], dtype='int64')
    y = fluid.layers.data(name='y', shape=[1], dtype='float32')
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, EMB_DIM], is_sparse=True,
        is_distributed=distributed_table,
        param_attr=fluid.ParamAttr(
            name='emb_w',
            initializer=fluid.initializer.Normal(scale=0.1, seed=5)))
    pooled = fluid.layers.reduce_mean(emb, dim=1)
    pred = fluid.layers.fc(input=pooled, size=1,
                           param_attr=fluid.ParamAttr(
                               name='fc_w',
                               initializer=fluid.initializer.Normal(
                                   scale=0.1, seed=13)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    params = ['fc_w'] if distributed_table else ['emb_w', 'fc_w']
    return loss, ['ids', 'y'], params


def build_deepfm():
    """DeepFM-style CTR model (BASELINE parity config 5): sparse first-
    order weights + sparse field embeddings, FM second-order interaction,
    deep MLP tower, logistic loss."""
    fields = 8
    ids = fluid.layers.data(name='ids', shape=[fields], dtype='int64')
    label = fluid.layers.data(name='label', shape=[1], dtype='float32')
    first = fluid.layers.embedding(
        ids, size=[VOCAB, 1], is_sparse=True,
        param_attr=fluid.ParamAttr(
            name='fm_w1',
            initializer=fluid.initializer.Normal(scale=0.01, seed=3)))
    emb = fluid.layers.embedding(
        ids, size=[VOCAB, EMB_DIM], is_sparse=True,
        param_attr=fluid.ParamAttr(
            name='fm_emb',
            initializer=fluid.initializer.Normal(scale=0.01, seed=9)))
    # FM second order: 0.5 * sum((sum_f v_f)^2 - sum_f v_f^2)
    summed = fluid.layers.reduce_sum(emb, dim=1)               # [B, D]
    sum_sq = fluid.layers.square(summed)
    sq_sum = fluid.layers.reduce_sum(fluid.layers.square(emb), dim=1)
    second = fluid.layers.scale(
        fluid.layers.reduce_sum(
            fluid.layers.elementwise_sub(sum_sq, sq_sum),
            dim=1, keep_dim=True), scale=0.5)                  # [B, 1]
    fo = fluid.layers.reduce_sum(first, dim=1)                 # [B, 1]
    deep_in = fluid.layers.reshape(emb, shape=[-1, 8 * EMB_DIM])
    deep = fluid.layers.fc(input=deep_in, size=32, act='relu',
                           param_attr=fluid.ParamAttr(
                               name='deep_w1',
                               initializer=fluid.initializer.Normal(
                                   scale=0.1, seed=21)))
    deep_out = fluid.layers.fc(input=deep, size=1,
                               param_attr=fluid.ParamAttr(
                                   name='deep_w2',
                                   initializer=fluid.initializer.Normal(
                                       scale=0.1, seed=23)))
    logit = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(fo, second), deep_out)
    loss = fluid.layers.mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(logit, label))
    return loss, ['ids', 'label'], ['fm_w1', 'fm_emb', 'deep_w1', 'deep_w2']


MODELS = {'mlp': build_mlp, 'sparse': build_sparse,
          'table': lambda: build_sparse(distributed_table=True),
          'deepfm': build_deepfm}


def make_batch(model, rng, batch):
    if model == 'mlp':
        x = rng.randn(batch, 64).astype('float32')
        w = np.linspace(-1, 1, 64).astype('float32')[:, None]
        return {'x': x, 'y': (x @ w + 0.1).astype('float32')}
    if model in ('sparse', 'table'):
        ids = rng.randint(0, VOCAB, size=(batch, 4)).astype('int64')
        return {'ids': ids,
                'y': rng.rand(batch, 1).astype('float32')}
    ids = rng.randint(0, VOCAB, size=(batch, 8)).astype('int64')
    return {'ids': ids,
            'label': (rng.rand(batch, 1) > 0.5).astype('float32')}


def make_optimizer(name):
    if name == 'adam':
        return fluid.optimizer.Adam(0.01)
    return fluid.optimizer.SGD(0.01)


def local_train(model, steps, optimizer='sgd', trainers=2):
    """The non-distributed baseline over the same GLOBAL batches."""
    loss, feeds, params = MODELS[model]()
    make_optimizer(optimizer).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        batch = make_batch(model, rng, BATCH_PER_TRAINER * trainers)
        l, = exe.run(feed=batch, fetch_list=[loss])
        losses.append(float(l))
    weights = {p: fluid.fetch_var(p).tolist() for p in params}
    return losses, weights


def main():
    role = os.environ['PS_ROLE']
    model = os.environ['PS_MODEL']
    eps = os.environ['PS_ENDPOINTS']
    trainers = int(os.environ['PS_TRAINERS'])
    steps = int(os.environ['PS_STEPS'])
    sync = os.environ.get('PS_SYNC', '1') == '1'
    optimizer = os.environ.get('PS_OPTIMIZER', 'sgd')
    trainer_id = int(os.environ.get('PS_TRAINER_ID', 0))

    loss, feeds, params = MODELS[model]()
    make_optimizer(optimizer).minimize(loss)

    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id, pservers=eps, trainers=trainers,
                sync_mode=sync)
    exe = fluid.Executor(fluid.CPUPlace())

    if role == 'pserver':
        ep = eps.split(',')[int(os.environ['PS_PSERVER_ID'])]
        main_prog, startup = t.get_pserver_programs(
            ep, checkpoint_dir=os.environ.get('PS_RESTORE') or None)
        exe.run(startup)
        exe.run(main_prog)       # blocks until all trainers COMPLETE
        return

    # elastic recovery: a RESTARTED trainer (incarnation > 0, set by the
    # supervisor) must re-register BEFORE the startup recv — the
    # handshake lifts a dead-tid ban and reports each shard's round
    # state. Resume point: min(expected) across shards when the servers
    # were still waiting for us (fast restart — the stale-round
    # ack-ignore catches any ahead shard up), max(round) when some shard
    # had already retired us (rounds ran without us; re-align with the
    # global round count). The startup recv below then pulls the
    # authoritative post-round params, so recomputation at resume_step
    # starts from exactly the state the dead incarnation saw.
    from paddle_tpu.flags import get_flag
    incarnation = int(get_flag('trainer_incarnation', 0) or 0)
    resume_step = 0
    if incarnation > 0:
        from paddle_tpu.distributed.rpc import get_client
        clients = [get_client(ep, trainer_id) for ep in eps.split(',')]
        infos = [c.register() for c in clients]
        if any(i.get('rejoined') for i in infos):
            resume_step = max(int(i['round']) for i in infos)
        else:
            resume_step = min(int(i['expected']) for i in infos)
        for c in clients:
            c.set_round(resume_step)
        print('REJOIN inc=%d resume_step=%d infos=%s'
              % (incarnation, resume_step, infos), flush=True)

    exe.run(t.get_trainer_startup_program())
    prog = t.get_trainer_program()
    rng = np.random.RandomState(0)
    losses = []
    # PS_DIE_AFTER=k: this trainer dies SILENTLY (no COMPLETE, no
    # socket shutdown handshake) after k steps — the rpc_deadline
    # fault-tolerance test's murder weapon
    die_after = int(os.environ.get('PS_DIE_AFTER', 0))
    for step in range(steps):
        if die_after and trainer_id == int(
                os.environ.get('PS_DIE_TID', 1)) and step == die_after:
            os._exit(137)
        gbatch = make_batch(model, rng, BATCH_PER_TRAINER * trainers)
        lo = trainer_id * BATCH_PER_TRAINER
        batch = {k: v[lo:lo + BATCH_PER_TRAINER] for k, v in gbatch.items()}
        if step < resume_step:
            continue   # replayed history: batch drawn (RNG parity) only
        l, = exe.run(prog, feed=batch, fetch_list=[loss])
        losses.append(float(l))
    ckpt = os.environ.get('PS_CHECKPOINT')
    if ckpt and trainer_id == 0:
        # production path: the transpiler's checkpoint-notify program
        exe.run(t.checkpoint_notify_program(ckpt))
    weights = {p: fluid.fetch_var(p).tolist() for p in params
               if fluid.global_scope().find_var(p) is not None}
    print('RESULT ' + json.dumps({'losses': losses, 'weights': weights}))
    exe.close()


if __name__ == '__main__':
    main()
