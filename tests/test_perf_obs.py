"""Device-side performance observatory (paddle_tpu/obs/perf.py +
obs/slo.py + the executor wiring).

What must hold:

- a two-step CPU train run lands xla.jit_cache hit/miss counts, a
  nonzero perf.step_latency histogram, and live hbm.* gauges in one
  registry snapshot, and the SECOND identical Executor.run is a pure
  cache hit — no new xla.compile span appears in the trace stream;
- ParallelExecutor keeps full jit_cache_stats parity with Executor and
  compiles the SPMD step exactly once across a steady-state loop;
- memory.estimate_program_memory upper-bounds what the framework
  actually holds after running the program (CPU-safe: allocator stats
  degrade to the scope footprint);
- histogram snapshots carry p50/p95/p99 derived from the exponential
  buckets, the report rollup ships percentiles instead of raw bucket
  dumps, and a torn metrics tail (kill -9 mid-write) merges with a
  warning instead of crashing;
- profiler device-op events round-trip into the merged chrome
  timeline as device lanes distinct from the host lanes, on the same
  clock;
- a deliberately breached SLO rule emits a slo.breach event;
- tools/perf_gate.py exits 0 on the committed BENCH trajectory and
  nonzero on a synthetically regressed fixture.
"""
import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import memory
from paddle_tpu.obs import perf, report, slo, telemetry, trace

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
_PERF_GATE = os.path.join(_ROOT, 'tools', 'perf_gate.py')


@pytest.fixture
def obs_on(tmp_path):
    """Telemetry + tracing into a tmp dir; always restored to the
    disabled default afterwards (other tests rely on zero overhead)."""
    d = str(tmp_path / 'obs')
    telemetry.reset()
    perf._reset_for_tests()
    telemetry.enable(d, role='t0', period=60.0)
    trace.enable(d, role='t0')
    yield d
    trace.disable()
    telemetry.disable(final_flush=False)
    telemetry.reset()
    perf._reset_for_tests()
    slo.stop_global()


def _events(obs_dir):
    out = []
    for dirpath, _, files in os.walk(obs_dir):
        for fn in files:
            if fn.startswith('events-'):
                with open(os.path.join(dirpath, fn)) as f:
                    out.extend(json.loads(l) for l in f if l.strip())
    return out


def _tiny_train(bs=4):
    x = fluid.layers.data(name='x', shape=[8], dtype='float32')
    y = fluid.layers.fc(input=x, size=4)
    loss = fluid.layers.mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    feed = {'x': np.ones((bs, 8), dtype='float32')}
    return loss, feed


# ---------------------------------------------------------------------------
# compile/JIT + step telemetry through the Executor
# ---------------------------------------------------------------------------

def test_two_step_train_emits_perf_telemetry(obs_on):
    """The headline acceptance path: two identical train steps -> jit
    hit+miss counts, nonzero step latency, live hbm gauges, and the
    second run adds NO new xla.compile span."""
    fluid.set_flags({'FLAGS_perf_peak_tflops': 1.0})
    try:
        loss, feed = _tiny_train()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        exe.run(feed=feed, fetch_list=[loss])

        snap = telemetry.snapshot()
        assert snap['counters']['xla.jit_cache.miss'] >= 2  # startup+main
        compiles_before = [e for e in _events(obs_on)
                           if e.get('name') == 'xla.compile']
        assert compiles_before, 'first run must trace xla.compile spans'
        for e in compiles_before:
            assert e.get('fingerprint'), 'span must carry a fingerprint'
        assert snap['hists']['xla.compile_latency']['count'] == \
            snap['counters']['xla.jit_cache.miss']

        exe.run(feed=feed, fetch_list=[loss])   # identical -> pure hit
        snap = telemetry.snapshot()
        assert snap['counters']['xla.jit_cache.hit'] >= 1
        compiles_after = [e for e in _events(obs_on)
                          if e.get('name') == 'xla.compile']
        assert len(compiles_after) == len(compiles_before), \
            'cache hit must not emit a new compile span'

        # live step attribution
        assert snap['hists']['perf.step_latency']['count'] == 3
        assert snap['hists']['perf.step_latency']['sum'] > 0
        assert snap['counters']['perf.steps'] == 3
        # hbm gauges live even on CPU (scope-footprint fallback): the
        # fc weight/bias are persistable device arrays by now
        assert snap['gauges']['hbm.bytes_in_use'] > 0
        assert snap['gauges']['hbm.watermark_bytes'] >= \
            snap['gauges']['hbm.bytes_in_use']
        assert snap['gauges']['hbm.scope_bytes'] > 0
        # cost analysis fed the work model -> nonzero MFU against the
        # pinned 1-TFLOP/s peak
        assert snap['gauges']['perf.achieved_tflops'] > 0
        assert snap['gauges']['perf.mfu'] > 0

        stats = exe.jit_cache_stats()
        assert stats['segment_misses'] == stats['compiled_segments']
        assert stats['segment_hits'] >= 1
    finally:
        fluid.set_flags({'FLAGS_perf_peak_tflops': 0.0})


def test_prepared_program_fingerprint_and_cost(obs_on):
    loss, feed = _tiny_train()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(feed=feed, fetch_list=[loss])
    prepared = [p for k, p in exe._prepared_cache.items()
                if k[0] != 'block_run']
    assert all(p.fingerprint for p in prepared)
    # the train program's matmul segment must report analytical flops
    assert any(p.cost_flops > 0 for p in prepared)


def test_disabled_mode_records_nothing():
    """With obs off, the same run must leave the registry untouched
    (the hooks are on the Executor hot path)."""
    telemetry.reset()
    loss, feed = _tiny_train()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(feed=feed, fetch_list=[loss])
    telemetry.enable()
    try:
        snap = telemetry.snapshot()
    finally:
        telemetry.disable(final_flush=False)
        telemetry.reset()
    assert snap['counters']['perf.steps'] == 0
    assert snap['hists']['perf.step_latency']['count'] == 0


def test_parallel_executor_compile_once_spmd(obs_on):
    """jit_cache_stats parity on the SPMD path: steady-state training
    compiles each segment exactly once; later steps are pure hits."""
    loss, feed = _tiny_train(bs=8)
    startup_exe = fluid.Executor()
    startup_exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(
        use_cuda=False, loss_name=loss.name,
        main_program=fluid.default_main_program())
    pe.run(fetch_list=[loss.name], feed=feed)
    stats1 = pe.jit_cache_stats()
    assert set(stats1) == {'prepared_programs', 'compiled_segments',
                           'segment_hits', 'segment_misses'}
    assert stats1['compiled_segments'] >= 1
    assert stats1['segment_misses'] == stats1['compiled_segments']
    for _ in range(3):
        pe.run(fetch_list=[loss.name], feed=feed)
    stats2 = pe.jit_cache_stats()
    assert stats2['compiled_segments'] == stats1['compiled_segments'], \
        'SPMD steady state must not recompile'
    assert stats2['segment_hits'] >= stats1['segment_hits'] + 3
    snap = telemetry.snapshot()
    assert snap['counters']['xla.jit_cache.hit'] >= 3


# ---------------------------------------------------------------------------
# memory estimator vs live stats
# ---------------------------------------------------------------------------

def test_estimate_bounds_live_footprint(obs_on):
    """estimate_program_memory (analytic upper bound) must dominate
    what the framework actually holds for the same program, and the
    run must surface live hbm.* gauges in the snapshot."""
    loss, feed = _tiny_train()
    est = memory.estimate_program_memory(
        fluid.default_main_program(), batch_size=4)
    assert est['params'] > 0 and est['total'] >= est['params']
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(feed=feed, fetch_list=[loss])
    actual = memory.max_memory_allocated()   # CPU: scope footprint
    assert actual > 0
    assert est['total'] >= actual, \
        'analytic estimate must upper-bound the live footprint ' \
        '(est=%d actual=%d)' % (est['total'], actual)
    snap = telemetry.snapshot()
    for g in ('hbm.bytes_in_use', 'hbm.peak_bytes',
              'hbm.scope_bytes', 'hbm.watermark_bytes'):
        assert g in snap['gauges']
        assert snap['gauges'][g] > 0, g


def test_hbm_snapshot_shape():
    snap = memory.hbm_snapshot()
    assert set(snap) == {'bytes_in_use', 'peak_bytes', 'bytes_limit',
                         'scope_bytes'}
    assert snap['peak_bytes'] >= snap['bytes_in_use']


# ---------------------------------------------------------------------------
# percentiles + torn-tail merge
# ---------------------------------------------------------------------------

def test_histogram_snapshot_percentiles(obs_on):
    h = telemetry.histogram('test.pct')
    for v in [0.001] * 50 + [0.010] * 45 + [0.100] * 5:
        h.observe(v)
    d = telemetry.snapshot()['hists']['test.pct']
    assert d['p50'] is not None
    assert d['min'] <= d['p50'] <= d['p95'] <= d['p99'] <= d['max']
    # the mass sits at 1ms / 10ms / 100ms: p50 must be in the low
    # bucket's range, p99 near the top
    assert d['p50'] < 0.01
    assert d['p99'] > 0.01


def test_hist_quantile_single_sample():
    d = {'count': 1, 'min': 0.005, 'max': 0.005, 'sum': 0.005,
         'buckets': [0, 0, 0, 1] + [0] * 8}
    assert telemetry.hist_quantile(d, 0.5) == pytest.approx(0.005)
    assert telemetry.hist_quantile({'count': 0, 'buckets': []},
                                   0.99) is None


def test_rollup_ships_percentiles_not_buckets(tmp_path):
    d = str(tmp_path / 'obs')
    os.makedirs(d)
    telemetry.reset()
    telemetry.enable(d, role='r0', period=60.0)
    try:
        h = telemetry.histogram('test.roll')
        for v in (0.001, 0.002, 0.004, 0.2):
            h.observe(v)
        telemetry.flush()
    finally:
        telemetry.disable(final_flush=False)
        telemetry.reset()
    _, metric_lasts = report.collect(d)
    ru = report.rollup(metric_lasts)
    hd = ru['roles']['r0']['hists']['test.roll']
    assert 'buckets' not in hd
    assert hd['p50'] is not None and hd['p99'] is not None
    assert hd['min'] <= hd['p50'] <= hd['p99'] <= hd['max']
    text = report.format_rollup_text(ru)
    assert 'p50=' in text and 'p99=' in text


def test_torn_metrics_tail_warns_not_crashes(tmp_path):
    d = str(tmp_path / 'obs')
    os.makedirs(d)
    good = {'ts': 1.0, 'role': 'r0', 'counters': {'c': 3},
            'gauges': {}, 'hists': {}}
    with open(os.path.join(d, 'metrics-r0-1.jsonl'), 'w') as f:
        f.write(json.dumps(good) + '\n')
        f.write(json.dumps(good)[:25])   # kill -9 mid-write
    with pytest.warns(UserWarning, match='torn tail'):
        _, metric_lasts = report.collect(d)
    assert len(metric_lasts) == 1
    assert report.rollup(metric_lasts)['totals']['c'] == 3


# ---------------------------------------------------------------------------
# device lanes in the merged timeline
# ---------------------------------------------------------------------------

def test_device_lanes_round_trip(tmp_path):
    """Synthetic device-op events (the profiler.device_op_events
    4-tuple shape) must land in the chrome trace as device lanes
    distinct from the host lane, clock-aligned without an offset."""
    base = 1700000000.0     # host spans stamp unix time.time()
    host = [{'type': 'span', 'kind': 'host', 'name': 'step',
             'sid': 'h1', 't0': base, 't1': base + 0.010,
             'tid': 0, 'role': 'trainer0', 'pid': 10}]
    dev_events = [
        ('fusion.1', int((base + 0.002) * 1e9), 1_000_000,
         '/device:TPU:0'),
        ('mul.3', int((base + 0.004) * 1e9), 2_000_000,
         '/device:TPU:1'),
    ]
    recs = report.device_events_to_records(dev_events)
    assert all(r['kind'] == 'device' for r in recs)
    tl = report.build_timeline(host + recs)
    lanes = {e['args']['name']: e['pid'] for e in tl['traceEvents']
             if e.get('ph') == 'M'}
    assert 'trainer0' in lanes
    assert 'device:TPU:0' in lanes and 'device:TPU:1' in lanes
    assert len({lanes['trainer0'], lanes['device:TPU:0'],
                lanes['device:TPU:1']}) == 3, 'lanes must be distinct'
    spans = {e['name']: e for e in tl['traceEvents']
             if e.get('ph') == 'X'}
    assert spans['fusion.1']['cat'] == 'device'
    # same clock family: the device op started 2ms into the host step
    assert spans['fusion.1']['ts'] - spans['step']['ts'] == \
        pytest.approx(2000, abs=1)
    assert spans['mul.3']['dur'] == pytest.approx(2000, abs=1)


def test_write_report_merges_xplane_dir(tmp_path, monkeypatch):
    """write_report(xplane_dir=...) pulls device lanes through
    profiler.device_op_events (stubbed: no real capture on CPU)."""
    d = str(tmp_path / 'obs')
    os.makedirs(d)
    with open(os.path.join(d, 'events-t0-1.jsonl'), 'w') as f:
        f.write(json.dumps({'type': 'span', 'kind': 'host',
                            'name': 'host_op', 'sid': 'a', 't0': 5.0,
                            't1': 5.5, 'tid': 0, 'role': 't0',
                            'pid': 1}) + '\n')
    from paddle_tpu import profiler
    monkeypatch.setattr(
        profiler, 'device_op_events',
        lambda xdir, op_map=None, with_plane=False:
            [('conv2d.0', int(5.1e9), 50_000_000, '/device:TPU:0')])
    tl, _ = report.write_report(d, xplane_dir=str(tmp_path))
    names = [e['name'] for e in tl['traceEvents']
             if e.get('ph') == 'X']
    assert 'host_op' in names and 'conv2d.0' in names
    cats = {e['name']: e['cat'] for e in tl['traceEvents']
            if e.get('ph') == 'X'}
    assert cats['conv2d.0'] == 'device'


# ---------------------------------------------------------------------------
# SLO watchdog
# ---------------------------------------------------------------------------

def test_slo_breach_emits_event(obs_on):
    """A rule breached on purpose -> slo.breach in the event stream +
    the slo.breaches counter; a satisfied rule stays silent."""
    telemetry.gauge('test.slo.mfu').set(0.10)
    h = telemetry.histogram('test.slo.lat')
    for _ in range(10):
        h.observe(0.5)
    wd = slo.SLOWatchdog(slo.parse_rules(json.dumps([
        {'name': 'mfu_floor', 'metric': 'test.slo.mfu',
         'kind': 'gauge_min', 'threshold': 0.45},
        {'name': 'lat_p99', 'metric': 'test.slo.lat',
         'kind': 'p99_max', 'threshold': 0.010, 'min_count': 5},
        {'name': 'ok_rule', 'metric': 'test.slo.mfu',
         'kind': 'gauge_max', 'threshold': 0.90},
    ])))
    breaches = wd.check_now()
    assert {b['rule'] for b in breaches} == {'mfu_floor', 'lat_p99'}
    mfu_breach = next(b for b in breaches if b['rule'] == 'mfu_floor')
    assert mfu_breach['value'] == pytest.approx(0.10)
    assert mfu_breach['threshold'] == pytest.approx(0.45)
    evs = [e for e in _events(obs_on) if e.get('type') == 'slo.breach']
    assert len(evs) == 2
    assert {e['rule'] for e in evs} == {'mfu_floor', 'lat_p99'}
    snap = telemetry.snapshot()
    assert snap['counters']['slo.breaches'] == 2
    assert snap['gauges']['slo.breaching'] == 2


def test_slo_rate_rule_needs_two_checks(obs_on):
    c = telemetry.counter('test.slo.tokens')
    wd = slo.SLOWatchdog([slo.SLORule(
        'tok_floor', 'test.slo.tokens', 'rate_min', 1e9)])
    assert wd.check_now() == []     # first check only primes
    c.inc(100)
    breaches = wd.check_now()       # 100 tokens over ~0s << 1e9/s
    assert [b['rule'] for b in breaches] == ['tok_floor']


def test_slo_min_count_suppresses_cold_registry(obs_on):
    telemetry.histogram('test.slo.cold').observe(9.0)
    wd = slo.SLOWatchdog([slo.SLORule(
        'cold', 'test.slo.cold', 'p99_max', 0.001, min_count=5)])
    assert wd.check_now() == []


def test_watchdog_from_flags(obs_on, tmp_path):
    rules_path = str(tmp_path / 'rules.json')
    with open(rules_path, 'w') as f:
        json.dump([{'name': 'r', 'metric': 'g', 'kind': 'gauge_min',
                    'threshold': 1.0}], f)
    assert slo.watchdog_from_flags() is None    # default: no rules
    fluid.set_flags({'FLAGS_slo_rules': '@' + rules_path})
    try:
        wd = slo.watchdog_from_flags()
        assert wd is not None
        assert wd.rules[0].name == 'r'
    finally:
        fluid.set_flags({'FLAGS_slo_rules': ''})


# ---------------------------------------------------------------------------
# perf gate CLI
# ---------------------------------------------------------------------------

def _gate(*argv):
    return subprocess.run([sys.executable, _PERF_GATE] + list(argv),
                          capture_output=True, text=True, cwd=_ROOT)


def test_perf_gate_smoke():
    out = _gate('--smoke')
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'smoke: ok' in out.stdout


def test_perf_gate_real_trajectory_clean():
    out = _gate()
    assert out.returncode == 0, \
        'committed BENCH trajectory must gate clean:\n%s' % out.stdout
    assert 'no regressions' in out.stdout


def test_perf_gate_trips_on_regressed_fixture(tmp_path):
    for n, metrics in ((1, {'mfu': 0.30, 'tokens_per_sec': 1000.0}),
                       (2, {'mfu': 0.21, 'tokens_per_sec': 990.0})):
        with open(str(tmp_path / ('BENCH_r%02d.json' % n)), 'w') as f:
            json.dump({'n': n, 'parsed': metrics}, f)
    out = _gate('--bench-glob', str(tmp_path / 'BENCH_r*.json'))
    assert out.returncode == 1, out.stdout + out.stderr
    assert 'REGRESSION mfu' in out.stdout
    # tokens only dipped 1% — inside tolerance, must not be flagged
    assert 'tokens_per_sec' not in \
        [l.split()[1] for l in out.stdout.splitlines()
         if 'REGRESSION' in l]


def test_perf_gate_candidate_mode(tmp_path):
    cand = str(tmp_path / 'cand.json')
    with open(cand, 'w') as f:
        json.dump({'mfu': 0.29, 'new_metric_per_sec': 5.0}, f)
    ref = str(tmp_path / 'BENCH_r01.json')
    with open(ref, 'w') as f:
        json.dump({'n': 1, 'parsed': {'mfu': 0.30}}, f)
    out = _gate('--candidate', cand, '--bench-glob',
                str(tmp_path / 'BENCH_r*.json'))
    assert out.returncode == 0, out.stdout   # 3% dip inside tolerance


# ---------------------------------------------------------------------------
# bench_suite --quick feed (slow: two real model builds + compiles)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_suite_quick_stamps_gauges():
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, 'tools', 'bench_suite.py'),
         '--quick', '--json', '--model', 'mnist', '--steps', '2'],
        capture_output=True, text=True, cwd=_ROOT, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    rows = json.loads(out.stdout.splitlines()[-1])
    row = rows[0]
    assert row['model'] == 'mnist' and 'error' not in row
    assert row['compile_ms'] > 0
    assert row['hbm_peak'] > 0
    assert 'mfu' in row
    assert 'decode_speedup' not in row   # subprocess extras skipped
