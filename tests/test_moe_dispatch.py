"""MoE top-k capacity dispatch (ops/moe_ops.py): parity with the dense
reference at ample capacity, FLOPs independence of the expert count (the
property that makes expert parallelism scale), capacity dropping, and
the load-balance aux loss."""
import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.parallel.layers import moe_layer

import jax
import jax.numpy as jnp


def _moe_prog(E, k, dispatch, capacity_factor=2.0, S=8, D=16, H=32,
              seed=5, aux_loss=False):
    prog, startup = Program(), Program()
    prog.random_seed = startup.random_seed = seed
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[S, D], dtype='float32',
                              append_batch_size=False)
        out = moe_layer(x, num_experts=E, hidden_size=H, k=k,
                        dispatch=dispatch, capacity_factor=capacity_factor,
                        aux_loss=aux_loss)
        if aux_loss:
            out, aux = out
        loss = fluid.layers.mean(out)
    fetch = [out, loss] + ([aux] if aux_loss else [])
    return prog, startup, fetch


def test_topk_matches_dense_at_ample_capacity():
    """With capacity >= S (no token can be dropped), topk dispatch must
    reproduce the dense top-k-masked combine exactly."""
    S, E, k = 8, 4, 2
    xv = np.random.RandomState(3).rand(S, 16).astype('float32')
    outs = {}
    for mode in ('dense', 'topk'):
        prog, startup, fetch = _moe_prog(
            E, k, mode, capacity_factor=float(E * S), S=S)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            o, l = exe.run(prog, feed={'x': xv},
                           fetch_list=fetch)
        outs[mode] = np.asarray(o)
    np.testing.assert_allclose(outs['topk'], outs['dense'],
                               rtol=1e-5, atol=1e-6)


def test_topk_flops_independent_of_expert_count():
    """Expert compute is E*C*(D*H) with E*C = k*S*cf: doubling E at fixed
    k must NOT double FLOPs (the dense path does exactly that)."""
    S, D, H, k, cf = 32, 64, 128, 2, 1.0

    def flops_for(E, mode):
        def f(x, gate, w_up, w_down):
            from paddle_tpu.ops.moe_ops import (_topk_route,
                                                _dispatch_combine)
            route = _topk_route(gate, k)
            if mode == 'dense':
                h = jax.nn.relu(jnp.einsum('sd,edh->seh', x, w_up))
                return jnp.einsum('seh,ehd,se->sd', h, w_down, route)
            C = max(1, int(math.ceil(S * k * cf / E)))
            disp, comb = _dispatch_combine(route, k, C)
            ein = jnp.einsum('sec,sd->ecd', disp, x)
            h = jax.nn.relu(jnp.einsum('ecd,edh->ech', ein, w_up))
            y = jnp.einsum('ech,ehd->ecd', h, w_down)
            return jnp.einsum('sec,ecd->sd', comb, y)
        args = (jnp.zeros((S, D)), jnp.zeros((S, E)),
                jnp.zeros((E, D, H)), jnp.zeros((E, H, D)))
        comp = jax.jit(f).lower(*args).compile()
        (an,) = comp.cost_analysis() if isinstance(comp.cost_analysis(),
                                                   list) \
            else (comp.cost_analysis(),)
        return an['flops']

    f4, f16 = flops_for(4, 'topk'), flops_for(16, 'topk')
    d4, d16 = flops_for(4, 'dense'), flops_for(16, 'dense')
    assert d16 > 2.5 * d4          # dense scales ~linearly in E
    assert f16 < 1.5 * f4, (f4, f16)   # topk stays ~flat


def test_capacity_dropping_zeroes_overflow_tokens():
    """With capacity 1 and all tokens routed to one expert, only the
    first token (slot-major priority) gets expert output; the rest
    combine to zero."""
    from paddle_tpu.ops.moe_ops import _dispatch_combine
    S, E = 4, 2
    route = np.zeros((S, E), 'float32')
    route[:, 0] = 1.0                     # everyone wants expert 0
    disp, comb = _dispatch_combine(jnp.asarray(route), 1, 1)
    disp = np.asarray(disp)
    assert disp[0, 0, 0] == 1.0
    assert disp[1:].sum() == 0.0          # overflow dropped
    assert np.asarray(comb)[1:].sum() == 0.0


def test_moe_topk_trains_and_drops_loss():
    prog, startup = Program(), Program()
    prog.random_seed = startup.random_seed = 5
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[8, 16], dtype='float32',
                              append_batch_size=False)
        y = fluid.layers.data(name='y', shape=[8, 16], dtype='float32',
                              append_batch_size=False)
        out, aux = moe_layer(x, num_experts=4, hidden_size=32, k=2,
                             aux_loss=True)
        mse = fluid.layers.mean(
            fluid.layers.square_error_cost(out, y))
        loss = fluid.layers.elementwise_add(
            mse, fluid.layers.scale(aux, scale=0.01))
        fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    xv = rng.rand(8, 16).astype('float32')
    yv = np.tanh(xv)
    first = last = None
    for _ in range(60):
        l, a = exe.run(prog, feed={'x': xv, 'y': yv},
                       fetch_list=[loss, aux])
        if first is None:
            first = float(np.asarray(l))
        last = float(np.asarray(l))
    assert np.isfinite(last) and last < 0.5 * first, (first, last)
    # aux = E * sum(f*P): ~1 near balance (f is the hard top-1 count, P
    # the soft mean, so it can sit slightly either side of 1)
    assert 0.5 < float(np.asarray(a)) < 4.0


def test_moe_topk_on_ep_mesh():
    """topk dispatch compiles and runs under the ep axis on the 8-device
    mesh (GSPMD turns the dispatch einsum into collectives)."""
    from paddle_tpu.parallel import DistributedStrategy
    prog, startup = Program(), Program()
    prog.random_seed = startup.random_seed = 5
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[8, 16], dtype='float32',
                              append_batch_size=False)
        out = moe_layer(x, num_experts=4, hidden_size=32, k=2)
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 virtual devices')
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    pe = fluid.ParallelExecutor(use_cuda=True, main_program=prog,
                                scope=scope, devices=jax.devices()[:8],
                                strategy=DistributedStrategy(dp=2, ep=4))
    xv = np.random.RandomState(1).rand(8, 16).astype('float32')
    l1, = pe.run(fetch_list=[loss.name], feed={'x': xv})
    l2, = pe.run(fetch_list=[loss.name], feed={'x': xv})
    assert np.isfinite(np.asarray(l1)).all()
    assert not np.allclose(np.asarray(l1), np.asarray(l2))
