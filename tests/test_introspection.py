"""Memory stats, Predictor inference ABI, graphviz debugger, new
datasets (reference memory/ stats surface, inference/api/
paddle_inference_api.h, debugger.py, dataset/{voc2012,mq2007}.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard


def _train_and_save(tmp_path):
    prog, startup = Program(), Program()
    prog.random_seed = startup.random_seed = 7
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[6], dtype='float32')
        pred = fluid.layers.fc(input=x, size=3, act='softmax',
                               param_attr=fluid.ParamAttr(name='pw'))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = str(tmp_path / 'model')
        fluid.io.save_inference_model(model_dir, ['x'], [pred], exe,
                                      main_program=prog)
        w = np.asarray(scope.find_var('pw'))
    return model_dir, w


def test_predictor_runs_and_matches_direct(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    model_dir, w = _train_and_save(tmp_path)
    pred = create_predictor(Config(model_dir, place=fluid.CPUPlace()))
    assert pred.get_input_names() == ['x']
    xv = np.random.RandomState(0).rand(4, 6).astype('float32')
    out, = pred.run({'x': xv})
    # softmax(x @ w) computed directly
    logits = xv @ w
    ref = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(out, ref, rtol=1e-4)
    # positional input form
    out2, = pred.run([xv])
    np.testing.assert_allclose(out2, out)


def test_predictor_clone_shares_weights(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    model_dir, _ = _train_and_save(tmp_path)
    p1 = create_predictor(Config(model_dir, place=fluid.CPUPlace()))
    p2 = p1.clone()
    assert p1._scope is p2._scope
    xv = np.random.RandomState(1).rand(2, 6).astype('float32')
    np.testing.assert_allclose(p1.run([xv])[0], p2.run([xv])[0])


def test_save_inference_model_prunes_reader_ops(tmp_path):
    """Saving with a reader-produced feed var must cut the 'read' op
    (feeds are graph boundaries in _prune) so the Predictor can feed it
    directly without a live py_reader."""
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        rd = fluid.layers.py_reader(capacity=2, shapes=[[-1, 6]],
                                    dtypes=['float32'], name='prune_r',
                                    use_double_buffer=False)
        x = fluid.layers.read_file(rd)
        pred = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = str(tmp_path / 'm')
        fluid.io.save_inference_model(model_dir, [x.name], [pred], exe,
                                      main_program=prog)
    from paddle_tpu.inference import Config, create_predictor
    p = create_predictor(Config(model_dir, place=fluid.CPUPlace()))
    assert all(op.type != 'read'
               for op in p._program.global_block().ops)
    out, = p.run([np.ones((3, 6), 'float32')])
    assert out.shape == (3, 2)


def test_memory_stats_and_estimate():
    stats = fluid.memory.memory_stats()
    assert stats is None or isinstance(stats, dict)
    assert fluid.memory.memory_allocated() >= 0
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[16], dtype='float32')
        y = fluid.layers.fc(input=x, size=32)
    est = fluid.memory.estimate_program_memory(prog, batch_size=8)
    # fc weight 16x32 fp32 + bias 32 = 2176 bytes of params
    assert est['params'] == 16 * 32 * 4 + 32 * 4
    assert est['activations'] > 0
    assert est['total'] == est['params'] + est['activations']


def test_estimate_peak_memory_stacks_sub_blocks():
    """A While body's live set must be priced ON TOP of the parent live
    set (the sub-block runs while the parent op holds its operands),
    and sub-block references to parent-block vars must resolve up the
    parent chain instead of costing 0."""
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[256], dtype='float32')
        big = fluid.layers.fc(input=x, size=1024, bias_attr=False)
        i = fluid.layers.fill_constant(shape=[1], dtype='int64', value=0)
        n = fluid.layers.fill_constant(shape=[1], dtype='int64', value=2)
        cond = fluid.layers.less_than(x=i, y=n)
        w = fluid.layers.While(cond=cond)
        with w.block():
            # reads the PARENT-block var `big`: cost must resolve via
            # the parent chain (non-zero), stacked on the parent live
            # set that holds `big` across the while op
            inner = fluid.layers.elementwise_add(big, big)
            fluid.layers.increment(x=i, in_place=True)
            fluid.layers.less_than(x=i, y=n, cond=cond)
        out = fluid.layers.elementwise_add(big, big)
        fluid.layers.mean(out)
    peak = fluid.memory.estimate_peak_memory(prog, batch_size=4)
    # `big` (parent, live across the while) + `inner` (sub-block) must
    # BOTH be in the peak: 2 batch-scaled [4, 1024] fp32 tensors plus
    # params; max-over-blocks or 0-cost parent refs would be below it
    big_bytes = 4 * 1024 * 4
    params = 256 * 1024 * 4
    assert peak >= params + 2 * big_bytes
    # amp halves fp32 activation pricing but never params
    peak_amp = fluid.memory.estimate_peak_memory(prog, batch_size=4,
                                                 amp_bf16=True)
    assert params < peak_amp < peak


def test_estimate_peak_memory_recompute_no_double_count():
    """layers.recompute hoists its output into the parent block under
    the SAME name (one buffer in two var tables); the estimator must
    price it once."""
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[256], dtype='float32')
        y = fluid.layers.recompute(
            lambda h: fluid.layers.fc(input=h, size=1024,
                                      bias_attr=False), x)
        fluid.layers.mean(y)
    peak = fluid.memory.estimate_peak_memory(prog, batch_size=4)
    params = 256 * 1024 * 4
    y_bytes = 4 * 1024 * 4
    x_bytes = 4 * 256 * 4
    # one y + one x (+ tiny mean scalar), never two y's
    assert peak <= params + y_bytes + x_bytes + 64


def test_scope_footprint_counts_persistables():
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        fluid.layers.fc(input=x, size=4,
                        param_attr=fluid.ParamAttr(name='fw'),
                        bias_attr=False)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    assert fluid.memory.scope_footprint(scope) >= 4 * 4 * 4


def test_graphviz_dump(tmp_path):
    from paddle_tpu.debugger import draw_block_graphviz, program_to_dot
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        h = fluid.layers.fc(input=x, size=8, act='relu')
        loss = fluid.layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    path = str(tmp_path / 'g.dot')
    draw_block_graphviz(prog.global_block(), path)
    dot = open(path).read()
    assert dot.startswith('digraph')
    assert 'matmul' in dot or 'mul' in dot
    assert 'relu' in dot and '->' in dot
    full = program_to_dot(prog)
    assert 'cluster_block_0' in full


def test_build_strategy_graphviz_knob(tmp_path):
    import jax
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        loss = fluid.layers.mean(fluid.layers.fc(input=x, size=2))
        fluid.optimizer.SGD(0.1).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    bs = fluid.BuildStrategy()
    path = str(tmp_path / 'pe.dot')
    bs.debug_graphviz_path = path
    fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                           main_program=prog, scope=scope,
                           build_strategy=bs,
                           devices=jax.devices()[:1])
    assert os.path.exists(path) and 'digraph' in open(path).read()


def test_voc2012_contract():
    samples = list(fluid.dataset.voc2012.train()())[:4]
    for img, label in samples:
        assert img.shape == (3, 64, 64) and img.dtype == np.float32
        assert label.shape == (64, 64) and label.dtype == np.int32
        classes = set(np.unique(label)) - {255}
        assert classes <= set(range(21))


def test_mq2007_contract():
    pw = list(fluid.dataset.mq2007.train(format='pairwise')())[:50]
    for hi, lo, f1, f2 in pw:
        assert hi > lo
        assert f1.shape == (46,) and f2.shape == (46,)
    lw = list(fluid.dataset.mq2007.train(format='listwise')())[:3]
    for labels, feats in lw:
        assert len(labels) == len(feats)
    pt = list(fluid.dataset.mq2007.test(format='pointwise')())[:10]
    for f, l in pt:
        assert f.shape == (46,) and l in (0, 1, 2)
