"""conv2d / pool2d / batch_norm / layer_norm / dropout / reshape family
(pattern of reference test_conv2d_op.py, test_pool2d_op.py,
test_batch_norm_op.py, test_layer_norm_op.py)."""
import numpy as np

from op_test import OpTest


def np_conv2d(x, w, stride, pad):
    n, c, h, wd = x.shape
    oc, ic, kh, kw = w.shape
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((n, oc, oh, ow), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.tensordot(patch, w, axes=([1, 2, 3],
                                                           [1, 2, 3]))
    return out.astype('float32')


class TestConv2d(OpTest):
    op_type = 'conv2d'

    def test_all(self):
        x = np.random.rand(2, 3, 7, 7).astype('float32')
        w = np.random.rand(4, 3, 3, 3).astype('float32')
        self.inputs = {'Input': x, 'Filter': w}
        self.attrs = {'strides': [2, 2], 'paddings': [1, 1],
                      'dilations': [1, 1], 'groups': 1}
        self.outputs = {'Output': np_conv2d(x, w, 2, 1)}
        self.check_output(atol=1e-3)
        self.check_grad(['Input', 'Filter'], max_relative_error=0.03)


class TestPool2dMax(OpTest):
    op_type = 'pool2d'

    def test_output(self):
        x = np.random.rand(2, 3, 6, 6).astype('float32')
        self.inputs = {'X': x}
        self.attrs = {'pooling_type': 'max', 'ksize': [2, 2],
                      'strides': [2, 2], 'paddings': [0, 0]}
        expect = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
        self.outputs = {'Out': expect}
        self.check_output()


class TestPool2dAvg(OpTest):
    op_type = 'pool2d'

    def test_all(self):
        x = np.random.rand(2, 3, 6, 6).astype('float32')
        self.inputs = {'X': x}
        self.attrs = {'pooling_type': 'avg', 'ksize': [2, 2],
                      'strides': [2, 2], 'paddings': [0, 0]}
        expect = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
        self.outputs = {'Out': expect}
        self.check_output()
        self.check_grad(['X'])


class TestPool2dGlobal(OpTest):
    op_type = 'pool2d'

    def test_output(self):
        x = np.random.rand(2, 3, 5, 5).astype('float32')
        self.inputs = {'X': x}
        self.attrs = {'pooling_type': 'avg', 'ksize': [1, 1],
                      'global_pooling': True}
        self.outputs = {'Out': x.mean(axis=(2, 3), keepdims=True)}
        self.check_output()


class TestBatchNormTrain(OpTest):
    op_type = 'batch_norm'

    def test_output(self):
        np.random.seed(3)
        x = np.random.rand(4, 3, 5, 5).astype('float32') * 2
        scale = np.random.rand(3).astype('float32')
        bias = np.random.rand(3).astype('float32')
        mean = np.zeros(3, dtype='float32')
        var = np.ones(3, dtype='float32')
        eps, momentum = 1e-5, 0.9
        mu = x.mean(axis=(0, 2, 3))
        v = x.var(axis=(0, 2, 3))
        y = ((x - mu.reshape(1, 3, 1, 1))
             / np.sqrt(v.reshape(1, 3, 1, 1) + eps)
             * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1))
        self.inputs = {'X': x, 'Scale': scale, 'Bias': bias,
                       'Mean': mean, 'Variance': var}
        self.attrs = {'epsilon': eps, 'momentum': momentum,
                      'is_test': False}
        self.outputs = {
            'Y': y,
            'MeanOut': mean * momentum + mu * (1 - momentum),
            'VarianceOut': var * momentum + v * (1 - momentum),
            'SavedMean': mu, 'SavedVariance': v,
        }
        self.check_output(atol=2e-4)


class TestLayerNorm(OpTest):
    op_type = 'layer_norm'

    def test_all(self):
        x = np.random.rand(3, 8).astype('float32')
        scale = np.random.rand(8).astype('float32')
        bias = np.random.rand(8).astype('float32')
        eps = 1e-5
        mu = x.mean(axis=1, keepdims=True)
        v = x.var(axis=1, keepdims=True)
        y = (x - mu) / np.sqrt(v + eps) * scale + bias
        self.inputs = {'X': x, 'Scale': scale, 'Bias': bias}
        self.attrs = {'epsilon': eps, 'begin_norm_axis': 1}
        self.outputs = {'Y': y, 'Mean': mu.reshape(3),
                        'Variance': v.reshape(3)}
        self.check_output(atol=2e-4)
        self.check_grad(['X', 'Scale', 'Bias'], output_names='Y',
                        max_relative_error=0.03)


class TestDropoutInfer(OpTest):
    op_type = 'dropout'

    def test_output(self):
        x = np.random.rand(4, 5).astype('float32')
        self.inputs = {'X': x}
        self.attrs = {'dropout_prob': 0.35, 'is_test': True}
        self.outputs = {'Out': x * (1 - 0.35)}
        self.check_output()


class TestReshape(OpTest):
    op_type = 'reshape2'

    def test_output(self):
        x = np.random.rand(2, 3, 4).astype('float32')
        self.inputs = {'X': x}
        self.attrs = {'shape': [2, -1]}
        self.outputs = {'Out': x.reshape(2, 12),
                        'XShape': np.zeros((0, 2, 3, 4), 'float32')}
        self.check_output(no_check_set=('XShape',))


class TestTranspose(OpTest):
    op_type = 'transpose2'

    def test_output(self):
        x = np.random.rand(2, 3, 4).astype('float32')
        self.inputs = {'X': x}
        self.attrs = {'axis': [1, 0, 2]}
        self.outputs = {'Out': x.transpose(1, 0, 2),
                        'XShape': np.zeros((0, 2, 3, 4), 'float32')}
        self.check_output(no_check_set=('XShape',))


class TestSlice(OpTest):
    op_type = 'slice'

    def test_output(self):
        x = np.random.rand(4, 5, 6).astype('float32')
        self.inputs = {'Input': x}
        self.attrs = {'axes': [0, 2], 'starts': [1, 2], 'ends': [3, 6]}
        self.outputs = {'Out': x[1:3, :, 2:6]}
        self.check_output()


class TestOneHot(OpTest):
    op_type = 'one_hot'

    def test_output(self):
        ids = np.random.randint(0, 6, (5, 1)).astype('int32')
        expect = np.zeros((5, 6), dtype='float32')
        expect[np.arange(5), ids.reshape(-1)] = 1.0
        self.inputs = {'X': ids}
        self.attrs = {'depth': 6}
        self.outputs = {'Out': expect}
        self.check_output()


class TestAccuracy(OpTest):
    op_type = 'accuracy'

    def test_output(self):
        idx = np.array([[0, 1], [2, 3], [4, 0], [1, 2]]).astype('int64')
        label = np.array([[1], [5], [4], [0]]).astype('int64')
        # rows 0 and 2 contain the label in topk
        self.inputs = {'Out': idx.astype('float32'), 'Indices': idx,
                       'Label': label}
        self.outputs = {
            'Accuracy': np.asarray(0.5, 'float32'),
            'Correct': np.asarray(2, 'int32'),
            'Total': np.asarray(4, 'int32'),
        }
        self.check_output()


class TestSigmoidCrossEntropyWithLogits(OpTest):
    op_type = 'sigmoid_cross_entropy_with_logits'

    def test_all(self):
        # seeded: unseeded draws occasionally land a logit near 0 where
        # the finite-difference grad check's 2% tolerance is marginal
        rng = np.random.RandomState(11)
        x = (rng.rand(4, 5).astype('float32') - 0.5) * 4
        label = rng.rand(4, 5).astype('float32')
        expect = np.maximum(x, 0) - x * label + np.log1p(np.exp(-np.abs(x)))
        self.inputs = {'X': x, 'Label': label}
        self.outputs = {'Out': expect}
        self.check_output(atol=1e-4)
        self.check_grad(['X'], max_relative_error=0.02)


class TestHuberLoss(OpTest):
    op_type = 'huber_loss'

    def test_output(self):
        x = np.random.rand(5, 1).astype('float32')
        y = np.random.rand(5, 1).astype('float32')
        delta = 0.5
        r = y - x
        a = np.abs(r)
        loss = np.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
        self.inputs = {'X': x, 'Y': y}
        self.attrs = {'delta': delta}
        self.outputs = {'Out': loss.astype('float32'), 'Residual': r}
        self.check_output(no_check_set=('Residual',))
