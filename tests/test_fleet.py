"""Fleet serving resilience: FleetRouter over ReplicaServer replicas.

The acceptance triangle (ISSUE: fleet serving resilience):

1. kill -9 one replica mid-stream -> every stream still completes and
   every token stream is BIT-exact vs the solo reference (failover
   re-prefills from the accumulated prefix; greedy decode makes the
   continuation identical);
2. a rolling param-version deploy across 2 live replicas drops zero
   streams and converges every replica to the new version's digests;
3. sustained overload trips admission control (typed OverloadError +
   fleet.shed) BEFORE the TTFT SLO rule breaches.

Plus the PR's satellites: Supervisor restart-budget reset after
healthy uptime, ServingEngine drain-timeout escalation and the
submit/cancel-during-drain races, and the ReplicaServer wire surface.

Replica processes for the kill test are real subprocesses
(tools/serve_replica.py) — SIGKILL needs a pid; everything else runs
in-process (ReplicaServer threads) to keep tier-1 wall-clock down.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import fleet_worker as fw
from paddle_tpu.distributed import wire
from paddle_tpu.integrity import crc32
from paddle_tpu.serving import (FleetRouter, LMServer, OverloadError,
                                ReplicaServer, ServingEngine)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SERVE_REPLICA = os.path.join(_ROOT, 'tools', 'serve_replica.py')
GEN = 12


def _free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(('127.0.0.1', 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.fixture(scope='module')
def model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp('fleet_model'))
    fw.build_model(d)
    return d


@pytest.fixture(scope='module')
def ref_dec(model_dir):
    """In-process solo-decode reference over the same saved bytes."""
    from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
    pred = AnalysisPredictor(AnalysisConfig(model_dir))
    return pred.prepare_decoding(slots=4, prefill_batch=1)


def _launch_replicas(model_dir, n, slots=4, extra_env=None):
    """extra_env: {replica index: {env overrides}} — how a single
    replica gets a FaultPlan while its peers run clean."""
    eps, procs = [], []
    for i, port in enumerate(_free_ports(n)):
        ep = '127.0.0.1:%d' % port
        env = dict(os.environ, SERVE_MODEL_DIR=model_dir,
                   SERVE_ENDPOINT=ep, SERVE_SLOTS=str(slots),
                   SERVE_WORKERS='1')
        env.pop('XLA_FLAGS', None)
        env.pop('JAX_PLATFORMS', None)
        env.update((extra_env or {}).get(i, {}))
        procs.append(subprocess.Popen(
            [sys.executable, _SERVE_REPLICA], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        eps.append(ep)
    return procs, eps


def _cleanup_replicas(procs, eps):
    for ep in eps:
        host, port = ep.rsplit(':', 1)
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=2.0) as s:
                wire.write_msg(s, wire.COMPLETE, {'seq': 0})
                wire.read_msg(s)
        except OSError:
            pass
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)


class _InprocReplica(object):
    """ReplicaServer over an in-process LMServer, serving on a daemon
    thread — the wire surface without a subprocess."""

    def __init__(self, srv):
        self.rs = ReplicaServer(srv, '127.0.0.1:0')
        self.ep = '127.0.0.1:%d' % self.rs.port
        self._t = threading.Thread(target=self.rs.serve_forever,
                                   daemon=True)
        self._t.start()

    def stop(self):
        self.rs.shutdown()
        self._t.join(timeout=10)


# -- acceptance 1: replica kill-9, bit-exact failover ----------------------

@pytest.mark.timeout(600)
def test_fleet_replica_kill_failover_bit_exact(model_dir, ref_dec):
    procs, eps = _launch_replicas(model_dir, 2)
    router = FleetRouter(eps, poll_secs=0.005, probe_secs=0.05,
                         probe_fail_threshold=2)
    router.start()
    try:
        router.wait_healthy(timeout=240.0)
        work = fw.make_prompts(0, 30, GEN)
        reqs = [router.submit(p, max_new_tokens=GEN, session=s)
                for p, s in work]
        # kill the moment a replica is provably mid-stream: >= 2
        # active streams that already produced tokens
        victim_ep, deadline = None, time.monotonic() + 180
        while victim_ep is None and time.monotonic() < deadline:
            with router._mu:
                for ep, rep in router._reps.items():
                    if len([r for r in rep.active.values()
                            if r.tokens]) >= 2:
                        victim_ep = ep
                        break
            time.sleep(0.002)
        assert victim_ep, 'no replica reached 2 live streams'
        procs[eps.index(victim_ep)].kill()        # SIGKILL
        for r in reqs:
            assert r.wait(timeout=240.0), (r.id, r.state)
        assert router.stats()['failovers'] >= 1
        for r, (p, _s) in zip(reqs, work):
            assert r.state == 'DONE'
            assert r.result() == ref_dec.generate(p, GEN)
    finally:
        router.stop()
        _cleanup_replicas(procs, eps)


# -- acceptance 2: rolling deploy, zero drops, digest convergence ----------

@pytest.mark.timeout(600)
def test_fleet_rolling_deploy_zero_drop(model_dir):
    from paddle_tpu.distributed.param_service import ParameterService
    from paddle_tpu.distributed.rpc import PSClient, PSServer

    srv_a = LMServer(model_dir, slots=4)
    srv_b = LMServer(model_dir, slots=4)
    # the pserver hosts the model's own params from a test-owned dict:
    # mutating the dict + closing a round IS the new trained version
    params = {n: np.copy(np.asarray(
                  srv_a._decode._weight_scope.find_var(n)))
              for n in srv_a._decode.param_names()}
    svc = ParameterService(num_trainers=1, sync_mode=True,
                           get_param=lambda n: params[n],
                           run_round=lambda merged: None,
                           rpc_deadline=60.0,
                           param_names=sorted(params))
    ps = PSServer('127.0.0.1:0', svc)
    pst = threading.Thread(target=ps.serve_forever, daemon=True)
    pst.start()
    ps_eps = ['127.0.0.1:%d' % ps.port]
    srv_a.enable_refresh(ps_eps, subscriber_id=101, poll_secs=0.05,
                         paused=True)
    srv_b.enable_refresh(ps_eps, subscriber_id=102, poll_secs=0.05,
                         paused=True)
    ra, rb = _InprocReplica(srv_a), _InprocReplica(srv_b)
    router = FleetRouter([ra.ep, rb.ep], poll_secs=0.005,
                         probe_secs=0.05)
    reqs, stop_traffic = [], threading.Event()

    def traffic():
        rng = np.random.RandomState(7)
        while not stop_traffic.is_set():
            prompt = [int(t) for t in rng.randint(1, fw.CFG.vocab, 3)]
            reqs.append(router.submit(prompt, max_new_tokens=8))
            time.sleep(0.01)

    t = threading.Thread(target=traffic, daemon=True)
    try:
        router.start()
        router.wait_healthy(timeout=120.0)
        t.start()
        time.sleep(0.3)           # streams live on both replicas
        for n in list(params):
            params[n] = params[n] + np.float32(0.01)
        svc.on_send_var('r@GRAD', 0, np.zeros(1, 'f4'), seq=('t', 1))
        svc.on_batch_barrier(0, seq=('t', 2))     # publish version 1
        out = router.rolling_deploy(min_version=1)
        assert out == {ra.ep: 1, rb.ep: 1}
        time.sleep(0.2)           # post-deploy traffic too
        stop_traffic.set()
        t.join(timeout=10)
        assert reqs
        for r in reqs:
            assert r.wait(timeout=240.0), (r.id, r.state)
            assert r.state == 'DONE'          # zero drops
            assert len(r.tokens) == 8
        want = {n: crc32(wire._payload_of(
                    np.ascontiguousarray(params[n]))[1])
                for n in params}
        assert srv_a.param_digests() == want
        assert srv_b.param_digests() == want
        st = router.stats()
        assert st['deploys'] == 1
        assert st['shed'] == 0 and st['failed'] == 0
        assert {v['param_version']
                for v in st['replicas'].values()} == {1}
    finally:
        stop_traffic.set()
        t.join(timeout=10) if t.is_alive() else None
        router.stop()
        ra.stop()
        rb.stop()
        srv_a.close(drain=False)
        srv_b.close(drain=False)
        cli = PSClient('127.0.0.1:%d' % ps.port, trainer_id=0)
        cli.complete()
        cli.close()
        pst.join(timeout=10)


# -- acceptance 3: admission control sheds before the TTFT SLO -------------

@pytest.mark.timeout(600)
def test_fleet_admission_control_sheds_before_slo(model_dir):
    from paddle_tpu.obs import telemetry
    from paddle_tpu.obs.slo import SLORule

    srv = LMServer(model_dir, slots=2)
    rep = _InprocReplica(srv)
    router = FleetRouter(
        [rep.ep], poll_secs=0.005, probe_secs=0.02,
        shed_consecutive=1,
        admission_rules=[{'name': 'fleet_backlog',
                          'metric': 'fleet.queue_depth',
                          'kind': 'gauge_max', 'threshold': 6}])
    telemetry.enable()
    try:
        telemetry.reset()
        router.start()
        router.wait_healthy(timeout=120.0)
        rng = np.random.RandomState(9)
        accepted, sheds = [], 0
        for _ in range(60):
            prompt = [int(v) for v in rng.randint(1, fw.CFG.vocab, 3)]
            try:
                accepted.append(router.submit(prompt,
                                              max_new_tokens=6))
            except OverloadError:
                sheds += 1
            time.sleep(0.005)
        assert sheds > 0, router.stats()
        assert accepted
        st = router.stats()
        assert st['shed'] == sheds
        snap = telemetry.snapshot()
        assert snap['counters'].get('fleet.shed') == sheds
        # shedding protected the accepted streams: all complete, and
        # the TTFT SLO rule the shed pre-empts never breaches
        for r in accepted:
            assert r.wait(timeout=240.0), (r.id, r.state)
            assert r.state == 'DONE'
        rule = SLORule('ttft_slo', 'fleet.ttft', 'p99_max', 10.0)
        out = rule.evaluate(router.admission_snapshot())
        assert out is not None and not out[1], out
    finally:
        telemetry.disable(final_flush=False)
        telemetry.reset()
        router.stop()
        rep.stop()
        srv.close(drain=False)


# -- satellite: ReplicaServer wire surface ---------------------------------

@pytest.mark.timeout(600)
def test_replica_server_wire_roundtrip(model_dir, ref_dec):
    srv = LMServer(model_dir, slots=2)
    rep = _InprocReplica(srv)
    sock = socket.create_connection(('127.0.0.1', rep.rs.port),
                                    timeout=10)
    seq = [0]

    def call(mt, meta=None, value=None):
        seq[0] += 1
        m = dict(meta or {}, seq=seq[0])
        wire.write_msg(sock, mt, m, value)
        rt, rmeta, _ = wire.read_msg(sock)
        assert rmeta['seq'] == seq[0]     # every reply echoes the seq
        return rt, rmeta

    try:
        rt, h = call(wire.SRV_HEALTH, {})
        assert rt == wire.REPLY_OK
        assert h['capacity'] == 2
        assert h['max_len'] == fw.CFG.max_len
        assert h['draining'] is False
        rt, h2 = call(wire.SRV_HEALTH, {'digests': True})
        assert h2['digests'] == srv.param_digests()

        prompt = [3, 1, 4]
        rt, _m = call(wire.SRV_SUBMIT, {'rid': 'r1', 'mnt': 6},
                      np.asarray(prompt, np.int64))
        assert rt == wire.REPLY_OK
        deadline = time.monotonic() + 120
        while True:
            rt, pr = call(wire.SRV_POLL, {'rids': ['r1', 'ghost']})
            assert pr['streams']['ghost'] == {'state': 'UNKNOWN',
                                              'tokens': []}
            if pr['streams']['r1']['state'] == 'DONE':
                break
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert pr['streams']['r1']['tokens'] == \
            ref_dec.generate(prompt, 6)

        # drain fence: submits rejected RETRYABLY while draining
        rt, _m = call(wire.SRV_DRAIN, {'on': True})
        assert rt == wire.REPLY_OK
        rt, err = call(wire.SRV_SUBMIT, {'rid': 'r2', 'mnt': 2},
                       np.asarray([5], np.int64))
        assert rt == wire.REPLY_ERR and err['retryable'] is True
        rt, _m = call(wire.SRV_DRAIN, {'on': False})

        # cancel mid-stream: terminal state, partial tokens kept
        rt, _m = call(wire.SRV_SUBMIT, {'rid': 'r3', 'mnt': 10 ** 6},
                      np.asarray([2, 6], np.int64))
        assert rt == wire.REPLY_OK
        while True:
            rt, pr = call(wire.SRV_POLL, {'rids': ['r3']})
            if pr['streams']['r3']['tokens']:
                break
            assert time.monotonic() < deadline
            time.sleep(0.01)
        rt, _m = call(wire.SRV_CANCEL, {'rid': 'r3'})
        assert rt == wire.REPLY_OK
        while pr['streams']['r3']['state'] != 'CANCELLED':
            rt, pr = call(wire.SRV_POLL, {'rids': ['r3']})
            assert time.monotonic() < deadline
            time.sleep(0.01)

        # no subscriber attached: refresh is a NON-retryable error
        rt, err = call(wire.SRV_REFRESH, {})
        assert rt == wire.REPLY_ERR and err['retryable'] is False
        # a message type the replica does not serve
        rt, err = call(wire.GET_VAR, {'name': 'w'})
        assert rt == wire.REPLY_ERR and err['retryable'] is False
    finally:
        sock.close()
        rep.stop()
        srv.close(drain=False)


# -- satellite: SRV_SUBMIT prio/deadline meta across both encodings --------

@pytest.mark.timeout(600)
def test_replica_submit_prio_deadline_meta_roundtrip(model_dir):
    """priority + deadline_ms must survive the SRV_SUBMIT hop under
    BOTH meta encodings (JSON and binary-meta v3), and a peer that
    predates the keys (meta simply lacks them) must decode to the
    defaults — tier 0, no deadline — not an error."""
    srv = LMServer(model_dir, slots=2)
    seen = []
    orig_submit = srv.submit

    def spy(prompt, **kw):
        seen.append(dict(kw))
        return orig_submit(prompt, **kw)

    srv.submit = spy
    rep = _InprocReplica(srv)
    try:
        for bmeta in (False, True):
            sock = socket.create_connection(
                ('127.0.0.1', rep.rs.port), timeout=10)
            if bmeta:
                wire._mark_peer_bmeta(sock)    # force bmeta v3 framing
            seq = [0]

            def call(mt, meta=None, value=None, _sock=sock, _seq=seq):
                _seq[0] += 1
                m = dict(meta or {}, seq=_seq[0])
                wire.write_msg(_sock, mt, m, value)
                rt, rmeta, _ = wire.read_msg(_sock)
                assert rmeta['seq'] == _seq[0]
                return rt, rmeta

            try:
                tag = 'b' if bmeta else 'j'
                rt, _m = call(wire.SRV_SUBMIT,
                              {'rid': tag + '1', 'mnt': 4, 'prio': 2,
                               'deadline_ms': 60000.0},
                              np.asarray([3, 1, 4], np.int64))
                assert rt == wire.REPLY_OK
                assert seen[-1]['priority'] == 2
                assert seen[-1]['deadline_ms'] == pytest.approx(60000.0)

                # old-peer meta: absent keys mean defaults, not errors
                rt, _m = call(wire.SRV_SUBMIT,
                              {'rid': tag + '2', 'mnt': 2},
                              np.asarray([5], np.int64))
                assert rt == wire.REPLY_OK
                assert seen[-1]['priority'] == 0
                assert seen[-1]['deadline_ms'] is None

                # a near-spent deadline expires inside the engine and
                # the typed failure class crosses SRV_POLL back out
                rt, _m = call(wire.SRV_SUBMIT,
                              {'rid': tag + '3', 'mnt': 10 ** 6,
                               'deadline_ms': 1.0},
                              np.asarray([2, 6], np.int64))
                assert rt == wire.REPLY_OK
                deadline = time.monotonic() + 120
                while True:
                    rt, pr = call(wire.SRV_POLL,
                                  {'rids': [tag + '3']})
                    st = pr['streams'][tag + '3']
                    if st['state'] in ('DONE', 'FAILED', 'CANCELLED'):
                        break
                    assert time.monotonic() < deadline
                    time.sleep(0.01)
                assert st['state'] == 'FAILED'
                assert 'DeadlineExceeded' in st['error']
            finally:
                sock.close()
    finally:
        rep.stop()
        srv.close(drain=False)


# -- satellite: progress watchdog gray-marks a stalled replica -------------

@pytest.mark.timeout(600)
def test_fleet_watchdog_fails_over_stalled_replica_bit_exact(
        model_dir, ref_dec):
    """Gray failure, not fail-stop: replica0's data path freezes for
    25s mid-burst (FaultPlan stall on its 2nd inbound SRV_POLL) while
    its health probes keep answering. The progress watchdog must
    gray-mark it, interrupt the wedged connection, and fail its streams
    over — every stream completing bit-exact vs the solo reference."""
    plan = json.dumps({'rules': [{'when': 'recv', 'type': 'SRV_POLL',
                                  'nth': 2, 'action': 'stall',
                                  'secs': 25.0}]})
    from paddle_tpu import flags
    procs, eps = _launch_replicas(
        model_dir, 2, extra_env={0: {'FLAGS_fault_plan': plan}})
    router = None
    old_timeout = flags.get_flag('fleet_progress_timeout_secs')
    try:
        work = fw.make_prompts(3, 8, GEN)
        # warm both replicas over direct connections (SRV_SUBMIT +
        # SRV_HEALTH only): the cold jit compile happens before the
        # watchdog is armed, and the stall rule's SRV_POLL count
        # survives untouched into the measured burst
        for ep in eps:
            fw._warm_replica(ep, work[0][0], GEN)
        flags.set_flags({'FLAGS_fleet_progress_timeout_secs': 2.5})
        router = FleetRouter(eps, poll_secs=0.005, probe_secs=0.05,
                             probe_fail_threshold=2)
        router.start()
        router.wait_healthy(timeout=240.0)
        reqs = [router.submit(p, max_new_tokens=GEN, session=s)
                for p, s in work]
        for r in reqs:
            assert r.wait(timeout=240.0), (r.id, r.state)
        st = router.stats()
        assert st['gray_marks'] >= 1, st
        for r, (p, _s) in zip(reqs, work):
            assert r.state == 'DONE'
            assert np.array_equal(r.result(), ref_dec.generate(p, GEN))
    finally:
        flags.set_flags(
            {'FLAGS_fleet_progress_timeout_secs': old_timeout})
        if router is not None:
            router.stop()
        _cleanup_replicas(procs, eps)


# -- satellite: supervisor restart-budget reset ----------------------------

def test_supervisor_budget_reset_after_healthy_uptime(tmp_path):
    from paddle_tpu.distributed.supervisor import Supervisor
    script = 'import time, sys; time.sleep(0.7); sys.exit(1)'
    sup = Supervisor(max_restarts=1, backoff=0.05, healthy_secs=0.5,
                     log_dir=str(tmp_path))
    sup.add_role('r', [sys.executable, '-c', script])
    sup.start()
    try:
        # budget is 1, but every crash follows >= healthy_secs of
        # uptime, so the budget keeps resetting and the LIFETIME count
        # climbs past it
        deadline = time.monotonic() + 60
        while sup.restarts['r'] < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sup.restarts['r'] >= 2
        assert any('budget reset' in e[2] for e in sup.events)
        assert sup.states()['r'] != 'failed'
    finally:
        sup.stop()


def test_supervisor_budget_still_bounds_crash_loops(tmp_path):
    from paddle_tpu.distributed.supervisor import Supervisor
    sup = Supervisor(max_restarts=1, backoff=0.05, healthy_secs=0.5,
                     log_dir=str(tmp_path))
    sup.add_role('r', [sys.executable, '-c',
                       'import sys; sys.exit(1)'])
    sup.start()
    try:
        states = sup.wait(timeout=60)
        assert states['r'] == 'failed'
        assert sup.restarts['r'] == 1     # instant crashes: no reset
        assert not any('budget reset' in e[2] for e in sup.events)
    finally:
        sup.stop()


# -- satellite: engine drain timeout + drain races -------------------------

@pytest.fixture()
def engine_dec(model_dir):
    from paddle_tpu.inference import AnalysisConfig, AnalysisPredictor
    pred = AnalysisPredictor(AnalysisConfig(model_dir))
    return pred.prepare_decoding(slots=2, prefill_batch=1)


def _wait_tokens(req, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not req.tokens:
        assert time.monotonic() < deadline, req.state
        time.sleep(0.005)


@pytest.mark.timeout(600)
def test_engine_drain_timeout_escalates_to_cancel(engine_dec):
    eng = ServingEngine(engine_dec).start()
    req = eng.submit([1, 2, 3], max_new_tokens=10 ** 9)
    _wait_tokens(req)
    t0 = time.monotonic()
    clean = eng.stop(drain=True, timeout=0.5)
    took = time.monotonic() - t0
    assert clean is False          # the escalation fired
    assert took < 30.0             # ... instead of hanging forever
    assert req.state == 'CANCELLED'
    assert req.tokens              # partial stream stays readable


@pytest.mark.timeout(600)
def test_engine_submit_during_drain_rejected(engine_dec):
    eng = ServingEngine(engine_dec).start()
    req = eng.submit([1, 2], max_new_tokens=10 ** 9)
    _wait_tokens(req)
    stopper = threading.Thread(
        target=lambda: eng.stop(drain=True, timeout=5.0), daemon=True)
    stopper.start()
    time.sleep(0.2)                # stop() flipped _accepting first
    with pytest.raises(RuntimeError, match='draining'):
        eng.submit([3], max_new_tokens=2)
    stopper.join(timeout=60.0)
    assert not stopper.is_alive()
    assert req.state == 'CANCELLED'


@pytest.mark.timeout(600)
def test_engine_cancel_during_drain_completes_promptly(engine_dec):
    eng = ServingEngine(engine_dec).start()
    req = eng.submit([1, 2], max_new_tokens=10 ** 9)
    _wait_tokens(req)
    result = {}

    def stopper():
        result['clean'] = eng.stop(drain=True, timeout=120.0)

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    time.sleep(0.2)
    eng.cancel(req)                # unblocks the drain immediately
    t.join(timeout=60.0)
    assert not t.is_alive()
    assert result['clean'] is True
    assert req.state == 'CANCELLED'
    assert req.tokens
