"""CSP channels (paddle_tpu/channels.py; reference concurrency ops) —
buffered/unbuffered semantics, close contract, Select, and a
producer/consumer pipeline around Executor.run."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.channels import Channel, ChannelClosed, Select


def test_buffered_send_recv_order():
    ch = fluid.make_channel(capacity=4)
    for i in range(4):
        ch.send(i)
    assert [ch.recv() for _ in range(4)] == [0, 1, 2, 3]


def test_unbuffered_rendezvous():
    ch = Channel(capacity=0)
    got = []

    def sender():
        ch.send('x')
        got.append('sent')
    t = threading.Thread(target=sender)
    t.start()
    time.sleep(0.1)
    assert not got            # send blocks until a receiver arrives
    assert ch.recv() == 'x'
    t.join(timeout=5)
    assert got == ['sent']


def test_close_drains_then_raises():
    ch = Channel(capacity=3)
    ch.send(1)
    ch.send(2)
    ch.close()
    assert ch.recv() == 1 and ch.recv() == 2
    with pytest.raises(ChannelClosed):
        ch.recv()
    with pytest.raises(ChannelClosed):
        ch.send(3)


def test_range_iteration():
    ch = Channel(capacity=8)
    for i in range(5):
        ch.send(i)
    ch.close()
    assert list(ch) == [0, 1, 2, 3, 4]


def test_select_commits_to_one_ready_case():
    a, b = Channel(capacity=1), Channel(capacity=1)
    b.send('from_b')
    fired = []
    with Select() as sel:
        sel.case_recv(a, lambda v: fired.append(('a', v)))
        sel.case_recv(b, lambda v: fired.append(('b', v)))
    assert fired == [('b', 'from_b')]
    # a untouched
    ok, _ = a.poll()
    assert not ok


def test_select_default():
    a = Channel(capacity=1)
    fired = []
    with Select() as sel:
        sel.case_recv(a, lambda v: fired.append(v))
        sel.default(lambda: fired.append('none'))
    assert fired == ['none']


def test_close_on_full_buffer_does_not_block():
    ch = fluid.make_channel(capacity=1)
    ch.send(1)
    t = threading.Thread(target=ch.close)
    t.start()
    t.join(timeout=2)
    assert not t.is_alive()          # close() must never block
    assert ch.recv() == 1            # buffered value still drains
    with pytest.raises(ChannelClosed):
        ch.recv()


def test_timed_out_recv_leaves_no_stale_ticket():
    ch = Channel(capacity=0)
    with pytest.raises(TimeoutError):
        ch.recv(timeout=0.1)
    # a later send must still block (no phantom receiver)
    with pytest.raises(TimeoutError):
        ch.send('x', timeout=0.2)


def test_all_blocked_senders_wake_on_close():
    ch = Channel(capacity=0)
    errs = []

    def sender():
        try:
            ch.send('v')
        except ChannelClosed:
            errs.append('closed')
    threads = [threading.Thread(target=sender) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    ch.close()
    for t in threads:
        t.join(timeout=2)
    assert not any(t.is_alive() for t in threads)
    assert errs == ['closed'] * 3


def test_select_send_respects_rendezvous():
    ch = Channel(capacity=0)
    fired = []
    with Select() as sel:
        sel.case_send(ch, 'v', lambda: fired.append('sent'))
        sel.default(lambda: fired.append('none'))
    assert fired == ['none']         # no receiver -> default, not send


def test_channel_pipeline_around_executor():
    """The host-side role channels keep on TPU: a producer thread feeds
    batches to a consumer driving Executor.run."""
    from paddle_tpu.framework import Program, program_guard
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        x = fluid.layers.data(name='x', shape=[4], dtype='float32')
        y = fluid.layers.data(name='y', shape=[1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    ch = Channel(capacity=2)
    rng = np.random.RandomState(0)
    w = rng.randn(4, 1).astype('float32')

    def producer():
        for _ in range(12):
            xb = rng.randn(8, 4).astype('float32')
            ch.send((xb, xb @ w))
        ch.close()
    t = threading.Thread(target=producer)
    t.start()
    losses = [float(np.asarray(exe.run(
        prog, feed={'x': xb, 'y': yb}, fetch_list=[loss])[0]))
        for xb, yb in ch]
    t.join(timeout=10)
    assert len(losses) == 12 and losses[-1] < losses[0]
