"""Inference engine: the Predictor ABI over saved inference models.

Capability analog of the reference inference API —
paddle/fluid/inference/api/paddle_inference_api.h (PaddlePredictor,
NativeConfig, CreatePaddlePredictor) — redesigned for the XLA execution
model: a Predictor owns a private Scope with the loaded weights resident
on device, the pruned inference Program compiles ONCE per fed batch
shape through the executor's whole-block jit cache, and clone() shares
the weight scope between predictors (the reference's
PaddlePredictor::Clone contract) so serving threads don't duplicate HBM.

The reference's TensorRT/analysis sub-engines are N/A by design: XLA is
the graph optimizer here.
"""
from __future__ import annotations

import numpy as np

from . import io as io_mod
from .executor import Executor, Scope, TPUPlace, scope_guard

__all__ = ['Config', 'Predictor', 'create_predictor',
           'create_paddle_predictor', 'AnalysisConfig',
           'AnalysisPredictor', 'create_analysis_predictor']


class Config(object):
    """(reference NativeConfig) model_dir holds a save_inference_model
    artifact; model_filename/params_filename follow io.py's layout."""

    def __init__(self, model_dir, model_filename=None,
                 params_filename=None, place=None):
        self.model_dir = model_dir
        self.model_filename = model_filename
        self.params_filename = params_filename
        self.place = place


class Predictor(object):
    def __init__(self, config, _clone_of=None):
        self._config = config
        self._place = config.place if config.place is not None \
            else TPUPlace()
        self._exe = Executor(self._place)
        if _clone_of is not None:
            # clone from memory (reference PaddlePredictor::Clone is
            # independent of the model directory): share the weight
            # scope, copy the program so compile caches stay per-clone
            self._scope = _clone_of._scope
            self._program = _clone_of._program.clone(for_test=True)
            self._feed_names = list(_clone_of._feed_names)
            self._fetch_vars = [
                self._program.global_block().var(v.name)
                for v in _clone_of._fetch_vars]
        else:
            self._scope = Scope()
            with scope_guard(self._scope):
                (self._program, self._feed_names,
                 self._fetch_vars) = io_mod.load_inference_model(
                    config.model_dir, self._exe,
                    model_filename=config.model_filename,
                    params_filename=config.params_filename)
        self._program._is_test = True

    # -- reference PaddlePredictor surface ---------------------------------
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [v.name for v in self._fetch_vars]

    def run(self, inputs, return_numpy=True):
        """inputs: dict name->array, or list matching get_input_names()
        order. Returns list of np.ndarray outputs — or, with
        return_numpy=False, device arrays without a host sync (the
        async serving/throughput path: dispatches pipeline, and the
        caller fetches when it actually needs values)."""
        if not isinstance(inputs, dict):
            if len(inputs) != len(self._feed_names):
                raise ValueError(
                    'predictor expects %d inputs %s, got %d'
                    % (len(self._feed_names), self._feed_names,
                       len(inputs)))
            inputs = dict(zip(self._feed_names, inputs))
        else:
            # validate the dict against the model ABI up front: the
            # executor would only notice a missing feed deep inside
            # compilation, and would silently ignore an unknown one
            unknown = sorted(set(inputs) - set(self._feed_names))
            missing = sorted(set(self._feed_names) - set(inputs))
            if unknown or missing:
                parts = []
                if unknown:
                    parts.append('unknown input name(s) %s' % unknown)
                if missing:
                    parts.append('missing input name(s) %s' % missing)
                raise ValueError(
                    '%s — this model\'s inputs are get_input_names() '
                    '= %s' % ('; '.join(parts), self._feed_names))
        # scope= kwarg, NOT scope_guard: run() must be safe from serving
        # threads, and the guard swaps a process-global
        outs = self._exe.run(self._program, feed=inputs,
                             fetch_list=self._fetch_vars,
                             scope=self._scope,
                             return_numpy=return_numpy)
        if not return_numpy:
            return outs
        return [np.asarray(o) for o in outs]

    def clone(self):
        """A predictor sharing this one's weights (device arrays are
        shared through the common Scope; programs/compile caches are
        per-clone). Works from memory — the model dir may be gone."""
        return Predictor(self._config, _clone_of=self)


def create_predictor(config):
    return Predictor(config)


# reference CreatePaddlePredictor spelling
create_paddle_predictor = create_predictor


class AnalysisConfig(Config):
    """(reference contrib AnalysisConfig / analysis_predictor.cc) —
    Config plus IR-optimization switches consumed by
    AnalysisPredictor."""

    def __init__(self, model_dir, model_filename=None,
                 params_filename=None, place=None, ir_optim=True):
        super(AnalysisConfig, self).__init__(
            model_dir, model_filename=model_filename,
            params_filename=params_filename, place=place)
        self.ir_optim = ir_optim

    def switch_ir_optim(self, flag=True):
        self.ir_optim = flag
        return self


class AnalysisPredictor(Predictor):
    """Predictor that runs offline graph rewrites on the loaded program
    before serving (reference inference/api/analysis_predictor.cc runs
    the ir fusion passes — fc_fuse, conv+bn, ... — before Prepare).
    Here the rewrite set is the InferenceTranspiler's batch-norm
    folding; elementwise/activation fusion is XLA's job at JIT time, so
    those reference passes have no offline analog by design."""

    def __init__(self, config, _clone_of=None):
        super(AnalysisPredictor, self).__init__(config, _clone_of=_clone_of)
        if _clone_of is None and getattr(config, 'ir_optim', True):
            from .transpiler import InferenceTranspiler
            InferenceTranspiler().transpile(
                self._program, self._place, scope=self._scope)

    def clone(self):
        return AnalysisPredictor(self._config, _clone_of=self)

    def prepare_decoding(self, slots=None, prefill_batch=None,
                         paged=False, page_tokens=None, kv_pages=None,
                         prefill_chunk=None, speculative=False,
                         spec_k=None, draft_layers=None,
                         draft_predictor=None, mesh=None):
        """Transpile the loaded LM into the KV-cached prefill + decode
        pair and return a serving.DecodePredictor over this predictor's
        weight scope (see paddle_tpu/serving/decode.py). paged=True
        returns a serving.PagedDecodePredictor instead — page-pool
        cache with copy-on-write prefix sharing and chunked prefill
        (serving/paged.py; page_tokens / kv_pages / prefill_chunk
        default from FLAGS_serving_*). speculative=True (implies paged)
        returns a serving.SpeculativeDecodePredictor: draft/verify
        greedy speculation with bit-exact acceptance
        (serving/speculative.py; spec_k / draft_layers default from
        FLAGS_spec_*; draft_predictor supplies an explicit smaller
        draft LM instead of the layer-truncated self-draft). mesh makes
        every decode/prefill/verify program ONE GSPMD SPMD program over
        a device mesh ('tp=2' / MeshConfig / jax Mesh; None = read
        FLAGS_serve_mesh_shape, '' = single-chip) — greedy decode stays
        bit-exact vs single-chip (serving/mesh.py). Raises
        transpiler.DecodeTranspileError if the program is not a
        recognizable decoder-only LM."""
        if speculative:
            from .serving import SpeculativeDecodePredictor
            return SpeculativeDecodePredictor(
                self, slots=slots, spec_k=spec_k,
                draft_layers=draft_layers,
                draft_predictor=draft_predictor,
                page_tokens=page_tokens, kv_pages=kv_pages,
                prefill_chunk=prefill_chunk, mesh=mesh)
        if paged:
            from .serving import PagedDecodePredictor
            return PagedDecodePredictor(self, slots=slots,
                                        page_tokens=page_tokens,
                                        kv_pages=kv_pages,
                                        prefill_chunk=prefill_chunk,
                                        mesh=mesh)
        from .serving import DecodePredictor
        return DecodePredictor(self, slots=slots,
                               prefill_batch=prefill_batch, mesh=mesh)


def create_analysis_predictor(config):
    if not isinstance(config, AnalysisConfig):
        config = AnalysisConfig(
            config.model_dir, model_filename=config.model_filename,
            params_filename=config.params_filename, place=config.place)
    return AnalysisPredictor(config)
