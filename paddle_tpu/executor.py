"""Executor: jit-compiles whole program blocks to XLA.

TPU-native re-design of the reference C++ Executor
(paddle/fluid/framework/executor.cc: Prepare :294, hot loop :332-339). The
reference interprets a block op-by-op, dispatching each op to a per-device
kernel -- per-op host overhead the TPU cannot tolerate. Here `Prepare`
partitions a block into maximal *device segments* separated by host ops
(save/load/print/feed/fetch), composes each segment's op emitters into one
Python function over traced JAX values, and `jax.jit`s it with persistable
state donated -- so a whole training step (forward + backward + optimizer
update) is ONE XLA executable with in-place parameter buffers in HBM. This is
exactly the BASELINE.json north star: "Executor jit-compiles ProgramDesc
blocks to XLA HLO instead of dispatching per-op CUDA kernels".

Compile cache: keyed on (program identity, mutation version, block, feed
shape/dtype signature, fetch names) -- the analog of the reference Python
Executor's program cache (executor.py:374) plus XLA's own executable cache.
"""
from __future__ import annotations

import contextlib
import hashlib
import time

import numpy as np

import jax
import jax.numpy as jnp

from . import registry
from .framework import default_main_program, Program, Variable

__all__ = ['Executor', 'Scope', 'global_scope', 'scope_guard',
           '_switch_scope', 'CPUPlace', 'TPUPlace', 'XLAPlace',
           'CUDAPlace', 'fetch_var', 'OpExecutionError']


class OpExecutionError(RuntimeError):
    """An op failed during lowering/execution, annotated with the op's
    identity and its declared I/O (the PADDLE_ENFORCE-style context of
    reference platform/enforce.h:253 + operator.cc error wrapping — a
    user with a shape bug in a 200-op program gets the offending op
    named, not a bare JAX traceback)."""


def _describe_op(op, block, pos=None):
    def slot_str(mapping):
        parts = []
        for slot, names in mapping.items():
            descs = []
            for n in names:
                try:
                    v = block.var_recursive(n)
                    descs.append('%s%s' % (n, list(v.shape)
                                           if v.shape is not None else ''))
                except KeyError:
                    descs.append(n)
            parts.append('%s=[%s]' % (slot, ', '.join(descs)))
        return '; '.join(parts)
    where = ('op #%d ' % pos) if pos is not None else 'op '
    return ('%s%r in block %d\n  inputs:  %s\n  outputs: %s'
            % (where, op.type, block.idx, slot_str(op.inputs),
               slot_str(op.outputs)))


def _passthrough_exception(e):
    """Exceptions that are control flow, not op failures — never wrap."""
    from .reader.pipeline import EOFException
    return isinstance(e, (OpExecutionError, EOFException))


def _wrap_op_error(e, op, block, pos=None):
    return OpExecutionError(
        'Error running %s\n  cause: %s: %s'
        % (_describe_op(op, block, pos), type(e).__name__, e))


# ---------------------------------------------------------------------------
# Places (reference paddle/fluid/platform/place.h:78 boost::variant<...>)
# ---------------------------------------------------------------------------

class Place(object):
    platform = None

    def __init__(self, device_id=0):
        self.device_id = device_id

    def jax_device(self):
        devs = (jax.devices(self.platform) if self.platform
                else jax.devices())
        return devs[self.device_id % len(devs)]

    def __repr__(self):
        return '%s(%d)' % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))


class CPUPlace(Place):
    platform = 'cpu'


class TPUPlace(Place):
    """The default-accelerator place: whatever JAX's default backend is
    (TPU on hardware, CPU elsewhere) -- the analog of fluid.CUDAPlace and
    the north star's fluid.XLAPlace."""
    platform = None


# reference-compatible aliases: scripts say fluid.CUDAPlace(0) / XLAPlace(0)
XLAPlace = TPUPlace
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace    # pinned host staging is PJRT's job here


# ---------------------------------------------------------------------------
# Scope (reference paddle/fluid/framework/scope.h:39): name -> runtime value.
# Values are jax.Arrays (device-resident) or host numpy for host-only vars.
# ---------------------------------------------------------------------------

class Scope(object):
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self._kids = []

    def var(self, name):
        """Find-or-create (reference Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return self._vars.get(name)

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def set_var(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def new_scope(self):
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def local_var_names(self):
        return list(self._vars)


_global_scope = Scope()


def global_scope():
    return _global_scope


def _switch_scope(scope):
    """Swap the global scope, returning the previous one (reference
    executor.py:39 — scripts use it for manual scope juggling where
    scope_guard's context shape does not fit)."""
    global _global_scope
    prev, _global_scope = _global_scope, scope
    return prev


@contextlib.contextmanager
def scope_guard(scope):
    prev = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(prev)


def fetch_var(name, scope=None, return_numpy=True):
    scope = scope or global_scope()
    val = scope.find_var(name)
    if val is None:
        raise KeyError('var %r not found in scope' % name)
    return np.asarray(val) if return_numpy else val


def pad_lod_to_batch(flat, lod_level0_offsets):
    """Flat LoD rows [N, ...] + level-0 offsets -> (padded [B, T, ...],
    lengths [B] int32). The padded-batch lowering of the reference's
    no-padding LoD batching (lod_tensor.h:58); masks/lengths carry the
    raggedness instead of ragged shapes (XLA needs static shapes)."""
    offs = list(lod_level0_offsets)
    lens = np.diff(offs).astype('int32')
    B, T = len(lens), (int(lens.max()) if len(lens) else 0)
    padded = np.zeros((B, max(T, 1)) + flat.shape[1:], dtype=flat.dtype)
    for b in range(B):
        padded[b, :lens[b]] = flat[offs[b]:offs[b + 1]]
    return padded, lens


def _expand_sequence_feeds(program, feed):
    """Expand LoD feeds into the padded + '@SEQ_LEN' companion pair."""
    from .lod_tensor import LoDTensor
    out = {}
    for name, value in feed.items():
        var = program.global_block().vars.get(name)
        if var is None or var.lod_level == 0:
            out[name] = value
            continue
        lens_name = name + '@SEQ_LEN'
        if isinstance(value, LoDTensor) and value.lod():
            lod = value.lod()
            if len(lod) != 1:
                raise NotImplementedError(
                    'only lod_level=1 feeds are supported on TPU '
                    '(got %d levels for %r)' % (len(lod), name))
            padded, lens = pad_lod_to_batch(value.numpy(), lod[0])
            out[name] = padded
            out.setdefault(lens_name, lens)
        elif isinstance(value, tuple) and len(value) == 2:
            padded, lens = value
            out[name] = np.asarray(padded)
            out.setdefault(lens_name, np.asarray(lens, dtype='int32'))
        else:
            arr = np.asarray(value)
            declared = len(var.shape or ())
            if arr.ndim != declared + 1:
                raise ValueError(
                    'feed %r is a lod_level=%d var: feed a LoDTensor, a '
                    '(padded, lengths) tuple, or a padded array of rank %d '
                    '(got rank %d)' % (name, var.lod_level, declared + 1,
                                       arr.ndim))
            out[name] = arr
            out.setdefault(lens_name,
                           np.full((arr.shape[0],), arr.shape[1], 'int32'))
    return out


# ---------------------------------------------------------------------------
# Emit contexts
# ---------------------------------------------------------------------------

class EmitContext(object):
    """Traced-value environment handed to op emitters during lowering.

    _op_index: globally-unique index for RNG folding (synthetic inside
    sub-blocks). _block_pos: the op's position within ctx.block.ops (used
    for IR-level constant folding, e.g. tensor-array indices)."""

    __slots__ = ('env', 'block', 'rng_key', 'is_test', '_op_index',
                 '_block_pos', '_fold_limits', 'mesh', 'amp',
                 'bn_local_stats')

    def __init__(self, env, block, rng_key, is_test, amp=False):
        self.env = env
        self.block = block
        self.rng_key = rng_key
        self.is_test = is_test
        self.amp = amp
        self._op_index = 0
        self._block_pos = 0
        # block idx -> op-position limit for IR constant folding: inside a
        # sub-block, ancestor blocks may only be scanned up to the
        # enclosing control-flow op's position (ops after it haven't
        # "happened" yet)
        self._fold_limits = {}
        # device mesh for sharding_constraint emitters; None on a plain
        # single-device Executor (ParallelExecutor sets its Mesh)
        self.mesh = None
        # per-executor BuildStrategy.bn_local_stats override (None =
        # follow the global flag); see ops/nn_ops.py _bn_local_mode
        self.bn_local_stats = None

    def get(self, name):
        try:
            return self.env[name]
        except KeyError:
            raise KeyError(
                'var %r is not available on device; produced ops must come '
                'before consumers in the block' % name)

    def set(self, name, value):
        self.env[name] = value

    def var(self, name):
        return self.block.var_recursive(name)

    def rng(self, op):
        if self.rng_key is None:
            raise RuntimeError('op %s needs RNG but none was threaded'
                               % op.type)
        return jax.random.fold_in(self.rng_key, self._op_index)


class HostContext(object):
    """Host-side environment for host ops (print/save/load/...)."""

    def __init__(self, scope, block):
        self.scope = scope
        self.block = block
        self.is_test = False

    def get(self, name):
        val = self.scope.find_var(name)
        if val is None:
            raise KeyError('host op input %r not found in scope' % name)
        return np.asarray(val)

    def get_raw(self, name):
        """Like get() but without numpy coercion — for host ops consuming
        structured values (SelectedRows gradients in the send op)."""
        val = self.scope.find_var(name)
        if val is None:
            raise KeyError('host op input %r not found in scope' % name)
        return val

    def set(self, name, value):
        self.scope.set_var(name, np.asarray(value))

    def set_raw(self, name, value):
        self.scope.set_var(name, value)

    def delete(self, name):
        self.scope.erase(name)

    def var(self, name):
        return self.block.var_recursive(name)

    def rng(self, op):
        raise RuntimeError('host ops have no device RNG')


# ---------------------------------------------------------------------------
# Prepared program: segments + metadata
# ---------------------------------------------------------------------------

class _DeviceSegment(object):
    __slots__ = ('ops', 'op_offsets', 'in_names', 'out_names', 'jitted',
                 'needs_rng', '_arg_struct')

    def __init__(self, ops, op_offsets):
        self.ops = ops
        self.op_offsets = op_offsets  # global op indices (stable rng folding)
        self.in_names = []
        self.out_names = []
        self.jitted = None
        self.needs_rng = False
        self._arg_struct = None   # set on first run; see _run_prepared


class _HostStep(object):
    __slots__ = ('op',)

    def __init__(self, op):
        self.op = op


class PreparedProgram(object):
    """Analog of reference ExecutorPrepareContext (executor.h:28)."""

    def __init__(self, program, block_id, feed_names, fetch_names,
                 donate=True):
        self.program = program
        self.block = program.blocks[block_id]
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        # donate=False for pserver optimize blocks: the RPC threads may
        # serve a parameter concurrently with the next async update, so
        # buffers must not be invalidated in place
        self.donate = donate
        # perf observatory (obs/perf.py): fingerprint tags this
        # prepared program's xla.compile spans; cost_* accumulate the
        # XLA cost analysis of each compiled segment (complete once
        # every segment has run) — the work model behind perf.mfu
        self.fingerprint = None
        self.cost_flops = 0.0
        self.cost_bytes = 0.0
        self.steps = []          # list of _DeviceSegment | _HostStep
        self._build_segments()
        self._analyze_dataflow()

    def _build_segments(self):
        cur_ops, cur_offsets = [], []
        for idx, op in enumerate(self.block.ops):
            if op.type in ('feed', 'fetch'):
                continue
            opdef = registry._REGISTRY.get(op.type)
            if opdef is None or opdef.emit is None:
                raise KeyError('op %r has no emitter registered' % op.type)
            if opdef.host:
                if cur_ops:
                    self.steps.append(_DeviceSegment(cur_ops, cur_offsets))
                    cur_ops, cur_offsets = [], []
                self.steps.append(_HostStep(op))
            else:
                cur_ops.append(op)
                cur_offsets.append(idx)
        if cur_ops:
            self.steps.append(_DeviceSegment(cur_ops, cur_offsets))

    def _analyze_dataflow(self):
        """Per-segment inputs (read-before-write) and live outputs (written
        and needed by later steps / fetches / persistable state)."""
        persistable = {name for name, var in self.block.vars.items()
                       if var.persistable}
        # also persistables from the global block (sub-block case)
        b = self.block
        while b.parent_block is not None:
            b = b.parent_block
            persistable |= {n for n, v in b.vars.items() if v.persistable}

        step_reads, step_writes = [], []
        for step in self.steps:
            if isinstance(step, _DeviceSegment):
                reads, writes = set(), set()
                for op in step.ops:
                    for n in op.input_arg_names():
                        if n not in writes:
                            reads.add(n)
                    writes.update(op.output_arg_names())
                step_reads.append(reads)
                step_writes.append(writes)
            else:
                step_reads.append(set(step.op.input_arg_names()))
                step_writes.append(set(step.op.output_arg_names()))

        fetch_set = set(self.fetch_names)
        for i, step in enumerate(self.steps):
            if not isinstance(step, _DeviceSegment):
                continue
            later_reads = set()
            for j in range(i + 1, len(self.steps)):
                later_reads |= step_reads[j]
            writes = step_writes[i]
            step.in_names = sorted(step_reads[i])
            step.out_names = sorted(
                (writes & (later_reads | fetch_set | persistable)))
            step.needs_rng = any(
                self._op_is_stateful(op) for op in step.ops)

    def _op_is_stateful(self, op):
        """stateful (RNG-using) check, recursing into control-flow
        sub-blocks (dropout inside an RNN step still needs the key)."""
        if registry._REGISTRY[op.type].stateful:
            return True
        sub_idx = op.attr('sub_block', None) if op.attrs else None
        if sub_idx is not None:
            sub = self.program.blocks[sub_idx]
            return any(self._op_is_stateful(sop) for sop in sub.ops)
        return False


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

import weakref

_LIVE_EXECUTORS = weakref.WeakSet()


def all_compiled_hlo_texts():
    """Compiled HLO of every device segment run so far by any live
    Executor — the instruction→op_name metadata source the profiler
    joins against xplane device events (profiler.py op attribution;
    reference analog: device_tracer.cc correlating CUPTI records to op
    annotations)."""
    texts = []
    for exe in list(_LIVE_EXECUTORS):
        texts.extend(exe.compiled_hlo_texts())
    return texts


class Executor(object):
    def __init__(self, place=None):
        self.place = place if place is not None else TPUPlace()
        # device resolved lazily: constructing an Executor must not touch
        # the JAX backend (a later ParallelExecutor(num_trainers>1) in the
        # same script still needs to run jax.distributed.initialize first)
        self._device = None
        self._prepared_cache = {}
        self._step = 0
        self._base_key = None
        # device segments jit-compiled by this executor (monotonic):
        # serving asserts the decode program compiles exactly once
        # across a generation loop (jit_cache_stats)
        self._compile_count = 0
        # per-executor mirror of the xla.jit_cache.{hit,miss} telemetry
        # counters: one hit/miss per device-segment dispatch (misses ==
        # compiled_segments outside check_nan_inf mode)
        self._segment_hits = 0
        self._segment_misses = 0
        _LIVE_EXECUTORS.add(self)

    def jit_cache_stats(self):
        """{'prepared_programs', 'compiled_segments', 'segment_hits',
        'segment_misses'} — compiled_segments is monotonic, so a
        steady-state serving loop proves jit-cache hits by observing it
        stay constant across N decode steps; hits/misses count every
        device-segment dispatch (ParallelExecutor inherits all four —
        SPMD and pipeline paths feed the same counters)."""
        return {'prepared_programs': len(self._prepared_cache),
                'compiled_segments': self._compile_count,
                'segment_hits': self._segment_hits,
                'segment_misses': self._segment_misses}

    def compiled_hlo_texts(self):
        """Optimized-HLO text of each compiled device segment (re-lowered
        from the stashed abstract arg signature; hits the jit cache)."""
        texts = []
        for prepared in self._prepared_cache.values():
            for step in prepared.steps:
                if isinstance(step, _DeviceSegment) \
                        and step.jitted is not None \
                        and step._arg_struct is not None:
                    try:
                        texts.append(step.jitted.lower(*step._arg_struct)
                                     .compile().as_text())
                    except Exception:
                        pass
        return texts

    @property
    def device(self):
        if self._device is None:
            self._device = self.place.jax_device()
        return self._device

    # -- rng ---------------------------------------------------------------
    def _rng_key(self, program):
        seed = program.random_seed
        if self._base_key is None or seed != getattr(self, '_seed_used', None):
            if seed == 0:
                seed = np.random.randint(0, 2**31 - 1)
            self._base_key = jax.random.PRNGKey(seed)
            self._realized_seed = int(seed)   # checkpointable (Trainer)
            self._seed_used = program.random_seed
        return jax.random.fold_in(self._base_key, self._step)

    # -- public API (reference python executor.py:374 Executor.run) --------
    def run(self, program=None, feed=None, fetch_list=None,
            feed_var_name='feed', fetch_var_name='fetch', scope=None,
            return_numpy=True, use_program_cache=True):
        from .obs import perf as _perf
        t0_perf = _perf.step_begin()
        program = program or default_main_program()
        if not isinstance(program, Program):
            raise TypeError('Executor.run expects a Program')
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in fetch_list]

        feed_arrays = {}
        feed = _expand_sequence_feeds(program, feed)
        for name, value in feed.items():
            from .lod_tensor import LoDTensor
            if isinstance(value, LoDTensor):
                value = value.numpy()
            if isinstance(value, jax.Array):
                # already device-resident (e.g. a pre-placed benchmark batch
                # or double-buffered reader output): hand it to the feed
                # placer without a host round-trip, casting on device if the
                # declared var dtype differs (canonicalized: x64 is off).
                var = program.global_block().vars.get(name)
                if var is not None and var.dtype is not None and \
                        var.dtype != 'bfloat16':
                    want = jax.dtypes.canonicalize_dtype(np.dtype(var.dtype))
                    if value.dtype != want:
                        value = value.astype(want)
                feed_arrays[name] = self._put_feed(name, value)
                continue
            arr = np.asarray(value)
            var = program.global_block().vars.get(name)
            if var is not None and var.dtype is not None and \
                    arr.dtype != np.dtype(var.dtype) and \
                    var.dtype != 'bfloat16':
                arr = arr.astype(var.dtype)
            feed_arrays[name] = self._put_feed(name, arr)

        feed_sig = tuple(sorted(
            (n, a.shape, str(a.dtype)) for n, a in feed_arrays.items()))
        cache_key = (program._uid, program._version, 0, feed_sig,
                     tuple(fetch_names))
        prepared = self._prepared_cache.get(cache_key) \
            if use_program_cache else None
        if prepared is None:
            prepared = PreparedProgram(program, 0, feed_arrays.keys(),
                                       fetch_names)
            if use_program_cache:
                self._prepared_cache[cache_key] = prepared
        if prepared.fingerprint is None:
            prepared.fingerprint = hashlib.md5(
                repr(cache_key).encode()).hexdigest()[:12]

        result = self._run_prepared(prepared, feed_arrays, fetch_names,
                                    scope, program)
        self._step += 1
        if return_numpy:
            # the host fetch below IS the device sync (PERF.md: the one
            # reliable barrier on the remoted transport) — stamp the
            # step after it so perf.step_latency covers real work
            result = [self._to_numpy(r) for r in result]
            if t0_perf is not None:
                _perf.step_end(t0_perf, prepared, device=self.device,
                               scope=scope)
            return result
        if t0_perf is not None:
            _perf.step_end(t0_perf, prepared, device=self.device,
                           scope=scope, sync=result)
        return result

    def _to_numpy(self, value):
        """Hook: fetch one result to host (ParallelExecutor overrides to
        all-gather multi-host-sharded results)."""
        return np.asarray(value)

    # -- internals ---------------------------------------------------------
    def _run_prepared(self, prepared, feed_arrays, fetch_names, scope,
                      program):
        block = prepared.block
        rng_key = None
        temp_names = set()
        # run-local view: feeds + scope
        local = dict(feed_arrays)

        def read_var(name):
            if name in local:
                return local[name]
            val = scope.find_var(name)
            if val is None:
                raise RuntimeError(
                    'var %r used before initialization -- did you run the '
                    'startup program?' % name)
            # Pin host-resident persistables to the device ONCE: values
            # written by host ops (load_inference_model's load ops, set
            # vars) arrive as numpy; without this, every run() of a
            # program that only READS them (inference!) re-uploads all
            # parameters through the transport — measured 5 s/call for
            # ResNet-50 and minutes for a 740M-param LM over the
            # remoted link (reference analog: parameters live on-device
            # in the Scope, framework/tensor.h holder semantics).
            # (64-bit dtypes excluded: with x64 off, device_put would
            # narrow them and the narrowed array would leak back into
            # host-side save paths)
            if isinstance(val, np.ndarray) and \
                    val.dtype not in (np.int64, np.uint64, np.float64):
                var = block.vars.get(name)
                if var is not None and var.persistable:
                    val = jax.device_put(val, self.device)
                    scope.set_var(name, val)
            return val

        from . import flags as flags_mod
        from . import profiler as _prof
        from .obs import trace as _trace
        check_nan_inf = flags_mod.get_flag('check_nan_inf')

        for step_idx, step in enumerate(prepared.steps):
            if isinstance(step, _HostStep):
                # sync host-visible values then run on host
                hctx = _RunHostContext(scope, local, block)
                try:
                    with _prof.RecordEvent('host_op:%s' % step.op.type):
                        registry._REGISTRY[step.op.type].emit(hctx,
                                                              step.op)
                except Exception as e:
                    if _passthrough_exception(e):
                        raise
                    raise _wrap_op_error(e, step.op, block) from e
                continue

            donated = {}
            const = {}
            out_set = set(step.out_names)
            for name in step.in_names:
                val = read_var(name)
                if name in out_set and name not in feed_arrays \
                        and not check_nan_inf:
                    donated[name] = val
                else:
                    const[name] = val
            if step.needs_rng and rng_key is None:
                rng_key = self._rng_key(program)
            key_arg = rng_key if step.needs_rng \
                else jnp.zeros((2,), dtype=jnp.uint32)
            if check_nan_inf:
                # debug mode: ops run eagerly one by one, every output
                # scanned for NaN/Inf (reference operator.cc:749
                # FLAGS_check_nan_inf semantics; unfused and slow).
                # Nothing is donated: buffers stay valid for inspection.
                outs = self._run_segment_checked(step, block, program,
                                                 const, key_arg)
            else:
                from .obs import perf as _perf
                fresh_compile = step.jitted is None
                if fresh_compile:
                    self._segment_misses += 1
                    _perf.jit_cache_miss()
                    step.jitted = self._compile_segment(
                        step, block, program,
                        feed_names=tuple(feed_arrays.keys()),
                        donate=prepared.donate)
                else:
                    self._segment_hits += 1
                    _perf.jit_cache_hit()
                if getattr(step, '_arg_struct', None) is None:
                    # abstract arg signature kept so the profiler can
                    # re-lower this segment and read the compiled HLO
                    # (instr -> op_name metadata join; profiler.py)
                    step._arg_struct = jax.tree.map(
                        lambda a: jax.ShapeDtypeStruct(
                            np.shape(a), getattr(a, 'dtype', None)
                            or np.asarray(a).dtype),
                        (donated, const, key_arg))
                if fresh_compile and (_perf.enabled()
                                      or _trace.enabled()):
                    # time the FIRST call: trace+lower+XLA-compile all
                    # happen inside it (an explicit lower().compile()
                    # does NOT warm jax's jit call cache), so this span
                    # is the user-visible compile stall
                    t0c = time.perf_counter()
                    # discard any extra-flops notes left over from
                    # traces outside this segment (direct tool calls
                    # into the pallas kernels) so they aren't billed
                    # to us
                    _perf.pallas_extra_flops()
                    with _perf.compile_span(prepared.fingerprint,
                                            step_idx, len(step.ops)):
                        with _prof.RecordEvent(
                                'device_segment:%d(%d ops)'
                                % (step_idx, len(step.ops))):
                            outs = step.jitted(donated, const, key_arg)
                    # the compiling call above is what traces the inner
                    # pallas jits — drain the work this segment's arms
                    # reported beyond the analytical cost model
                    extra = _perf.pallas_extra_flops()
                    flops, nbytes = _perf.segment_cost(
                        step.jitted, step._arg_struct)
                    flops += extra
                    prepared.cost_flops += flops
                    prepared.cost_bytes += nbytes
                    _perf.record_compile(time.perf_counter() - t0c,
                                         flops, nbytes)
                else:
                    with _prof.RecordEvent(
                            'device_segment:%d(%d ops)'
                            % (step_idx, len(step.ops))):
                        outs = step.jitted(donated, const, key_arg)
            for name, val in zip(step.out_names, outs):
                local[name] = val
                var = block.vars.get(name)
                if var is None and block.parent_block is not None:
                    # sub-block execution (pserver optimize blocks): the
                    # written var usually lives in an ancestor block
                    try:
                        var = block.var_recursive(name)
                    except KeyError:
                        var = None
                if var is not None and var.persistable:
                    scope.set_var(name, val)
                else:
                    temp_names.add(name)

        results = []
        for name in fetch_names:
            if name in local:
                results.append(local[name])
            else:
                val = scope.find_var(name)
                if val is None:
                    raise KeyError('fetch var %r was not produced' % name)
                results.append(val)
        return results

    def _run_segment_checked(self, segment, block, program, env_in,
                             rng_key):
        """check_nan_inf mode: emit ops eagerly, scan every op's outputs
        for non-finite values, and name the offending op+var."""
        from .selected_rows import SelectedRows
        env = dict(env_in)
        ctx = EmitContext(env, block, rng_key, program._is_test,
                          amp=getattr(program, '_use_bf16', False))
        ctx.mesh = self._emit_mesh()
        ctx.bn_local_stats = getattr(self, '_bn_local_stats', None)
        for op, off in zip(segment.ops, segment.op_offsets):
            ctx._op_index = off
            ctx._block_pos = off
            try:
                registry._REGISTRY[op.type].emit(ctx, op)
            except Exception as e:
                if _passthrough_exception(e):
                    raise
                raise _wrap_op_error(e, op, block, pos=off) from e
            for name in op.output_arg_names():
                val = env.get(name)
                if val is None:
                    continue
                if isinstance(val, SelectedRows):
                    val = val.values
                # jnp.issubdtype, not np: bfloat16 (the AMP activation
                # dtype) is not a subtype of np.floating and would be
                # silently skipped
                dt = getattr(val, 'dtype', None) or np.asarray(val).dtype
                if jnp.issubdtype(dt, jnp.floating) and \
                        not bool(jnp.isfinite(jnp.asarray(val)).all()):
                    raise OpExecutionError(
                        'NaN/Inf detected in output %r of %s'
                        % (name, _describe_op(op, block, pos=off)))
        return tuple(env[n] for n in segment.out_names)

    def _put_feed(self, name, arr):
        """Hook: place one feed array; ParallelExecutor overrides this to
        shard the global batch across the mesh."""
        return jax.device_put(arr, self.device)

    def _jit_options(self, segment, feed_names):
        """Hook: extra jax.jit kwargs (in_shardings for the SPMD path)."""
        return {}

    def _emit_mesh(self):
        """Hook: mesh visible to emitters (sharding constraints)."""
        return None

    def run_block(self, program, block_id, scope, fetch_names=()):
        """Run one block (no feeds) against `scope` — the nested-executor
        entry used by host ops that interpret sub-blocks on the host
        (listen_and_serv optimize blocks; reference
        listen_and_serv_op.cc:148 ParallelExecuteBlocks). Buffers are NOT
        donated: RPC threads may read a parameter concurrently."""
        # 'block_run' tag: run() caches donate=True entries for block 0
        # under a colliding signature — never share them
        cache_key = ('block_run', program._uid, program._version, block_id,
                     tuple(fetch_names))
        prepared = self._prepared_cache.get(cache_key)
        if prepared is None:
            prepared = PreparedProgram(program, block_id, (),
                                       list(fetch_names), donate=False)
            prepared.fingerprint = hashlib.md5(
                repr(cache_key).encode()).hexdigest()[:12]
            self._prepared_cache[cache_key] = prepared
        return self._run_prepared(prepared, {}, list(fetch_names), scope,
                                  program)

    def close(self):
        """Notify pservers this trainer is done (reference
        executor.cc:48 Executor::Close -> SendComplete)."""
        from .distributed.rpc import close_all_clients
        close_all_clients(send_complete=True)

    def _compile_segment(self, segment, block, program, feed_names=(),
                         donate=True):
        is_test = program._is_test
        ops = segment.ops
        offsets = segment.op_offsets
        out_names = segment.out_names

        amp = getattr(program, '_use_bf16', False)

        def seg_fn(donated, const, rng_key):
            env = {}
            env.update(const)
            env.update(donated)
            ctx = EmitContext(env, block, rng_key, is_test, amp=amp)
            ctx.mesh = self._emit_mesh()
            ctx.bn_local_stats = getattr(self, '_bn_local_stats', None)
            for op, off in zip(ops, offsets):
                ctx._op_index = off
                ctx._block_pos = off
                try:
                    # named_scope stamps the IR op identity into XLA
                    # metadata, so xplane device events carry
                    # "<type>.<index>/..." — the per-op device-time
                    # attribution the reference gets from correlating
                    # CUPTI records to op annotations
                    # (platform/device_tracer.cc); consumed by
                    # profiler.py + tools/timeline.py
                    with jax.named_scope('%s.%d' % (op.type, off)):
                        registry._REGISTRY[op.type].emit(ctx, op)
                except Exception as e:
                    if _passthrough_exception(e):
                        raise
                    raise _wrap_op_error(e, op, block, pos=off) from e
            return tuple(env[n] for n in out_names)

        self._compile_count += 1
        return jax.jit(seg_fn, donate_argnums=(0,) if donate else (),
                       **self._jit_options(segment, feed_names))


class _RunHostContext(HostContext):
    """Host context that also sees the run-local (non-persistable) values."""

    def __init__(self, scope, local, block):
        super(_RunHostContext, self).__init__(scope, block)
        self.local = local

    def get(self, name):
        if name in self.local:
            return np.asarray(self.local[name])
        return super(_RunHostContext, self).get(name)

    def get_raw(self, name):
        if name in self.local:
            return self.local[name]
        return super(_RunHostContext, self).get_raw(name)

    def set(self, name, value):
        self.local[name] = np.asarray(value)
        if self.scope.has_var(name) or \
                (name in self.block.vars and self.block.vars[name].persistable):
            self.scope.set_var(name, np.asarray(value))

    def set_raw(self, name, value):
        self.local[name] = value
        if self.scope.has_var(name) or \
                (name in self.block.vars and self.block.vars[name].persistable):
            self.scope.set_var(name, value)
