"""High-level Trainer with periodic checkpointing and exact-step resume.

Capability parity with reference python/paddle/fluid/trainer.py
(Trainer :169, CheckpointConfig :100, _save_checkpoint :558,
_load_checkpoint/clean_checkpoint :600-641), redesigned for this
framework's execution model:

- one Program pair built from the user's train_func/optimizer_func;
- a checkpoint = save_persistables (params + optimizer accumulators +
  bn stats) + a TRAINER_METADATA json carrying (epoch, step, executor
  RNG step counter) + a SUCCESS marker written LAST, so a checkpoint
  interrupted mid-write (preemption — the TPU failure mode SURVEY §5.3
  maps to) is never resumed from;
- resume restores scope state AND the executor step counter, then the
  training loop fast-forwards the data reader to the exact step, so a
  killed-and-restarted run continues with bit-identical losses
  (exercised in tests/test_trainer.py);
- max_num_checkpoints oldest-first pruning (reference trainer.py
  _scroll_delete semantics).
"""
from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

from . import io as io_mod
from .executor import Executor, TPUPlace, Scope, scope_guard
from .framework import Program, program_guard, default_main_program, \
    default_startup_program
from .obs import telemetry as _tm
from .obs import trace as _obs_trace

_STEPS = _tm.counter('trainer.steps')
_STEP_LATENCY = _tm.histogram('trainer.step_latency')

__all__ = ['Trainer', 'CheckpointConfig', 'BeginEpochEvent',
           'EndEpochEvent', 'BeginStepEvent', 'EndStepEvent',
           'FaultEvent']

_CHECKPOINT_PREFIX = 'checkpoint'
_METADATA_FILE = 'TRAINER_METADATA'
_SUCCESS_FILE = '_SUCCESS'
_DIGESTS_FILE = 'CHECKPOINT_DIGESTS'


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent(object):
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class FaultEvent(object):
    """A step hit an RPC/runtime fault (distributed/resilience.py
    taxonomy). action is 'retry' (the step will re-run in place after a
    retryable failure), 'rollback' (fatal failure: scope + RNG state
    restored from the last SUCCESS-marked checkpoint and training
    resumes from there) or 'anomaly' (the numeric guard saw a
    non-finite loss/gradient — FLAGS_anomaly_action; the step is
    skipped, or escalates per the flag); attempt counts retries,
    rollbacks, resp. the consecutive-anomaly streak."""

    def __init__(self, epoch_id, step_id, error, action, attempt=1):
        self.epoch = epoch_id
        self.step = step_id
        self.error = error
        self.action = action
        self.attempt = attempt
        # every FaultEvent construction site counts + lands in the obs
        # event log (one place instead of three): the cluster timeline
        # shows WHEN the retry/rollback/anomaly fired, the rollup how
        # often
        _tm.counter('trainer.fault.%s' % action).inc()
        _obs_trace.event('fault', action=action, epoch=epoch_id,
                         step=step_id, attempt=attempt,
                         error=str(error)[:200])


class CheckpointConfig(object):
    """(reference trainer.py:100) checkpoint_dir=None disables
    checkpointing; step_interval counts steps within an epoch.

    sharded=True switches to the mesh-native path
    (paddle_tpu/checkpoint/): checkpoint_dir becomes a two-generation
    sharded root (current/ + current.prev/) written per-shard with no
    host gather, and resume reshards onto whatever mesh the restarted
    process builds."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10,
                 pserver_endpoints=None, trainer_id=0, sharded=False):
        self.checkpoint_dir = checkpoint_dir
        self.max_num_checkpoints = max(1, int(max_num_checkpoints))
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        # pserver mode: endpoints to checkpoint_notify at each save
        # (reference trainer.py wires checkpoint_notify into the save
        # flow; DistributeTranspiler.checkpoint_notify_program builds
        # the same op for manual loops)
        self.pserver_endpoints = list(pserver_endpoints or [])
        self.trainer_id = int(trainer_id)
        self.sharded = bool(sharded)


def _poison_feed(feed):
    """The 'nan' step-fault action: NaN one element of the first float
    feed (sorted order — deterministic) so the poison flows through the
    real forward/backward into the loss and gradients."""
    feed = dict(feed)
    for key in sorted(feed):
        arr = np.asarray(feed[key])
        if arr.dtype.kind == 'f':
            arr = arr.copy()
            arr.flat[0] = np.nan
            feed[key] = arr
            break
    return feed


def _checkpoint_ids(ckpt_dir):
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return []
    ids = []
    for name in os.listdir(ckpt_dir):
        if name.startswith(_CHECKPOINT_PREFIX + '_'):
            path = os.path.join(ckpt_dir, name)
            if os.path.exists(os.path.join(path, _SUCCESS_FILE)):
                try:
                    ids.append(int(name.split('_')[-1]))
                except ValueError:
                    continue
    return sorted(ids)


class Trainer(object):
    """(reference trainer.py:169)

    train_func() -> loss Variable (or [loss, ...metrics]) builds the
    forward graph; optimizer_func() -> an Optimizer.
    """

    def __init__(self, train_func, optimizer_func, place=None,
                 param_path=None, parallel=False, checkpoint_config=None,
                 strategy=None):
        self.place = place if place is not None else TPUPlace()
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        # DistributedStrategy for the ParallelExecutor (multi-axis
        # mesh / ZeRO sharding); None = plain dp over all devices
        self._strategy = strategy
        self._mesh_checkpointer = None
        self.scope = Scope()
        self.train_program = Program()
        self.startup_program = Program()
        from .flags import get_flag
        self._anomaly_action = str(get_flag('anomaly_action', 'none')
                                   or 'none')
        self._anomaly_skip_steps = int(get_flag('anomaly_skip_steps', 1))
        self._anomaly_streak = 0
        self._guard_var = None
        with program_guard(self.train_program, self.startup_program):
            outs = train_func()
            if isinstance(outs, (list, tuple)):
                self.train_outputs = list(outs)
            else:
                self.train_outputs = [outs]
            loss = self.train_outputs[0]
            optimizer = optimizer_func()
            _opt_ops, params_grads = optimizer.minimize(loss)
            if self._anomaly_action != 'none':
                self._guard_var = self._build_anomaly_guard(loss,
                                                            params_grads)
        self.loss = loss
        self.exe = Executor(self.place)
        self._pe = None
        self.epoch_id = 0
        self.step_id = 0
        self._stop_requested = False
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
        if param_path:
            with scope_guard(self.scope):
                io_mod.load_persistables(self.exe, param_path,
                                         main_program=self.train_program)
        self._resumed = self._maybe_resume()

    def _build_anomaly_guard(self, loss, params_grads):
        """Append one fused `isfinite` reduction over the loss and
        every dense float gradient (FLAGS_anomaly_action != 'none') —
        a single scalar-bool fetch per step, evaluated inside the same
        jitted program as the step itself, so the production-mode guard
        costs one cheap reduction rather than the per-op eager scan of
        FLAGS_check_nan_inf."""
        from .framework import VarType
        block = self.train_program.global_block()
        xs = [loss.name]
        for _param, grad in params_grads:
            if grad is None or grad.type == VarType.SELECTED_ROWS:
                continue
            # dtype is the canonical string name ('float32',
            # 'bfloat16', ...) — np.dtype would choke on bfloat16
            if not str(grad.dtype or '').startswith(('float', 'bfloat')):
                continue
            xs.append(grad.name)
        guard = block.create_var(name='_anomaly_finite_guard',
                                 dtype='bool', shape=())
        block.append_op(type='isfinite', inputs={'X': xs},
                        outputs={'Out': [guard.name]})
        return guard

    # -- checkpointing -----------------------------------------------------
    def _ckpt_path(self, ckpt_id):
        return os.path.join(self.checkpoint_cfg.checkpoint_dir,
                            '%s_%d' % (_CHECKPOINT_PREFIX, ckpt_id))

    def _mesh_ckpt(self):
        if self._mesh_checkpointer is None:
            from .checkpoint import MeshCheckpointer
            self._mesh_checkpointer = MeshCheckpointer(
                self.checkpoint_cfg.checkpoint_dir)
        return self._mesh_checkpointer

    def _train_state_extras(self, epoch_id, step_id):
        active = self._pe if self._pe is not None else self.exe
        return {'epoch_id': epoch_id, 'step_id': step_id,
                'exe_step': active._step,
                # the REALIZED rng seed (random_seed=0 draws one at first
                # use): without it, a restarted process draws a fresh base
                # key and dropout streams diverge despite _step matching
                'rng_seed': getattr(active, '_realized_seed', None),
                'rng_seed_used': getattr(active, '_seed_used', None)}

    def _save_checkpoint(self, epoch_id, step_id):
        cfg = self.checkpoint_cfg
        if cfg.sharded:
            # mesh path: per-shard async save straight from the scope's
            # device arrays (checkpoint/sharded.py) — the step blocks
            # only for the device->host shard copies; file I/O, digests
            # and the generation rotation overlap the next steps
            self._mesh_ckpt().save_scope(
                self.scope, self.train_program,
                extras=self._train_state_extras(epoch_id, step_id))
            return
        ids = _checkpoint_ids(cfg.checkpoint_dir)
        new_id = (ids[-1] + 1) if ids else 0
        path = self._ckpt_path(new_id)
        os.makedirs(path, exist_ok=True)
        with scope_guard(self.scope):
            io_mod.save_persistables(self.exe, path,
                                     main_program=self.train_program)
        with open(os.path.join(path, _METADATA_FILE), 'w') as f:
            json.dump(self._train_state_extras(epoch_id, step_id), f)
        if cfg.pserver_endpoints and cfg.trainer_id == 0:
            # pserver mode: have each parameter server save its shard
            # (params + server-side optimizer state) under this
            # checkpoint before the SUCCESS marker commits it; restore
            # happens pserver-side via
            # get_pserver_programs(checkpoint_dir=...)
            from .transpiler.distribute_transpiler import \
                build_checkpoint_notify_program
            notify = build_checkpoint_notify_program(
                os.path.join(path, 'pserver_shards'),
                cfg.pserver_endpoints, cfg.trainer_id)
            with scope_guard(self.scope):
                self.exe.run(notify)
        # digest manifest next-to-last: it covers every payload file in
        # the checkpoint (tensors, metadata, pserver shards) so resume
        # can tell corruption from a clean save — the SUCCESS marker
        # alone only proves the save COMPLETED, not that the bytes
        # survived
        self._write_digests(path)
        # SUCCESS marker last: a partial checkpoint must never be resumed
        with open(os.path.join(path, _SUCCESS_FILE), 'w') as f:
            f.write('')
        for old in _checkpoint_ids(cfg.checkpoint_dir)[
                :-cfg.max_num_checkpoints]:
            shutil.rmtree(self._ckpt_path(old), ignore_errors=True)

    @staticmethod
    def _write_digests(path):
        """CHECKPOINT_DIGESTS: {relpath: [crc32, size]} over every file
        in the checkpoint dir (except the marker and the manifest) —
        the shared manifest story of checkpoint/manifest.py."""
        from .checkpoint import manifest as ckpt_manifest
        ckpt_manifest.write_digests(path)

    @staticmethod
    def _verify_checkpoint(path):
        """None if every digest matches (or the checkpoint predates
        digests — accepted for back-compat), else a reason string."""
        from .checkpoint import manifest as ckpt_manifest
        return ckpt_manifest.verify_digests(path)

    def _maybe_resume(self):
        """Restore from the newest VALID checkpoint. A dir with no
        SUCCESS marker is never considered (_checkpoint_ids); one whose
        metadata is corrupt/truncated or whose tensors fail to load is
        skipped with a warning, falling back to the next-newest — a
        single bad checkpoint (partial write, disk corruption) must not
        make the whole run unrecoverable."""
        cfg = self.checkpoint_cfg
        if cfg is None or not cfg.checkpoint_dir:
            return False
        if cfg.sharded:
            return self._maybe_resume_sharded()
        for ckpt_id in reversed(_checkpoint_ids(cfg.checkpoint_dir)):
            path = self._ckpt_path(ckpt_id)
            try:
                reason = self._verify_checkpoint(path)
            except Exception as e:
                reason = 'unreadable digest manifest: %r' % e
            if reason is not None:
                # corrupt payload: quarantine the WHOLE checkpoint dir
                # (renamed aside, kept for post-mortem — and no longer
                # SUCCESS-listed, so it is never retried) and fall back
                from .distributed.statefile import quarantine_dir
                quarantine_dir(path, reason)
                continue
            try:
                with open(os.path.join(path, _METADATA_FILE)) as f:
                    meta = json.load(f)
                epoch_id = int(meta['epoch_id'])
                step_id = int(meta['step_id'])
                with scope_guard(self.scope):
                    io_mod.load_persistables(
                        self.exe, path, main_program=self.train_program)
            except Exception as e:
                import sys
                print('skipping unusable checkpoint %s: %r' % (path, e),
                      file=sys.stderr)
                continue
            self.epoch_id = epoch_id
            self.step_id = step_id + 1   # resume AFTER that step
            # restore the RNG step counter AND base key: dropout streams
            # continue exactly (also applied to the ParallelExecutor
            # when one is created)
            self._restored_step = int(meta.get('exe_step', 0))
            self._restored_rng = (meta.get('rng_seed'),
                                  meta.get('rng_seed_used'))
            self._apply_rng_state(self.exe)
            if self._pe is not None:
                self._apply_rng_state(self._pe)
            return True
        return False

    def _maybe_resume_sharded(self):
        """Mesh-path resume: pour the last committed generation
        (digest-verified; .prev fallback and quarantine handled inside
        checkpoint/restore.py) back into the scope and restore the
        train-state extras. Values land as host arrays; the
        ParallelExecutor re-places them into each var's mesh sharding
        on its next run — exact, even onto a different topology than
        the one that saved."""
        extras = self._mesh_ckpt().restore_scope(self.scope,
                                                 self.train_program)
        if extras is None:
            return False
        self.epoch_id = int(extras.get('epoch_id', 0))
        self.step_id = int(extras.get('step_id', -1)) + 1
        self._restored_step = int(extras.get('exe_step', 0))
        self._restored_rng = (extras.get('rng_seed'),
                              extras.get('rng_seed_used'))
        self._apply_rng_state(self.exe)
        if self._pe is not None:
            self._apply_rng_state(self._pe)
            # force _bcast_params on the next run so the restored host
            # values return to their mesh shardings
            self._pe._params_placed = False
        return True

    def _apply_rng_state(self, executor):
        executor._step = getattr(self, '_restored_step', 0)
        seed, seed_used = getattr(self, '_restored_rng', (None, None))
        if seed is not None:
            import jax
            executor._base_key = jax.random.PRNGKey(int(seed))
            executor._realized_seed = int(seed)
            executor._seed_used = seed_used

    # -- training loop -----------------------------------------------------
    def _executor(self):
        if not self.parallel:
            return None
        if self._pe is None:
            from .parallel_executor import ParallelExecutor
            self._pe = ParallelExecutor(
                use_cuda=True, loss_name=self.loss.name,
                main_program=self.train_program, scope=self.scope,
                strategy=self._strategy)
            self._apply_rng_state(self._pe)
        return self._pe

    def train(self, num_epochs, event_handler, reader=None,
              feed_order=None):
        """reader(): generator of feed-able batches; feed_order: the
        data-var names, matched positionally against each batch item.

        Fault handling (distributed/resilience.py taxonomy): a step that
        raises RetryableRPCError re-runs in place up to
        FLAGS_trainer_step_retries times, then escalates; a fatal RPC
        failure rolls training back to the last SUCCESS-marked
        checkpoint (at most FLAGS_trainer_max_rollbacks times). Both
        paths emit a FaultEvent to the event handler first."""
        from .distributed.resilience import FatalRPCError
        from .flags import get_flag
        max_rollbacks = int(get_flag('trainer_max_rollbacks', 2))
        rollbacks = 0
        while True:
            try:
                result = self._train_loop(num_epochs, event_handler,
                                          reader, feed_order)
                if self._mesh_checkpointer is not None:
                    # drain in-flight async generation commits (and
                    # surface any async save failure) before the caller
                    # believes training — and its checkpoints — are done
                    self._mesh_checkpointer.wait()
                return result
            except FatalRPCError as e:
                cfg = self.checkpoint_cfg
                if cfg is None or not cfg.checkpoint_dir or \
                        rollbacks >= max_rollbacks:
                    raise
                rollbacks += 1
                event_handler(FaultEvent(self.epoch_id, self.step_id, e,
                                         'rollback', rollbacks))
                if not self._maybe_resume():
                    raise   # no SUCCESS-marked checkpoint to fall to

    def _run_step(self, pe, fetch, feed, epoch_id, step_id,
                  event_handler):
        from .distributed import resilience
        from .flags import get_flag
        retries = int(get_flag('trainer_step_retries', 2))
        attempt = 0
        while True:
            try:
                # deterministic fault injection; 'nan' poisons one feed
                # value so the numeric-anomaly guard sees a non-finite
                # loss computed through the real step
                if resilience.on_step() == 'nan':
                    feed = _poison_feed(feed)
                with scope_guard(self.scope):
                    if pe is not None:
                        return pe.run(fetch_list=fetch, feed=feed)
                    return self.exe.run(self.train_program, feed=feed,
                                        fetch_list=fetch)
            except resilience.RetryableRPCError as e:
                attempt += 1
                if attempt > retries:
                    raise resilience.FatalRPCError(
                        'step (%d, %d) failed after %d retries: %s'
                        % (epoch_id, step_id, retries, e)) from e
                event_handler(FaultEvent(epoch_id, step_id, e, 'retry',
                                         attempt))

    def _train_loop(self, num_epochs, event_handler, reader, feed_order):
        cfg = self.checkpoint_cfg
        start_epoch, start_step = self.epoch_id, self.step_id
        pe = self._executor()
        fetch = [v.name for v in self.train_outputs]
        if self._guard_var is not None:
            # the guard is fetched alongside the metrics (one fused
            # scalar reduction) and sliced off before events see them
            fetch = fetch + [self._guard_var.name]
        self._stop_requested = False
        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, data in enumerate(reader()):
                if epoch_id == start_epoch and step_id < start_step:
                    continue    # fast-forward to the resumed position
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                if self._stop_requested:
                    return
                feed = dict(zip(feed_order, data))
                _t0 = time.perf_counter()
                metrics = self._run_step(pe, fetch, feed, epoch_id,
                                         step_id, event_handler)
                _STEP_LATENCY.observe(time.perf_counter() - _t0)
                _STEPS.inc()
                if self._guard_var is not None:
                    finite = bool(np.asarray(metrics[-1]))
                    metrics = metrics[:-1]
                    if not finite:
                        self._on_anomaly(epoch_id, step_id,
                                         event_handler)
                        # skip: no EndStepEvent, no checkpoint — an
                        # anomalous step must never become a rollback
                        # target
                        self.epoch_id, self.step_id = epoch_id, step_id
                        continue
                    self._anomaly_streak = 0
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
                self.epoch_id, self.step_id = epoch_id, step_id
                if cfg and cfg.checkpoint_dir and \
                        (step_id + 1) % cfg.step_interval == 0:
                    self._save_checkpoint(epoch_id, step_id)
                if self._stop_requested:
                    return
            start_step = 0
            if cfg and cfg.checkpoint_dir and \
                    self._anomaly_streak == 0 and \
                    (epoch_id + 1) % cfg.epoch_interval == 0:
                # saved as (next epoch, step -1): resume starts cleanly at
                # epoch E+1 step 0 instead of replaying epoch E's
                # Begin/EndEpochEvent with zero steps and re-saving a
                # duplicate checkpoint
                self._save_checkpoint(epoch_id + 1, -1)
            event_handler(EndEpochEvent(epoch_id))
            if self._stop_requested:
                return

    def _on_anomaly(self, epoch_id, step_id, event_handler):
        """Numeric guard tripped: emit a FaultEvent and either tolerate
        (skip the step, up to FLAGS_anomaly_skip_steps consecutive
        times — a transient bad batch resolves itself) or escalate per
        FLAGS_anomaly_action. Escalation matters because a skipped
        step's UPDATE may already have poisoned the parameters: if
        every following step is anomalous too, skipping forever would
        train nothing — 'rollback' restores the last SUCCESS checkpoint
        (known-finite params) and replays from there."""
        self._anomaly_streak += 1
        err = FloatingPointError(
            'non-finite loss/gradient at step (%d, %d) '
            '(FLAGS_anomaly_action=%s, streak %d)'
            % (epoch_id, step_id, self._anomaly_action,
               self._anomaly_streak))
        event_handler(FaultEvent(epoch_id, step_id, err, 'anomaly',
                                 self._anomaly_streak))
        if self._anomaly_streak > self._anomaly_skip_steps:
            self._anomaly_streak = 0
            if self._anomaly_action == 'rollback':
                from .distributed.resilience import FatalRPCError
                raise FatalRPCError(
                    'numeric anomaly persisted past %d skipped steps; '
                    'rolling back: %s'
                    % (self._anomaly_skip_steps, err)) from err
            raise err

    def stop(self):
        """Request the training loop exit at the next event boundary
        (reference trainer.py Trainer.stop semantics)."""
        self._stop_requested = True

    def save_params(self, param_path):
        with scope_guard(self.scope):
            io_mod.save_persistables(self.exe, param_path,
                                     main_program=self.train_program)

    def test(self, reader, feed_order):
        """Mean metrics of the eval-mode program over the reader."""
        # clone once: a fresh clone per call would get a fresh Program
        # uid and force a full XLA recompile of the eval graph each time
        if getattr(self, '_test_program', None) is None:
            self._test_program = self.train_program.clone(for_test=True)
        test_program = self._test_program
        fetch = [v.name for v in self.train_outputs]
        totals = None
        n = 0
        with scope_guard(self.scope):
            for data in reader():
                feed = dict(zip(feed_order, data))
                vals = self.exe.run(test_program, feed=feed,
                                    fetch_list=fetch)
                vals = [np.asarray(v) for v in vals]
                totals = vals if totals is None else [
                    t + v for t, v in zip(totals, vals)]
                n += 1
        return [t / max(n, 1) for t in (totals or [])]
