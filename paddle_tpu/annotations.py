"""API annotations (reference python/paddle/fluid/annotations.py)."""
from __future__ import annotations

import functools
import sys

__all__ = ['deprecated']


def deprecated(since, instead, extra_message=''):
    def decorator(func):
        err_msg = 'API {0} is deprecated since {1}. Please use {2} ' \
                  'instead.'.format(func.__name__, since, instead)
        if len(extra_message) != 0:
            err_msg += '\n' + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            print(err_msg, file=sys.stderr)
            return func(*args, **kwargs)
        wrapper.__doc__ = (func.__doc__ or '') + '\n    ' + err_msg
        return wrapper
    return decorator
