"""Device-mesh management (the TPU answer to the reference's
NCCLContextMap places/ranks bookkeeping, platform/nccl_helper.h:81).

A MeshConfig names logical axes and their sizes; build() lays the
physical devices out as a jax.sharding.Mesh. Axis order follows the
ICI-locality rule of thumb: model axes (tp, sp, ep) innermost so their
collectives ride the fastest links, dp/pp outermost (their transfers are
smaller or overlappable)."""
from __future__ import annotations

import contextlib

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ['MeshConfig', 'get_mesh', 'set_mesh', 'mesh_scope']

# canonical axis order, outermost first
AXIS_ORDER = ('pp', 'dp', 'ep', 'sp', 'tp')


class MeshConfig(object):
    """Named parallel-axis sizes, e.g. MeshConfig(dp=2, tp=4)."""

    def __init__(self, devices=None, **axis_sizes):
        for ax in axis_sizes:
            if ax not in AXIS_ORDER:
                raise ValueError('unknown mesh axis %r (valid: %s)'
                                 % (ax, AXIS_ORDER))
        self.axis_sizes = {ax: int(axis_sizes.get(ax, 1))
                           for ax in AXIS_ORDER}
        self.devices = devices

    @property
    def size(self):
        return int(np.prod(list(self.axis_sizes.values())))

    def build(self):
        devices = self.devices if self.devices is not None \
            else jax.devices()[:self.size]
        if len(devices) < self.size:
            raise ValueError('mesh needs %d devices, have %d'
                             % (self.size, len(devices)))
        axes = [ax for ax in AXIS_ORDER if self.axis_sizes[ax] > 1]
        if not axes:
            axes = ['dp']
        shape = [self.axis_sizes[ax] for ax in axes]
        arr = np.array(devices[:int(np.prod(shape))]).reshape(shape)
        return Mesh(arr, tuple(axes))


_current_mesh = None


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh():
    return _current_mesh


@contextlib.contextmanager
def mesh_scope(mesh):
    global _current_mesh
    prev, _current_mesh = _current_mesh, mesh
    try:
        yield mesh
    finally:
        _current_mesh = prev


def named_sharding(mesh, spec):
    """spec: tuple of axis-name/None per dim (a PartitionSpec in tuple
    form, e.g. ('dp', None) or (None, 'tp'))."""
    if spec is None:
        return NamedSharding(mesh, PartitionSpec())
    names = set(mesh.axis_names)
    cleaned = tuple(s if (s in names or s is None) else None for s in spec)
    return NamedSharding(mesh, PartitionSpec(*cleaned))
