"""Device-mesh management (the TPU answer to the reference's
NCCLContextMap places/ranks bookkeeping, platform/nccl_helper.h:81).

A MeshConfig names logical axes and their sizes; build() lays the
physical devices out as a jax.sharding.Mesh. Axis order follows the
ICI-locality rule of thumb: model axes (tp, sp, ep) innermost so their
collectives ride the fastest links, dp/pp outermost (their transfers are
smaller or overlappable)."""
from __future__ import annotations

import contextlib

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ['MeshConfig', 'get_mesh', 'set_mesh', 'mesh_scope', 'fit_spec']

# canonical axis order, outermost first
AXIS_ORDER = ('pp', 'dp', 'ep', 'sp', 'tp')


class MeshConfig(object):
    """Named parallel-axis sizes, e.g. MeshConfig(dp=2, tp=4)."""

    def __init__(self, devices=None, **axis_sizes):
        for ax in axis_sizes:
            if ax not in AXIS_ORDER:
                raise ValueError('unknown mesh axis %r (valid: %s)'
                                 % (ax, AXIS_ORDER))
        self.axis_sizes = {ax: int(axis_sizes.get(ax, 1))
                           for ax in AXIS_ORDER}
        self.devices = devices

    @classmethod
    def from_spec(cls, shape, devices=None):
        """Parse an axis-spec string ('dp=2,tp=4'; ''/None = pure data
        parallelism over every local device) into a MeshConfig."""
        shape = str(shape or '').strip()
        if not shape:
            n = len(devices) if devices is not None else len(jax.devices())
            return cls(devices=devices, dp=n)
        sizes = {}
        for part in shape.split(','):
            part = part.strip()
            if not part:
                continue
            if '=' not in part:
                raise ValueError(
                    'mesh shape entry %r is not axis=size' % part)
            ax, n = part.split('=', 1)
            sizes[ax.strip()] = int(n)
        return cls(devices=devices, **sizes)

    @classmethod
    def from_flags(cls, devices=None):
        """Build from FLAGS_mesh_shape so tools/tests construct meshes
        without hand-wiring axis sizes."""
        from .. import flags
        return cls.from_spec(flags.get_flag('mesh_shape', ''),
                             devices=devices)

    @property
    def size(self):
        return int(np.prod(list(self.axis_sizes.values())))

    def build(self):
        devices = self.devices if self.devices is not None \
            else jax.devices()[:self.size]
        if len(devices) < self.size:
            raise ValueError('mesh needs %d devices, have %d'
                             % (self.size, len(devices)))
        axes = [ax for ax in AXIS_ORDER if self.axis_sizes[ax] > 1]
        if not axes:
            axes = ['dp']
        shape = [self.axis_sizes[ax] for ax in axes]
        arr = np.array(devices[:int(np.prod(shape))]).reshape(shape)
        return Mesh(arr, tuple(axes))


_current_mesh = None


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh():
    return _current_mesh


@contextlib.contextmanager
def mesh_scope(mesh):
    """Install `mesh` (a jax Mesh, or a MeshConfig to build) as the
    current mesh for the scope; the previous mesh is restored even when
    the body raises."""
    global _current_mesh
    if isinstance(mesh, MeshConfig):
        mesh = mesh.build()
    prev, _current_mesh = _current_mesh, mesh
    try:
        yield mesh
    finally:
        _current_mesh = prev


def fit_spec(spec, shape, mesh):
    """Adapt a PartitionSpec-in-tuple-form to a (possibly different)
    mesh: drop axis names the mesh does not have, and drop axes whose
    size does not divide the dim they shard — the reshard-on-restore
    rule (checkpoint/restore.py) that lets a spec recorded on a
    dp=2,tp=2 save apply on a tp=4 (or dp=4, or single-device) mesh.
    Entries may be an axis name, a tuple/list of names, or None."""
    if spec is None:
        return None
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    for dim, entry in zip(shape, tuple(spec) + (None,) * len(shape)):
        if entry is None:
            out.append(None)
            continue
        names = list(entry) if isinstance(entry, (tuple, list)) else [entry]
        kept, factor = [], 1
        for ax in names:
            n = axis_size.get(ax)
            if n is None:
                continue
            if int(dim) % (factor * n) != 0:
                continue
            kept.append(ax)
            factor *= n
        if not kept:
            out.append(None)
        elif len(kept) == 1:
            out.append(kept[0])
        else:
            out.append(tuple(kept))
    return tuple(out[:len(shape)])


def named_sharding(mesh, spec):
    """spec: tuple of axis-name/None per dim (a PartitionSpec in tuple
    form, e.g. ('dp', None) or (None, 'tp')); an entry may also be a
    tuple of names for a multi-axis dim."""
    if spec is None:
        return NamedSharding(mesh, PartitionSpec())
    names = set(mesh.axis_names)

    def _clean(s):
        if s is None:
            return None
        if isinstance(s, (tuple, list)):
            kept = tuple(a for a in s if a in names)
            return kept if kept else None
        return s if s in names else None

    return NamedSharding(mesh, PartitionSpec(*(_clean(s) for s in spec)))
