"""DistributedStrategy: one config object for the whole parallel stack
(the TPU-era analog of the reference's BuildStrategy/ExecutionStrategy
pair plus the transpiler's config, SURVEY.md §2.6)."""
from __future__ import annotations

from .mesh import MeshConfig

__all__ = ['DistributedStrategy']


class DistributedStrategy(object):
    """Axis sizes plus engine knobs.

    dp/tp/sp/pp/ep: parallel degrees (product must divide device count)
    sharded_optimizer: ZeRO-1-style optimizer-state sharding over dp
        (the reference BuildStrategy.kReduce analog; consumed by
        ParallelExecutor._bcast_params)
    sharded_params: ZeRO-3-style PARAMETER sharding over dp on top of
        the optimizer-state sharding (implies sharded_optimizer).
        Beyond-reference: per-device parameter memory drops ~dp-fold;
        GSPMD inserts the gather-on-use / reduce-scatter collectives.
        Parameters whose no dim divides dp stay replicated.
    micro_batches: pipeline microbatch count, consumed by the pp engine
        (parallel/pipeline.py pipeline_apply's n_micro)
    """

    def __init__(self, dp=1, tp=1, sp=1, pp=1, ep=1,
                 sharded_optimizer=False, sharded_params=False,
                 micro_batches=1):
        self.dp, self.tp, self.sp, self.pp, self.ep = dp, tp, sp, pp, ep
        self.sharded_optimizer = sharded_optimizer or sharded_params
        self.sharded_params = sharded_params
        self.micro_batches = micro_batches

    def mesh_config(self, devices=None):
        return MeshConfig(devices=devices, dp=self.dp, tp=self.tp,
                          sp=self.sp, pp=self.pp, ep=self.ep)

    @property
    def world_size(self):
        return self.dp * self.tp * self.sp * self.pp * self.ep
