"""Pipeline-parallel lowering of a Program training step.

Consumes `DistributedStrategy(pp=K, micro_batches=M)` from the
ParallelExecutor: a device segment whose ops carry `pp_stage`
annotations (parallel.api.pipeline_stage_guard) compiles into

    pre ops -> pipeline_apply(uniform stages over 'pp') -> post ops
    -> whole-graph jax.grad -> optimizer ops

instead of the per-op emission path. The program's per-op backward ops
are NOT emitted in this mode: the gradient of the whole pipelined
forward comes from one jax.value_and_grad, which differentiates through
the ppermute/scan schedule (the 1F1B-equivalent backward falls out of
XLA). This is the TPU-native design decision: under pipelining the
backward must interleave with the schedule, so it cannot be a per-op op
list — whole-graph autodiff replaces it. (No reference analog: the 2018
codebase has no pipeline engine; SURVEY §2.11 'beyond ref'.)

Requirements checked at compile time: the annotated stages must be
UNIFORM (same op sequence, same parameter shapes — transformer blocks),
carry exactly one activation in/out, and contain no RNG ops; gradient
clipping/regularization ops (which live between backward and optimizer)
are not supported under pp.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp

from .pipeline import pipeline_apply

__all__ = ['segment_has_pp', 'build_pp_segment_fn']


def segment_has_pp(segment):
    return any(op.attr('pp_stage', None) is not None for op in segment.ops)


def _reads_writes(ops):
    reads, writes = [], set()
    for op, _off in ops:
        for n in op.input_arg_names():
            if n not in writes and n not in reads:
                reads.append(n)
        writes.update(op.output_arg_names())
    return reads, writes


def _partition(segment):
    """Split segment ops into pre / stages / post forward ops, plus
    optimizer ops; backward ops are dropped (whole-graph grad)."""
    pre, stages, post, opt = [], OrderedDict(), [], []
    for op, off in zip(segment.ops, segment.op_offsets):
        role = op.attr('op_role', 'forward')
        if role == 'optimize':
            opt.append((op, off))
            continue
        if role == 'backward':
            if op.type in ('squared_l2_norm', 'clip', 'clip_by_norm'):
                raise NotImplementedError(
                    'gradient clipping is not supported under pipeline '
                    'parallelism (grads come from whole-graph autodiff)')
            continue
        st = op.attr('pp_stage', None)
        if st is None:
            (post if stages else pre).append((op, off))
        else:
            stages.setdefault(int(st), []).append((op, off))
    return pre, stages, post, opt


def _sig_attrs(op):
    """Attrs that must MATCH across stages for uniformity: everything
    except the stage id itself (stage 0's trace is reused for every
    stage, so any attr divergence would silently compute stage 0's op)."""
    return {k: v for k, v in op.attrs.items()
            if k not in ('pp_stage', 'op_role', 'op_namescope')
            and not k.startswith('__')}


def _validate_stages(stages, block):
    keys = sorted(stages)
    sigs = [[op.type for op, _ in stages[k]] for k in keys]
    if any(s != sigs[0] for s in sigs[1:]):
        raise ValueError('pipeline stages must be uniform (same op '
                         'sequence per stage); got %s' %
                         {k: len(stages[k]) for k in keys})
    for k in keys[1:]:
        for (op0, _), (opk, _) in zip(stages[keys[0]], stages[k]):
            if _sig_attrs(op0) != _sig_attrs(opk):
                raise ValueError(
                    'pipeline stages not uniform: op %r attrs differ '
                    'between stage %d and stage %d (%s vs %s)'
                    % (op0.type, keys[0], k, _sig_attrs(op0),
                       _sig_attrs(opk)))
    from ..registry import _REGISTRY
    for op, _ in stages[keys[0]]:
        if _REGISTRY[op.type].stateful:
            raise NotImplementedError(
                'RNG op %r inside a pipeline stage' % op.type)
    return keys


def _stage_io(stages, keys, block):
    """Per-stage (param_names, x_in, x_out). Params = persistable reads;
    the single non-persistable read is the carried activation."""
    infos = []
    for k in keys:
        reads, writes = _reads_writes(stages[k])
        params, acts = [], []
        for n in reads:
            var = block.var_recursive(n)
            (params if var.persistable else acts).append(n)
        if len(acts) != 1:
            raise ValueError(
                'pipeline stage %d must carry exactly one activation '
                '(got inputs %s)' % (k, acts))
        infos.append({'params': params, 'x_in': acts[0], 'writes': writes})
    # x_out of stage k = the write that stage k+1 (or the post ops) reads
    for i, k in enumerate(keys):
        nxt = infos[i + 1]['x_in'] if i + 1 < len(keys) else None
        if nxt is not None and nxt in infos[i]['writes']:
            infos[i]['x_out'] = nxt
        else:
            # last stage: its final op's output is the region output
            infos[i]['x_out'] = stages[k][-1][0].output_arg_names()[-1]
    # parameter lists must be shape-uniform across stages
    shapes0 = [tuple(block.var_recursive(n).shape)
               for n in infos[0]['params']]
    for info in infos[1:]:
        shapes = [tuple(block.var_recursive(n).shape)
                  for n in info['params']]
        if shapes != shapes0:
            raise ValueError('pipeline stage parameter shapes differ: '
                             '%s vs %s' % (shapes0, shapes))
    return infos


def build_pp_segment_fn(pe, segment, block, program):
    """The seg_fn for a pp-annotated device segment (same signature the
    executor jits: (donated, const, rng_key) -> outputs tuple)."""
    from ..executor import EmitContext
    from .. import registry

    strategy = pe._strategy
    mesh = pe.mesh
    bn_local = getattr(pe, '_bn_local_stats', None)
    n_micro = max(int(strategy.micro_batches or 0), strategy.pp)
    loss_name = pe._loss_name
    if not loss_name:
        raise ValueError('pipeline parallelism needs '
                         'ParallelExecutor(loss_name=...)')

    pre, stages, post, opt = _partition(segment)
    keys = _validate_stages(stages, block)
    infos = _stage_io(stages, keys, block)
    stage0_ops = stages[keys[0]]
    region_out = infos[-1]['x_out']
    region_in = infos[0]['x_in']

    # param -> grad var name, from the optimizer ops. Any grad
    # POST-PROCESSING (clipping, weight decay) renames the optimizer's
    # Grad input away from the raw autodiff name — seg_fn would write
    # the raw gradient under that name and silently drop the transform,
    # so refuse instead.
    from ..framework import grad_var_name
    grad_of = {}
    for op, _ in opt:
        if op.input('Param'):
            p = op.single_input('Param')
            g = op.single_input('Grad')
            if g != grad_var_name(p):
                raise NotImplementedError(
                    'pipeline parallelism: optimizer consumes a '
                    'transformed gradient %r for param %r (gradient '
                    'clipping / regularization are not supported under '
                    'pp — grads come from whole-graph autodiff)' % (g, p))
            grad_of[p] = g

    is_test = program._is_test
    amp = getattr(program, '_use_bf16', False)
    out_names = segment.out_names

    def emit_ops(ctx, op_list):
        for op, off in op_list:
            ctx._op_index = off
            ctx._block_pos = off
            registry._REGISTRY[op.type].emit(ctx, op)

    def seg_fn(donated, const, rng_key):
        env = {}
        env.update(const)
        env.update(donated)
        diff_params = {p: env[p] for p in sorted(grad_of) if p in env}

        def loss_fn(pvals):
            env2 = dict(env)
            env2.update(pvals)
            ctx = EmitContext(env2, block, rng_key, is_test, amp=amp)
            ctx.mesh = mesh
            ctx.bn_local_stats = bn_local
            emit_ops(ctx, pre)

            def stage_fn(plist, x):
                e3 = dict(zip(infos[0]['params'], plist))
                e3[region_in] = x
                sctx = EmitContext(e3, block, rng_key, is_test, amp=amp)
                sctx.mesh = mesh
                sctx.bn_local_stats = bn_local
                emit_ops(sctx, stage0_ops)
                return e3[infos[0]['x_out']]

            stacked = [jnp.stack([env2[info['params'][i]]
                                  for info in infos])
                       for i in range(len(infos[0]['params']))]
            x = env2[region_in]
            B = x.shape[0]
            if B % n_micro != 0:
                raise ValueError('batch %d not divisible by %d '
                                 'microbatches' % (B, n_micro))
            x_m = x.reshape((n_micro, B // n_micro) + x.shape[1:])
            out = pipeline_apply(stage_fn, mesh, n_micro, stacked, x_m)
            env2[region_out] = out.reshape((B,) + out.shape[2:])
            emit_ops(ctx, post)
            return env2[loss_name], env2

        (_, fwd_env), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(diff_params)
        for name in out_names:
            if name in fwd_env:
                env[name] = fwd_env[name]
        for p, g in grads.items():
            env[grad_of[p]] = g
        ctx = EmitContext(env, block, rng_key, is_test, amp=amp)
        ctx.mesh = mesh
        ctx.bn_local_stats = bn_local
        emit_ops(ctx, opt)
        return tuple(env[n] for n in out_names)

    return seg_fn
