"""Model-parallel layers: tensor-parallel fc/embedding, sequence-parallel
constraints, expert-parallel MoE.

The Megatron-style pair done the GSPMD way (scaling-book recipe): instead
of manual allreduce ops, parameters carry shard annotations and
activations get sharding constraints; XLA inserts the all-gather /
reduce-scatter / psum on ICI.

column_parallel_fc: weight [D, H] sharded (None, 'tp') -> output sharded
    on features.
row_parallel_fc: weight [D, H] sharded ('tp', None), input sharded on
    features -> XLA emits the psum that completes the matmul.
vocab_parallel_embedding: table sharded over vocab rows.
"""
from __future__ import annotations

from .. import layers as L
from ..layer_helper import LayerHelper
from .api import shard_tensor, sharding_constraint

__all__ = ['column_parallel_fc', 'row_parallel_fc',
           'vocab_parallel_embedding', 'sequence_parallel_scope',
           'moe_layer', 'ring_attention']


def ring_attention(q, k, v, causal=True, sm_scale=None, name=None):
    """Context-parallel attention (parallel/ring_attention.py): q/k/v
    [B, H, T, dh] with T sharded over 'sp'; K/V blocks rotate the ring
    via ppermute with online-softmax accumulation. Exactly equals full
    softmax attention; O(T/n) per-device memory. Falls back to plain
    fused attention off-mesh."""
    helper = LayerHelper('ring_attention', name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(
        type='ring_attention',
        inputs={'Q': [q], 'K': [k], 'V': [v]},
        outputs={'Out': [out]},
        attrs={'causal': causal, 'sm_scale': sm_scale})
    out.lod_level = q.lod_level
    return out


def _fc(input, size, param_spec, act=None, param_attr=None, bias_attr=None,
        num_flatten_dims=None, name=None):
    """L.fc with the weight annotated param_spec. Delegates to the standard
    fc builder (one code path) and annotates the created parameter; the
    weight gets a known name so it can be found afterwards. Bias vars are
    tiny and stay replicated."""
    from .. import unique_name
    from ..param_attr import ParamAttr
    if num_flatten_dims is None:
        # contract the feature (last) dim only: parallel fc keeps
        # batch/time structure ([B, T, D] @ [D, H] -> [B, T, H])
        num_flatten_dims = max(len(input.shape) - 1, 1)
    if param_attr is None:
        param_attr = ParamAttr(
            name=unique_name.generate(name or 'parallel_fc') + '.w')
    out = L.fc(input=input, size=size, act=act,
               num_flatten_dims=num_flatten_dims, param_attr=param_attr,
               bias_attr=bias_attr, name=name)
    w = input.block.program.global_block().var(param_attr.name)
    shard_tensor(w, param_spec)
    return out


def column_parallel_fc(input, size, act=None, param_attr=None,
                       bias_attr=None, axis='tp', name=None):
    """Output-feature-sharded linear: y[:, shard] = x @ W[:, shard]."""
    out = _fc(input, size, (None, axis), act=act, param_attr=param_attr,
              bias_attr=bias_attr, name=name)
    return sharding_constraint(out, ('dp', axis))


def row_parallel_fc(input, size, act=None, param_attr=None,
                    bias_attr=None, axis='tp', name=None):
    """Input-feature-sharded linear; XLA inserts the completing psum."""
    out = _fc(input, size, (axis, None), act=act, param_attr=param_attr,
              bias_attr=bias_attr, name=name)
    return sharding_constraint(out, ('dp', None))


def vocab_parallel_embedding(input, size, param_attr=None, dtype='float32',
                             axis='tp', name=None):
    """Embedding with the table sharded over vocab rows (the TP analog of
    the reference's distributed lookup table, SURVEY.md §2.11)."""
    helper = LayerHelper('embedding', param_attr=param_attr, name=name)
    w = helper.create_parameter(attr=helper.param_attr, shape=size,
                                dtype=dtype)
    shard_tensor(w, (axis, None))
    tmp = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='lookup_table',
                     inputs={'Ids': [input], 'W': [w]},
                     outputs={'Out': [tmp]}, attrs={'padding_idx': -1})
    return tmp


def sequence_parallel_scope(x, axis='sp'):
    """Pin the time axis of [B, T, D] activations to the sp mesh axis —
    sequence parallelism for the memory-heavy elementwise/norm regions
    (Korthikanti et al.; PAPERS.md)."""
    return sharding_constraint(x, ('dp', axis, None))


def moe_layer(input, num_experts, hidden_size, act='gelu', k=1,
              dispatch='topk', capacity_factor=2.0, aux_loss=False,
              param_attr=None, axis='ep', name=None):
    """Expert-parallel MoE FFN.

    Experts' weights are stacked [E, D, H]/[E, H, D] and sharded over the
    'ep' axis. dispatch='topk' (default) is GShard-style capacity-bounded
    routing: per-expert buffers hold ceil(S*k*capacity_factor/E) tokens,
    overflow tokens are dropped, and expert compute is independent of
    num_experts at fixed k. dispatch='dense' combines every token with
    every expert (exact, O(E) compute -- small-E fallback). See
    ops/moe_ops.py.

    aux_loss=True additionally returns the GShard load-balance loss
    scalar (add it to the training objective, typically weighted 1e-2)."""
    helper = LayerHelper('moe', param_attr=param_attr, name=name)
    D = input.shape[-1]
    dtype = input.dtype

    nfd = max(len(input.shape) - 1, 1)
    gate = L.fc(input=input, size=num_experts, act='softmax',
                num_flatten_dims=nfd)         # [..., E]

    w_up = helper.create_parameter(
        attr=helper.param_attr, shape=[num_experts, D, hidden_size],
        dtype=dtype)
    shard_tensor(w_up, (axis, None, None))
    w_down = helper.create_parameter(
        attr=helper.param_attr, shape=[num_experts, hidden_size, D],
        dtype=dtype)
    shard_tensor(w_down, (axis, None, None))

    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='moe_ffn',
        inputs={'X': [input], 'Gate': [gate], 'WUp': [w_up],
                'WDown': [w_down]},
        outputs={'Out': [out]},
        attrs={'act': act, 'k': k, 'dispatch': dispatch,
               'capacity_factor': capacity_factor})
    out.lod_level = input.lod_level
    if not aux_loss:
        return out
    aux = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='moe_aux_loss', inputs={'Gate': [gate]},
                     outputs={'Out': [aux]})
    return out, aux
