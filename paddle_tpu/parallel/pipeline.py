"""Pipeline parallelism: GPipe microbatch schedule over the 'pp' mesh axis.

No reference analog (SURVEY.md §2.11: PP absent in the 2018 codebase); this
is the TPU-native design: all stages share one code path (SPMD), stage
weights are STACKED on a leading [n_stages, ...] axis and sharded over
'pp', and activations rotate stage-to-stage with lax.ppermute inside a
lax.scan over schedule ticks -- the classic collective-pipeline formulation
(scaling-book). Autodiff through the schedule gives the 1F1B-equivalent
backward for free (XLA schedules the reverse ppermutes).

Works standalone on any mesh with a 'pp' axis; composable with dp/tp axes
(stage_fn's internals may carry their own sharding constraints).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['pipeline_apply', 'stack_stage_params']


def stack_stage_params(per_stage_params):
    """[{k: leaf}, ...] per stage -> one pytree with leaves stacked on a
    leading n_stages axis (the shardable layout)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def pipeline_apply(stage_fn, mesh, n_micro, params_stacked, x_micro,
                   axis='pp'):
    """Run x_micro ([M, mb, ...]) through n_stages pipelined stages.

    stage_fn(stage_params, x) -> y must map activation shapes to
    themselves (uniform-stage pipeline, transformer-block style).
    params_stacked: pytree with leading n_stages axis on every leaf.
    Returns [M, mb, ...] outputs (last stage's results, in microbatch
    order).
    """
    n_stages = mesh.shape[axis]
    M = n_micro
    T = M + n_stages - 1

    def per_device(params_local, xs):
        # params_local: leaves [1, ...] (this device's stage); xs: full
        # [M, mb, ...] (replicated; only stage 0 reads it)
        params = jax.tree_util.tree_map(lambda l: l[0], params_local)
        s = jax.lax.axis_index(axis)
        mb_shape = xs.shape[1:]
        carry = jnp.zeros(mb_shape, xs.dtype)
        outputs = jnp.zeros((M,) + mb_shape, xs.dtype)

        def tick(state, t):
            carry, outputs = state
            inject = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            x_in = jnp.where(s == 0, inject, carry)
            y = stage_fn(params, x_in)
            # the microbatch index this device just finished
            m = t - s
            is_valid_out = jnp.logical_and(
                s == n_stages - 1,
                jnp.logical_and(m >= 0, m < M))
            outputs = jax.lax.cond(
                is_valid_out,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(m, 0, M - 1), axis=0),
                lambda o: o, outputs)
            nxt = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages)
                          for i in range(n_stages)])
            return (nxt, outputs), None

        (carry, outputs), _ = jax.lax.scan(
            tick, (carry, outputs), jnp.arange(T))
        # zero non-final-stage buffers, then psum: the global result is the
        # last stage's outputs replicated across 'pp'
        outputs = jnp.where(s == n_stages - 1, outputs, 0)
        return jax.lax.psum(outputs, axis)

    in_specs = (
        jax.tree_util.tree_map(lambda _: P(axis), params_stacked),
        P(),
    )
    # manual ONLY over 'pp' (axis_names): the other mesh axes stay
    # automatic, so dp batch sharding propagates through the schedule and
    # tp/sp sharding constraints inside stage_fn remain legal — the
    # partial-manual composition that makes pp x dp x tp one executable
    f = jax.shard_map(per_device, mesh=mesh, axis_names=frozenset({axis}),
                      in_specs=in_specs, out_specs=P(),
                      check_vma=False)
    return f(params_stacked, x_micro)
