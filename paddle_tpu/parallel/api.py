"""Sharding annotations on IR Variables.

shard_tensor(var, spec) records a PartitionSpec-shaped tuple on the
Variable; the ParallelExecutor places feeds/params accordingly and GSPMD
propagates + inserts collectives. sharding_constraint(x, spec) additionally
pins an INTERMEDIATE value's layout inside the compiled block (the
with_sharding_constraint escape hatch for when propagation needs a hint)."""
from __future__ import annotations

import jax

from ..layer_helper import LayerHelper
from ..registry import register_op, op_emitter, same_shape_infer
from .mesh import get_mesh, named_sharding

__all__ = ['shard_tensor', 'sharding_constraint',
           'pipeline_stage_guard']


def shard_tensor(var, spec):
    """Annotate a Variable (param or feed) with a dim->axis spec, e.g.
    shard_tensor(w, (None, 'tp'))."""
    var.dist_attr = tuple(spec)
    return var


@op_emitter('sharding_constraint')
def _sharding_constraint_emit(ctx, op):
    x = ctx.get(op.single_input('X'))
    mesh = getattr(ctx, 'mesh', None)
    spec = tuple(op.attr('spec'))
    if mesh is None:
        ctx.set(op.single_output('Out'), x)
        return
    # pad the spec to the runtime rank (padded-sequence vars gain a time
    # axis at position 1); an over-long spec is a caller bug -- raise
    # rather than silently sharding the wrong dim
    if len(spec) < x.ndim:
        spec = (spec[0],) + (None,) * (x.ndim - len(spec)) + spec[1:]
    elif len(spec) > x.ndim:
        raise ValueError(
            'sharding_constraint: spec %s has rank %d but value has rank '
            '%d' % (spec, len(spec), x.ndim))
    ctx.set(op.single_output('Out'),
            jax.lax.with_sharding_constraint(x, named_sharding(mesh, spec)))


register_op('sharding_constraint', infer_shape=same_shape_infer())


def _sharding_constraint_grad(op, block):
    from ..framework import grad_var_name
    return [dict(type='sharding_constraint',
                 inputs={'X': [grad_var_name(op.single_output('Out'))]},
                 outputs={'Out': [grad_var_name(op.single_input('X'))]},
                 attrs=dict(op.attrs))]


register_op('sharding_constraint', grad=_sharding_constraint_grad)


def sharding_constraint(x, spec, name=None):
    helper = LayerHelper('sharding_constraint', name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type='sharding_constraint', inputs={'X': [x]},
                     outputs={'Out': [out]}, attrs={'spec': list(spec)})
    return out


import contextlib


@contextlib.contextmanager
def pipeline_stage_guard(stage):
    """Ops appended inside carry attrs['pp_stage']=stage — the unit the
    pipeline-parallel lowering (parallel/pp_lowering.py) partitions the
    program on. No reference analog (the 2018 codebase has no pp); the
    shape follows the reference's device_guard op-placement idiom."""
    from ..framework import default_main_program
    prog = default_main_program()
    prev = getattr(prog, '_pp_stage', None)
    prog._pp_stage = int(stage)
    try:
        yield
    finally:
        prog._pp_stage = prev
