"""Ring attention: context parallelism for long sequences.

No reference analog — the reference caps sequence length at what one
GPU's memory holds (its Transformer configs top out at T=256,
ref:benchmark/fluid/models/transformer.py). This is the TPU-native
long-context mechanism the SURVEY's scale goals require: the sequence
axis is sharded over the 'sp' mesh axis, every device keeps only its
own Q/K/V blocks, and K/V blocks rotate around the ring via
`lax.ppermute` over ICI while each device folds one block per step into
an online-softmax accumulator (the flash-attention recurrence, applied
ring-step-wise). Peak per-device score memory drops from O(T²) to
O(T²/n²) and K/V memory to O(T/n) — sequence length scales linearly
with ring size at constant memory — while the ppermute traffic
overlaps compute on the ICI torus.

Causality is handled at block granularity: a key block strictly ahead
of the query block contributes nothing (its scores are fully masked,
and the online-softmax max is guarded so all-masked steps are exact
no-ops, not NaNs); the diagonal block gets the elementwise triangular
mask.

`ring_attention(...)` is the inside-shard_map recurrence;
`ring_attention_global(...)` wraps it in `shard_map` over the current
mesh so op emitters (ops/attention_ops.py 'ring_attention') can call it
on GSPMD-global arrays.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
    _SHARD_MAP_KW = {}
except ImportError:                      # older jax
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = {'check_rep': False}
from jax.sharding import PartitionSpec as P

__all__ = ['ring_attention', 'ring_attention_global',
           'ring_flash_attention', 'ring_flash_attention_global']

_NEG_INF = -1e30


def ring_attention(q, k, v, axis_name='sp', causal=True, sm_scale=None):
    """Inside-shard_map ring attention.

    q, k, v: [B, H, Tl, dh] — this device's sequence block (Tl = T/n).
    Returns [B, H, Tl, dh], exactly softmax(QK^T·scale [+mask]) V over
    the FULL sequence.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, Tl, dh = q.shape
    scale = sm_scale if sm_scale is not None else dh ** -0.5
    # keep operands in their own dtype (bf16 under AMP runs the MXU at
    # full rate); accumulate in fp32 via preferred_element_type
    qs = q * jnp.asarray(scale, q.dtype)

    q_pos = my * Tl + jnp.arange(Tl)                 # global query rows

    # remat: without it, scan saves every step's [Tl, Tl] probability
    # block for backward — O(Tl·T) residents, re-creating the memory
    # wall ring attention exists to remove. Recomputing the fold in the
    # backward pass keeps residuals at O(Tl·dh) per step (the standard
    # flash/ring backward trade).
    @jax.checkpoint
    def fold(acc, kb, vb, src):
        """One online-softmax update of acc=(o, m, l) with block src."""
        o, m, l = acc
        s = jnp.einsum('bhqd,bhkd->bhqk', qs, kb,
                       preferred_element_type=jnp.float32)  # [B,H,Tl,Tl]
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        blk_max = jnp.max(s, axis=-1)                # [B,H,Tl]
        m_new = jnp.maximum(m, blk_max)
        # all-masked step: m_new stays _NEG_INF; freeze it so the
        # correction exp(m - m_new) is exp(0), an exact no-op
        safe_m = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        corr = jnp.exp(jnp.where(m <= _NEG_INF / 2, safe_m, m) - safe_m)
        p = jnp.exp(s - safe_m[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            'bhqk,bhkd->bhqd', p.astype(v.dtype), vb,
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    perm = [(j, (j - 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, kb, vb = carry
        # rotate FIRST (blocks arrive from the next ring neighbour), so
        # the scan runs n-1 rotations instead of n — the local block is
        # folded in before the scan and a final rotation would be
        # discarded (XLA cannot DCE a collective inside scan)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        o, m, l = fold((o, m, l), kb, vb, (my + i) % n)
        return (o, m, l, kb, vb), None

    # derive initial carries FROM q so they inherit its varying-manual-
    # axes type: newer shard_map rejects scan carries whose input is a
    # plain constant but whose output varies over mesh axes
    zq = qs.astype(jnp.float32) * 0.0
    acc0 = fold((zq, zq[..., 0] + _NEG_INF, zq[..., 0]), k, v, my)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, acc0 + (k, v), jnp.arange(1, n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ring_spec(mesh, q, seq_axis, batch_axis, head_axis):
    """PartitionSpec for the [B, H, T, dh] operands, mapping each mesh
    axis only when it exists, is >1, and divides the dim (shard_map
    hard-errors on non-divisible dims where GSPMD would pad). Returns
    (spec, seq_ok)."""
    def axis(name, dim):
        if name and mesh is not None and name in mesh.axis_names \
                and mesh.shape[name] > 1 and dim % mesh.shape[name] == 0:
            return name
        return None
    seq_ok = axis(seq_axis, q.shape[2]) is not None
    spec = P(axis(batch_axis, q.shape[0]), axis(head_axis, q.shape[1]),
             seq_axis if seq_ok else None, None)
    return spec, seq_ok


def ring_attention_global(q, k, v, mesh, causal=True, sm_scale=None,
                          seq_axis='sp', batch_axis='dp',
                          head_axis='tp'):
    """GSPMD-global entry: q/k/v are [B, H, T, dh] global arrays; the
    sequence axis is sharded over `seq_axis`, batch over `batch_axis`,
    heads over `head_axis` (each only if present in the mesh).
    mesh=None (no mesh in scope) lowers to plain fused attention; so do
    meshes whose sp size does not divide T (shard_map cannot pad the way
    GSPMD constraints can)."""
    spec, seq_ok = _ring_spec(mesh, q, seq_axis, batch_axis, head_axis)
    if mesh is None or not seq_ok:
        # no ring: plain attention, operand dtype preserved (bf16 under
        # AMP runs the MXU at full rate), fp32 accumulation
        scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
        s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            T = q.shape[2]
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum('bhqk,bhkd->bhqd', p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32
                          ).astype(q.dtype)
    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, **_SHARD_MAP_KW)(q, k, v)


# ---------------------------------------------------------------------------
# ring x flash composition: the multi-chip long-context path.
# ---------------------------------------------------------------------------

def _kernel_enabled():
    """Real kernel on TPU; interpreter mode only when the
    pallas_interpret flag opts in (CPU tests) — same gate as the
    single-chip flash_attention wrapper."""
    from ..flags import get_flag
    return jax.default_backend() == 'tpu' or bool(
        get_flag('pallas_interpret'))


def _flash_block(q, kb, vb, causal, sm_scale):
    """Run the Pallas flash kernel over one KV block, returning the
    attention PARTIAL (o, lse) for later merging. q/kb/vb: [B,H,Tl,dh]."""
    from ..pallas.flash_attention import _fwd, _supported
    B, H, Tl, dh = q.shape
    qf = q.reshape(B * H, Tl, dh)
    kf = kb.reshape(B * H, Tl, dh)
    vf = vb.reshape(B * H, Tl, dh)
    scale = sm_scale if sm_scale is not None else dh ** -0.5
    if _supported(Tl, dh) and _kernel_enabled():
        o, lse = _fwd(qf, kf, vf, causal, scale,
                      jax.default_backend() != 'tpu')
        lse = lse[..., 0]
    else:
        # small/unaligned blocks: same partial computed with XLA ops
        s = jnp.einsum('btd,bsd->bts', qf * jnp.asarray(scale, qf.dtype),
                       kf, preferred_element_type=jnp.float32)
        if causal:
            mask = jnp.tril(jnp.ones((Tl, Tl), bool))
            s = jnp.where(mask[None], s, _NEG_INF)
        m = jnp.max(s, axis=-1)
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        o = (jnp.einsum('bts,bsd->btd', p.astype(vf.dtype), vf,
                        preferred_element_type=jnp.float32)
             / jnp.maximum(l, 1e-30)[..., None]).astype(qf.dtype)
        lse = jnp.where(m <= _NEG_INF / 2, _NEG_INF, m + jnp.log(
            jnp.maximum(l, 1e-30)))
    return (o.reshape(B, H, Tl, dh), lse.reshape(B, H, Tl))


def _merge_partials(o1, lse1, o2, lse2):
    """Combine two attention partials over disjoint key sets: the
    standard log-sum-exp merge (o_i are softmax-normalized within their
    own key set, lse_i the log partition)."""
    m = jnp.maximum(lse1, lse2)
    safe_m = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    w1 = jnp.exp(jnp.where(lse1 <= _NEG_INF / 2, _NEG_INF, lse1) - safe_m)
    w2 = jnp.exp(jnp.where(lse2 <= _NEG_INF / 2, _NEG_INF, lse2) - safe_m)
    denom = jnp.maximum(w1 + w2, 1e-30)
    o = (o1.astype(jnp.float32) * w1[..., None] +
         o2.astype(jnp.float32) * w2[..., None]) / denom[..., None]
    lse = safe_m + jnp.log(denom)
    lse = jnp.where((lse1 <= _NEG_INF / 2) & (lse2 <= _NEG_INF / 2),
                    _NEG_INF, lse)
    return o, lse                  # fp32: the ring carries fp32 until
                                   # the final cast


def ring_flash_attention(q, k, v, axis_name='sp', causal=True,
                         sm_scale=None):
    """Ring attention whose per-block work runs through the Pallas
    flash kernel: K/V blocks rotate the 'sp' ring (ppermute) and each
    arriving block is consumed as a flash partial (o, lse), merged with
    the running partial by log-sum-exp. Per-device memory stays
    O(Tl·dh) — the [Tl, Tl] score block of the plain ring fold never
    exists either — and the MXU work inside each step is the tiled
    flash kernel, so the composition scales T across chips (ring) and
    within a chip (flash) at once.

    Gradients: pallas kernels have no JVP rule, so the ring carries its
    own custom_vjp — the backward re-runs the ring, feeding each block
    through the flash dq/dkv kernels with the GLOBAL lse (the flash
    backward identity P = exp(S − lse_global) makes per-block grads
    additive), and each block's (dk, dv) travels the ring with it until
    it arrives back home on the final rotation.

    Exact: equals softmax(QKᵀ·scale [+causal])·V over the full ring
    sequence (parity-tested against ring_attention/naive)."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    o, _lse = _ring_flash(q, k, v, axis_name, causal, scale)
    return o.astype(q.dtype)


def _flash_bwd_block(q, kb, vb, o, lse, g, causal, scale):
    """Per-block flash backward with the global lse (fully-masked
    future blocks are skipped by the caller's lax.cond)."""
    from ..pallas.flash_attention import _bwd, _supported
    B, H, Tl, dh = q.shape

    def flat(x):
        return x.reshape(B * H, Tl, -1)
    if _supported(Tl, dh) and _kernel_enabled():
        dq, dk, dv = _bwd(flat(q), flat(kb), flat(vb), flat(o),
                          lse.reshape(B * H, Tl, 1), flat(g),
                          causal, scale,
                          jax.default_backend() != 'tpu')
    else:
        qf, kf, vf, of, gf = (flat(q), flat(kb), flat(vb), flat(o),
                              flat(g))
        s = jnp.einsum('btd,bsd->bts', qf * jnp.asarray(scale, qf.dtype),
                       kf, preferred_element_type=jnp.float32)
        if causal:
            mask = jnp.tril(jnp.ones((Tl, Tl), bool))
            s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.exp(s - lse.reshape(B * H, Tl, 1))
        delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dp = jnp.einsum('btd,bsd->bts', gf, vf,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq = jnp.einsum('bts,bsd->btd', ds.astype(kf.dtype), kf,
                        preferred_element_type=jnp.float32) * scale
        dk = jnp.einsum('bts,btd->bsd',
                        ds.astype(qf.dtype),
                        qf * jnp.asarray(scale, qf.dtype),
                        preferred_element_type=jnp.float32)
        dv = jnp.einsum('bts,btd->bsd', p.astype(gf.dtype), gf,
                        preferred_element_type=jnp.float32)
    shp = q.shape
    return (dq.reshape(shp).astype(q.dtype),
            dk.reshape(shp).astype(kb.dtype),
            dv.reshape(shp).astype(vb.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, scale):
    o, lse = _ring_flash_fwd_loop(q, k, v, axis_name, causal, scale)
    return o, lse


def _ring_flash_fwd_loop(q, k, v, axis_name, causal, scale):
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    o, lse = _flash_block(q, k, v, causal, scale)
    o = o.astype(jnp.float32)      # fp32 merge carry (like the exact
    perm = [(j, (j - 1) % n) for j in range(n)]   # ring's o/m/l)

    def step(carry, i):
        o, lse, kb, vb = carry
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        src = (my + i) % n

        def compute(kb, vb):
            return _flash_block(q, kb, vb, False, scale)

        def masked(kb, vb):
            # future block under causal: contributes nothing — skip the
            # kernel entirely (lse=-inf makes the merge a no-op)
            return (jnp.zeros_like(q),
                    jnp.full(q.shape[:3], _NEG_INF, jnp.float32))

        if causal:
            o_b, lse_b = jax.lax.cond(src < my, compute, masked, kb, vb)
        else:
            o_b, lse_b = compute(kb, vb)
        o, lse = _merge_partials(o, lse, o_b, lse_b)
        return (o, lse, kb, vb), None

    (o, lse, _, _), _ = jax.lax.scan(step, (o, lse, k, v),
                                     jnp.arange(1, n))
    return o, lse


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_flash_fwd_loop(q, k, v, axis_name, causal, scale)
    return (o, lse), (q, k, v, o, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, res, cots):
    q, k, v, o, lse = res
    g, _g_lse = cots       # lse is an internal byproduct; its cotangent
    # is zero in any loss built from o (asserted by usage)
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j - 1) % n) for j in range(n)]

    dq, dkb, dvb = _flash_bwd_block(q, k, v, o, lse, g, causal, scale)

    def step(carry, i):
        dq, kb, vb, dkb, dvb = carry
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        dkb = jax.lax.ppermute(dkb, axis_name, perm)
        dvb = jax.lax.ppermute(dvb, axis_name, perm)
        src = (my + i) % n

        def compute(kb, vb):
            return _flash_bwd_block(q, kb, vb, o, lse, g, False, scale)

        def masked(kb, vb):
            # future block under causal: all three grads are exactly
            # zero — skip both backward kernels
            return (jnp.zeros_like(q), jnp.zeros_like(kb),
                    jnp.zeros_like(vb))

        if causal:
            dq_b, dk_b, dv_b = jax.lax.cond(src < my, compute, masked,
                                            kb, vb)
        else:
            dq_b, dk_b, dv_b = compute(kb, vb)
        return (dq + dq_b, kb, vb, dkb + dk_b, dvb + dv_b), None

    (dq, _, _, dkb, dvb), _ = jax.lax.scan(
        step, (dq, k, v, dkb, dvb), jnp.arange(1, n))
    # one final rotation returns each block's accumulated grads home
    dk = jax.lax.ppermute(dkb, axis_name, perm)
    dv = jax.lax.ppermute(dvb, axis_name, perm)
    return dq, dk, dv


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention_global(q, k, v, mesh, causal=True,
                                sm_scale=None, seq_axis='sp',
                                batch_axis='dp', head_axis='tp'):
    """GSPMD-global entry for ring_flash_attention (mirrors
    ring_attention_global's sharding contract and fallbacks)."""
    if mesh is None:
        from ..pallas.flash_attention import flash_attention as _fa
        return _fa(q, k, v, causal=causal, sm_scale=sm_scale)
    spec, seq_ok = _ring_spec(mesh, q, seq_axis, batch_axis, head_axis)
    if not seq_ok:
        # mesh present but no usable sp axis: a bare pallas_call on
        # GSPMD-sharded globals would all-gather (no partitioning rule
        # for the custom call) — use the einsum fallback, which XLA
        # partitions over dp/tp like any other op
        return ring_attention_global(q, k, v, None, causal=causal,
                                     sm_scale=sm_scale)
    fn = functools.partial(ring_flash_attention, axis_name=seq_axis,
                           causal=causal, sm_scale=sm_scale)
    # pallas_call outputs carry no varying-mesh-axes annotation, which
    # the new shard_map's check_vma rejects — disable the check (the
    # per-device computation is manifestly per-shard)
    kw = dict(_SHARD_MAP_KW)
    if 'check_rep' not in kw:
        kw['check_vma'] = False
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, **kw)(q, k, v)
