"""Ring attention: context parallelism for long sequences.

No reference analog — the reference caps sequence length at what one
GPU's memory holds (its Transformer configs top out at T=256,
ref:benchmark/fluid/models/transformer.py). This is the TPU-native
long-context mechanism the SURVEY's scale goals require: the sequence
axis is sharded over the 'sp' mesh axis, every device keeps only its
own Q/K/V blocks, and K/V blocks rotate around the ring via
`lax.ppermute` over ICI while each device folds one block per step into
an online-softmax accumulator (the flash-attention recurrence, applied
ring-step-wise). Peak per-device score memory drops from O(T²) to
O(T²/n²) and K/V memory to O(T/n) — sequence length scales linearly
with ring size at constant memory — while the ppermute traffic
overlaps compute on the ICI torus.

Causality is handled at block granularity: a key block strictly ahead
of the query block contributes nothing (its scores are fully masked,
and the online-softmax max is guarded so all-masked steps are exact
no-ops, not NaNs); the diagonal block gets the elementwise triangular
mask.

`ring_attention(...)` is the inside-shard_map recurrence;
`ring_attention_global(...)` wraps it in `shard_map` over the current
mesh so op emitters (ops/attention_ops.py 'ring_attention') can call it
on GSPMD-global arrays.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
    _SHARD_MAP_KW = {}
except ImportError:                      # older jax
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_KW = {'check_rep': False}
from jax.sharding import PartitionSpec as P

__all__ = ['ring_attention', 'ring_attention_global']

_NEG_INF = -1e30


def ring_attention(q, k, v, axis_name='sp', causal=True, sm_scale=None):
    """Inside-shard_map ring attention.

    q, k, v: [B, H, Tl, dh] — this device's sequence block (Tl = T/n).
    Returns [B, H, Tl, dh], exactly softmax(QK^T·scale [+mask]) V over
    the FULL sequence.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, H, Tl, dh = q.shape
    scale = sm_scale if sm_scale is not None else dh ** -0.5
    # keep operands in their own dtype (bf16 under AMP runs the MXU at
    # full rate); accumulate in fp32 via preferred_element_type
    qs = q * jnp.asarray(scale, q.dtype)

    q_pos = my * Tl + jnp.arange(Tl)                 # global query rows

    # remat: without it, scan saves every step's [Tl, Tl] probability
    # block for backward — O(Tl·T) residents, re-creating the memory
    # wall ring attention exists to remove. Recomputing the fold in the
    # backward pass keeps residuals at O(Tl·dh) per step (the standard
    # flash/ring backward trade).
    @jax.checkpoint
    def fold(acc, kb, vb, src):
        """One online-softmax update of acc=(o, m, l) with block src."""
        o, m, l = acc
        s = jnp.einsum('bhqd,bhkd->bhqk', qs, kb,
                       preferred_element_type=jnp.float32)  # [B,H,Tl,Tl]
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        blk_max = jnp.max(s, axis=-1)                # [B,H,Tl]
        m_new = jnp.maximum(m, blk_max)
        # all-masked step: m_new stays _NEG_INF; freeze it so the
        # correction exp(m - m_new) is exp(0), an exact no-op
        safe_m = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
        corr = jnp.exp(jnp.where(m <= _NEG_INF / 2, safe_m, m) - safe_m)
        p = jnp.exp(s - safe_m[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            'bhqk,bhkd->bhqd', p.astype(v.dtype), vb,
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    perm = [(j, (j - 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, kb, vb = carry
        # rotate FIRST (blocks arrive from the next ring neighbour), so
        # the scan runs n-1 rotations instead of n — the local block is
        # folded in before the scan and a final rotation would be
        # discarded (XLA cannot DCE a collective inside scan)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        o, m, l = fold((o, m, l), kb, vb, (my + i) % n)
        return (o, m, l, kb, vb), None

    # derive initial carries FROM q so they inherit its varying-manual-
    # axes type: newer shard_map rejects scan carries whose input is a
    # plain constant but whose output varies over mesh axes
    zq = qs.astype(jnp.float32) * 0.0
    acc0 = fold((zq, zq[..., 0] + _NEG_INF, zq[..., 0]), k, v, my)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, acc0 + (k, v), jnp.arange(1, n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_global(q, k, v, mesh, causal=True, sm_scale=None,
                          seq_axis='sp', batch_axis='dp',
                          head_axis='tp'):
    """GSPMD-global entry: q/k/v are [B, H, T, dh] global arrays; the
    sequence axis is sharded over `seq_axis`, batch over `batch_axis`,
    heads over `head_axis` (each only if present in the mesh).
    mesh=None (no mesh in scope) lowers to plain fused attention; so do
    meshes whose sp size does not divide T (shard_map cannot pad the way
    GSPMD constraints can)."""
    def _divisible_axis(name, dim):
        # map a mesh axis into the shard_map spec only when it exists,
        # is >1, and divides the dim — otherwise replicate that dim
        # (GSPMD pads non-divisible dims; shard_map hard-errors)
        if name and mesh is not None and name in mesh.axis_names \
                and mesh.shape[name] > 1 and dim % mesh.shape[name] == 0:
            return name
        return None

    if mesh is None or \
            _divisible_axis(seq_axis, q.shape[2]) is None:
        # no ring: plain attention, operand dtype preserved (bf16 under
        # AMP runs the MXU at full rate), fp32 accumulation
        scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
        s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            T = q.shape[2]
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum('bhqk,bhkd->bhqd', p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32
                          ).astype(q.dtype)
    spec = P(_divisible_axis(batch_axis, q.shape[0]),
             _divisible_axis(head_axis, q.shape[1]), seq_axis, None)
    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, **_SHARD_MAP_KW)(q, k, v)
