"""Parallelism subsystem: mesh topology, sharding annotations, tensor/
sequence/expert-parallel layers, pipeline scheduling.

The reference's multi-device engine (SURVEY.md §2.11) covers data
parallelism (ParallelExecutor allreduce/reduce) and parameter-server
sharding; TP/PP/SP/EP are absent there. This subsystem provides all of
them TPU-natively: a named `jax.sharding.Mesh` over (dp, tp, sp, pp, ep)
axes, PartitionSpec annotations on IR Variables, and GSPMD/shard_map
lowering that puts the collectives on ICI.
"""
from .mesh import MeshConfig, get_mesh, set_mesh, mesh_scope
from .api import shard_tensor, sharding_constraint, pipeline_stage_guard
from . import layers as players  # noqa: F401
from .strategy import DistributedStrategy
from . import distributed
from .distributed import init_parallel_env

__all__ = ['MeshConfig', 'get_mesh', 'set_mesh', 'mesh_scope',
           'shard_tensor', 'sharding_constraint', 'DistributedStrategy',
           'distributed', 'init_parallel_env']
