"""Multi-host runtime: DCN coordination service + global device mesh.

TPU-native replacement of the reference's multi-node NCCL bootstrap:
`gen_nccl_id_op` (reference paddle/fluid/operators/distributed/
gen_nccl_id_op.cc:31) has rank 0 run a throwaway RPC server handing the
ncclUniqueId to peers, after which `NCCLContextMap` builds communicators
over num_trainers*places ranks (reference platform/nccl_helper.h:118,
ncclCommInitRank). Here the JAX/PJRT coordination service plays the
out-of-band-exchange role: `jax.distributed.initialize(coordinator,
num_processes, process_id)` connects every trainer over DCN, after which
`jax.devices()` is the GLOBAL device list and XLA collectives ride ICI
within a slice / DCN across slices.

Env contract kept from the reference (trainer.py:329-377, SURVEY §5.6):

  PADDLE_TRAINER_ID          this process's rank
  PADDLE_TRAINERS_NUM        world size (PADDLE_TRAINERS also accepted)
  PADDLE_TRAINER_ENDPOINTS   comma list host:port; first is coordinator
  PADDLE_CURRENT_ENDPOINT    this process's endpoint (optional)

A reference script that ran `transpiler nccl2` mode under these env vars
runs here unmodified with `ParallelExecutor(num_trainers=..., trainer_id=...)`.
"""
from __future__ import annotations

import functools
import os

import numpy as np

import jax

__all__ = ['init_parallel_env', 'is_initialized', 'trainer_id',
           'num_trainers', 'local_batch_to_global', 'host_value_to_global',
           'shard_rows_for_process']

_initialized = False


def _coordination_client_up():
    """True if jax.distributed is already connected. Checked WITHOUT
    touching the backend (jax.process_count() would initialize it, making
    a later initialize() impossible)."""
    try:
        from jax._src import distributed as _jdist
        return _jdist.global_state.client is not None
    except Exception:
        return False


def is_initialized():
    return _initialized or _coordination_client_up()


def _backend_already_live():
    """True if some JAX backend has been created — then querying
    process_index/count is side-effect free (covers multi-process TPU pods
    where PJRT is multi-process without jax.distributed.initialize)."""
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


def trainer_id():
    # Consult the backend only when it is already live (coordination client
    # connected, or backend created some other way): jax.process_index()
    # on a cold process initializes the backend, which would permanently
    # prevent a later init_parallel_env() from connecting.
    if _coordination_client_up() or _backend_already_live():
        if jax.process_count() > 1:
            return jax.process_index()
    return int(os.environ.get('PADDLE_TRAINER_ID', 0))


def num_trainers():
    if _coordination_client_up() or _backend_already_live():
        if jax.process_count() > 1:
            return jax.process_count()
    return int(os.environ.get('PADDLE_TRAINERS_NUM',
                              os.environ.get('PADDLE_TRAINERS', 1)))


def init_parallel_env(trainer_id=None, num_trainers=None, endpoints=None,
                      coordinator=None):
    """Connect this process to the trainer job. Arguments override the
    PADDLE_* env contract. No-op when world size is 1 or already connected.

    MUST run before the first JAX computation (the coordination client and
    the collectives-capable CPU backend can only be created at backend
    init; same constraint as the reference requiring gen_nccl_id before
    NCCLContextMap construction)."""
    global _initialized
    if _initialized or _coordination_client_up():
        return
    if trainer_id is None:
        trainer_id = int(os.environ.get('PADDLE_TRAINER_ID', 0))
    if num_trainers is None:
        num_trainers = int(os.environ.get(
            'PADDLE_TRAINERS_NUM', os.environ.get('PADDLE_TRAINERS', 1)))
    if num_trainers <= 1:
        return
    if endpoints is None:
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        endpoints = [e for e in eps.split(',') if e]
    if coordinator is None:
        if not endpoints:
            raise ValueError(
                'multi-trainer init needs PADDLE_TRAINER_ENDPOINTS (or an '
                'explicit coordinator address)')
        coordinator = endpoints[0]
    # CPU backend needs an explicit cross-process collectives impl; on TPU
    # the PJRT plugin brings its own (ICI/DCN).
    try:
        jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_trainers,
                               process_id=trainer_id)
    _initialized = True


# -- host<->global array helpers (the BCast/feed-split analogs) ------------

def local_batch_to_global(arr, mesh, pspec):
    """This process's LOCAL batch -> global Array sharded per pspec over
    the (possibly multi-host) mesh. Single-process: plain device_put.
    The analog of feed_and_split_tensor_into_local_scopes (reference
    parallel_executor.py:168) at multi-host scale."""
    from jax.sharding import NamedSharding
    if jax.process_count() == 1:
        return jax.device_put(arr, NamedSharding(mesh, pspec))
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        np.asarray(arr), mesh, pspec)


def host_value_to_global(arr, mesh, pspec):
    """A host value PRESENT IDENTICALLY on every process (startup params
    run from one seed) -> global Array with the given sharding. For
    sharded specs each process contributes the rows its devices own
    (the ncclBcast analog, reference parallel_executor.cc:210)."""
    from jax.sharding import NamedSharding
    if jax.process_count() == 1:
        return jax.device_put(arr, NamedSharding(mesh, pspec))
    from jax.experimental import multihost_utils
    arr = np.asarray(arr)
    first = pspec[0] if len(pspec) > 0 else None
    if first is None:
        return multihost_utils.host_local_array_to_global_array(
            arr, mesh, pspec)
    return multihost_utils.host_local_array_to_global_array(
        shard_rows_for_process(arr, mesh, first), mesh, pspec)


def shard_rows_for_process(arr, mesh, axis_entry):
    """Rows of the full array that THIS process's host-local view covers
    when dim 0 is sharded over `axis_entry` (an axis name or tuple of axis
    names from a PartitionSpec).

    Derived from the mesh's actual device->process mapping rather than
    assuming the axis spans processes contiguously in process-index order:
    each dim-0 shard index is owned by the devices at that coordinate along
    the sharding axes; this process's view is the union of shards its
    local devices sit on (which host_local_array_to_global_array requires
    to be one contiguous range — asserted)."""
    names = axis_entry if isinstance(axis_entry, tuple) else (axis_entry,)
    lo, nmine, total = _process_shard_range(mesh, names)
    rows = arr.shape[0]
    if rows % total != 0:
        raise ValueError('dim0=%d not divisible by %d shards along %r'
                         % (rows, total, names))
    per = rows // total
    return arr[lo * per:(lo + nmine) * per]


@functools.lru_cache(maxsize=64)
def _process_shard_range(mesh, names):
    """(lo_shard, n_shards, total_shards) for this process along `names`.
    Depends only on (mesh, names) within a process — memoized, since the
    device walk is O(mesh size) and startup broadcast calls this per
    parameter."""
    pid = jax.process_index()
    axes = list(mesh.axis_names)
    dev_arr = np.asarray(mesh.devices)
    total = 1
    for nm in names:
        total *= mesh.shape[nm]
    mine = set()
    for idx in np.ndindex(*dev_arr.shape):
        coord = 0
        for nm in names:
            coord = coord * mesh.shape[nm] + idx[axes.index(nm)]
        if dev_arr[idx].process_index == pid:
            mine.add(coord)
    if not mine:
        raise ValueError(
            'process %d owns no devices in the mesh (axes %r) — every '
            'participating process must contribute devices' % (pid, names))
    lo = min(mine)
    if sorted(mine) != list(range(lo, lo + len(mine))):
        raise ValueError(
            'axis %r maps to non-contiguous dim-0 shards %s for process %d; '
            'reorder the mesh so dim-0 sharding is contiguous per host'
            % (names, sorted(mine), pid))
    return (lo, len(mine), total)
