"""SelectedRows: sparse row-set tensor (reference paddle/fluid/framework/
selected_rows.h:32) — the gradient format of sparse embeddings and the wire
format of the distributed lookup table.

TPU-native design: a registered JAX pytree of (values [N, ...], rows [N])
plus a static height, so it flows through jit/vjp with STATIC shapes (N =
number of lookups in the step, fixed at trace time — XLA-friendly, unlike
the reference's dynamically-sized rows vector). Optimizer emitters apply it
as a scatter update; the RPC layer ships rows+values instead of the dense
table (the bandwidth win that motivates the format)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ['SelectedRows']


@jax.tree_util.register_pytree_node_class
class SelectedRows(object):
    __slots__ = ('values', 'rows', 'height')

    def __init__(self, values, rows, height):
        self.values = values        # [N, ...] gradient rows
        self.rows = rows            # [N] int32 row ids (repeats allowed)
        self.height = int(height)   # dense dim0

    def tree_flatten(self):
        return (self.values, self.rows), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        values, rows = children
        return cls(values, rows, height)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def dense_shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def to_dense(self):
        """Dense [height, ...] with repeated rows summed (the reference
        merge+densify semantics)."""
        z = jnp.zeros(self.dense_shape, self.values.dtype)
        return z.at[jnp.asarray(self.rows, jnp.int32)].add(self.values)

    def numpy(self):
        return np.asarray(self.to_dense())

    def merged(self):
        """Host-side dedup: sum values of duplicate rows (the reference
        scatter::MergeAdd). Returns numpy-backed SelectedRows."""
        rows = np.asarray(self.rows)
        vals = np.asarray(self.values)
        uniq, inv = np.unique(rows, return_inverse=True)
        out = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        np.add.at(out, inv, vals)
        return SelectedRows(out, uniq.astype('int32'), self.height)

    def __repr__(self):
        return 'SelectedRows(height=%d, nrows=%s, value_shape=%s)' % (
            self.height, getattr(self.rows, 'shape', '?'),
            getattr(self.values, 'shape', '?'))
