"""Numpy-side metric accumulators (reference python/paddle/fluid/metrics.py:
MetricBase, CompositeMetric, Accuracy, ChunkEvaluator, EditDistance, Auc)."""
from __future__ import annotations

import numpy as np

__all__ = ['MetricBase', 'CompositeMetric', 'Precision', 'Recall', 'Accuracy',
           'ChunkEvaluator', 'EditDistance', 'Auc', 'DetectionMAP']


def _is_number_(v):
    return isinstance(v, (int, float)) or (
        isinstance(v, np.ndarray) and v.shape == (1,))


def _is_number_or_matrix_(v):
    return _is_number_(v) or isinstance(v, np.ndarray)


class MetricBase(object):
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for attr, value in self.__dict__.items():
            if attr.startswith('_'):
                continue
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, 0.0)
            elif isinstance(value, dict):
                setattr(self, attr, {})

    def get_config(self):
        return {attr: value for attr, value in self.__dict__.items()
                if not attr.startswith('_')}

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise TypeError('expected MetricBase')
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype('int32').flatten()
        labels = np.asarray(labels).astype('int32').flatten()
        for p, l in zip(preds, labels):
            if p == 1:
                if l == 1:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype('int32').flatten()
        labels = np.asarray(labels).astype('int32').flatten()
        for p, l in zip(preds, labels):
            if l == 1:
                if p == 1:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class Accuracy(MetricBase):
    """Weighted running accuracy over batches (reference metrics.py Accuracy)."""

    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).flatten()[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError('accuracy has no data; call update first')
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (float(self.num_correct_chunks) / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (float(self.num_correct_chunks) / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError('no data in EditDistance')
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    def __init__(self, name=None, curve='ROC', num_thresholds=200):
        super(Auc, self).__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def reset(self):
        n = self._num_thresholds
        self.tp_list = np.zeros((n,))
        self.fn_list = np.zeros((n,))
        self.tn_list = np.zeros((n,))
        self.fp_list = np.zeros((n,))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).flatten()
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (self._num_thresholds - 1)
                      for i in range(self._num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] >= 2 \
            else preds.flatten()
        for i, t in enumerate(thresholds):
            pred_pos = pos_prob >= t
            self.tp_list[i] += int(np.sum(pred_pos & (labels == 1)))
            self.fp_list[i] += int(np.sum(pred_pos & (labels == 0)))
            self.fn_list[i] += int(np.sum(~pred_pos & (labels == 1)))
            self.tn_list[i] += int(np.sum(~pred_pos & (labels == 0)))

    def eval(self):
        epsilon = 1e-6
        tpr = (self.tp_list.astype('float64')
               / (self.tp_list + self.fn_list + epsilon))
        fpr = (self.fp_list.astype('float64')
               / (self.fp_list + self.tn_list + epsilon))
        auc = 0.0
        for i in range(self._num_thresholds - 1):
            dx = fpr[i] - fpr[i + 1]
            y = (tpr[i] + tpr[i + 1]) / 2.0
            auc += dx * y
        return auc


class DetectionMAP(MetricBase):
    """Mean-average-precision accumulator (reference metrics.py
    DetectionMAP): update() takes the per-batch mAP value the
    detection_map op computed (plus the batch's image count as weight)
    and eval() returns the weighted mean. The reference carries the
    accumulation inside its op's AccumPosCount state; here the op is
    stateless per batch (ops/detection_ops.py) and the metric does the
    cross-batch averaging — evaluator.DetectionMAP wires both ends."""

    def __init__(self, name=None):
        super(DetectionMAP, self).__init__(name)
        self.total_map = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if not _is_number_or_matrix_(value):
            raise ValueError(
                'The parameter value must be a number or a numpy ndarray.')
        if not _is_number_(weight):
            raise ValueError('The parameter weight must be a number.')
        self.total_map += float(np.asarray(value).sum()) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError(
                'There is no data in DetectionMAP Metrics. '
                'Please check layers.detection_map output has added to '
                'DetectionMAP.')
        return self.total_map / self.weight
