"""Raw-operator creation helpers for tests (reference
python/paddle/fluid/op.py: OperatorFactory over OpProtos). The registry
replaces OpProtos, so the factory validates slot names loosely and
builds framework.Operator specs directly."""
from __future__ import annotations

from .registry import _REGISTRY

__all__ = ['Operator']


class OpInfo(object):
    def __init__(self, name):
        self.name = name
        self.type = name


class OperatorFactory(object):
    """`Operator('scale', X='x', Out='out', scale=2.0)` — builds the
    (type, inputs, outputs, attrs) spec for Block.append_op. Slot vs
    attr is decided by value type: strings / string-lists are variable
    slots, everything else is an attribute (the registry has no OpProto
    to consult)."""

    def types(self):
        return list(_REGISTRY.keys())

    def get_op_info(self, t):
        if t not in _REGISTRY:
            raise ValueError('op type %r is not registered' % t)
        return OpInfo(t)

    def __call__(self, type, **kwargs):
        self.get_op_info(type)
        inputs, outputs, attrs = {}, {}, {}

        def is_names(v):
            return isinstance(v, str) or (
                isinstance(v, (list, tuple)) and v and
                all(isinstance(x, str) for x in v))

        for key, value in kwargs.items():
            if is_names(value):
                names = [value] if isinstance(value, str) else list(value)
                # convention: output slots start uppercase and are
                # produced; grad slots end with @GRAD. Heuristic-free
                # split: ops name outputs 'Out*'/'Y'/'*Out' — callers
                # can force with out__/in__ prefixes.
                if key.startswith('out__'):
                    outputs[key[5:]] = names
                elif key.startswith('in__'):
                    inputs[key[4:]] = names
                elif key in ('Out', 'Output', 'Y', 'Outs', 'OutLens',
                             'Loss', 'Hidden', 'Cell', 'MAP'):
                    outputs[key] = names
                else:
                    inputs[key] = names
            else:
                attrs[key] = value
        return dict(type=type, inputs=inputs, outputs=outputs,
                    attrs=attrs)


Operator = OperatorFactory()
