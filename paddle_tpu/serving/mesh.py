"""Mesh-sharded serving: one SPMD decode step over a device mesh.

The serving answer to ParallelExecutor: instead of rewriting the
decode/prefill/verify programs per chip and hand-dispatching N copies,
the SAME whole-block jit compiles once over a `jax.sharding.Mesh` and
GSPMD partitions it — the page pool shards on its heads axis
([pages, page_tokens, heads/tp, dk]), weights keep whatever sharding
they were pinned with, and every host-visible feed (tokens, page
tables, positions, COW plans) replicates. Each decode step is ONE
compiled SPMD program across the mesh; the greedy argmax reduces the
(replicated-by-then) logits on device, so only token ids ever leave.

Bit-exactness vs single-chip is a LAYOUT discipline, not luck: only
column-style weight shardings survive to serve time
(DecodeSpec.serve_param_specs), every sharded contraction input is
gathered whole first (the builders' replicated sharding_constraints),
and the K/V state pins to the same heads-sharded NamedSharding in
in_shardings AND out_shardings — so the donated pool round-trips with
a stable layout and compile-once holds (jit_cache_stats).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..executor import Executor
from ..parallel.mesh import MeshConfig

__all__ = ['serving_mesh', 'mesh_shape_str', 'MeshDecodeExecutor']


def mesh_shape_str(mesh):
    """Canonical 'ax=n,...' string for a built jax Mesh (the form
    stats/SRV_HEALTH carry so routers and benches stay mesh-aware)."""
    return ','.join('%s=%d' % (ax, n)
                    for ax, n in zip(mesh.axis_names, mesh.devices.shape))


def serving_mesh(mesh=None):
    """Resolve a prepare_decoding mesh argument -> (jax.Mesh | None,
    shape_str). Accepts None (read FLAGS_serve_mesh_shape; '' keeps the
    single-chip path), an axis-spec string ('tp=2'), a MeshConfig, or a
    built jax Mesh."""
    from ..flags import get_flag
    if mesh is None:
        mesh = str(get_flag('serve_mesh_shape', '') or '').strip()
        if not mesh:
            return None, ''
    if isinstance(mesh, str):
        if not mesh.strip():
            return None, ''
        mesh = MeshConfig.from_spec(mesh)
    if isinstance(mesh, MeshConfig):
        mesh = mesh.build()
    return mesh, mesh_shape_str(mesh)


class MeshDecodeExecutor(Executor):
    """Executor whose whole-block jits compile as SPMD programs over a
    serving mesh.

    state_shardings maps the K/V cache/pool var names to their
    heads-sharded NamedSharding; those vars are pinned in BOTH
    in_shardings (they arrive donated from the Scope) and out_shardings
    (the donated update leaves with the identical layout — a host
    round-trip through save/restore_pages can't silently flip the
    layout and trigger a recompile). Feeds replicate; everything else
    (weights) passes None = inherit the committed sharding the
    predictor pinned at construction."""

    def __init__(self, place, mesh, state_shardings=None):
        super(MeshDecodeExecutor, self).__init__(place)
        self.mesh = mesh
        self._replicated = NamedSharding(mesh, PartitionSpec())
        self._state = dict(state_shardings or {})

    @property
    def mesh_devices(self):
        return int(self.mesh.devices.size)

    def state_sharding(self, name):
        """The pinned NamedSharding for a cache/pool var (replicated
        for anything unpinned) — paged.py re-places host-restored pools
        with this before writing them back into the Scope."""
        return self._state.get(name, self._replicated)

    # -- Executor hooks ----------------------------------------------------
    def _put_feed(self, name, arr):
        # every decode feed is host-computed control state (tokens,
        # positions, page tables, COW plans): tiny, and the SPMD program
        # needs it whole on every device
        return jax.device_put(arr, self._replicated)

    def _emit_mesh(self):
        return self.mesh

    def _jit_options(self, segment, feed_names):
        feed_set = set(feed_names)
        out_set = set(segment.out_names)
        donated_keys = [n for n in segment.in_names
                        if n in out_set and n not in feed_set]
        const_keys = [n for n in segment.in_names
                      if n not in set(donated_keys)]

        def spec(name):
            explicit = self._state.get(name)
            if explicit is not None:
                return explicit
            if name in feed_set:
                return self._replicated
            # weights: None = inherit the sharding the predictor
            # committed (column-sharded or replicated per
            # serve_param_specs) — never force a host round-trip
            return None

        in_shardings = (
            {n: spec(n) for n in donated_keys},
            {n: spec(n) for n in const_keys},
            self._replicated,
        )
        out_shardings = tuple(self._state.get(n)
                              for n in segment.out_names)
        return {'in_shardings': in_shardings,
                'out_shardings': out_shardings}

    def place_state(self, name, value):
        """Place (or re-place) a cache/pool value under the var's
        pinned sharding. Host arrays upload sharded; device-resident
        jax arrays reshard without a host round-trip — the
        restore_pages `.at[].set` result re-pins in place."""
        return jax.device_put(value, self.state_sharding(name))
