"""ServingEngine: continuous batching over a fixed slot pool.

Orca-style iteration-level scheduling, TPU-flavored: the decode step is
ONE compiled program over all `slots` lanes, so admission/eviction
never changes a shape — a request joining the running batch is a
prefill (whole-row cache overwrite for its slot) between two decode
steps, a finished/cancelled request is simply a lane the scheduler
stops reading (decode_mask already hides whatever the dead lane
writes). Worker threads each own a DecodePredictor clone — private
cache scope + executor, weights shared through the parent Scope — and
pull from one shared queue.

Requests carry a PRIORITY tier (submit(priority=), higher = more
important, 0 = the default lowest tier): one queue per tier, popped
highest-tier first, and the queue-full admission bound applies only to
the lowest tier. On paged-cache exhaustion the engine preempts the
lowest-tier longest-idle stream (serving/preempt.py — swap its pages
to host RAM or drop them for re-prefill) instead of shedding it; the
victim re-enters the FRONT of its own tier and resumes bit-exact.

Telemetry (paddle_tpu/obs/, exported when FLAGS_obs_dir is set):
  serving.requests.{submitted,admitted,completed,cancelled,rejected,
  failed}  counters; serving.tokens_generated / serving.decode_steps /
  serving.prefills  counters; serving.queue_depth /
  serving.slot_occupancy  gauges; serving.ttft /
  serving.token_latency / serving.decode_batch  histograms (seconds /
  seconds / active lanes per step); plus the preemption set from
  serving/preempt.py (serving.preemptions / serving.swapped_pages /
  serving.swap_bytes / serving.resume_latency /
  serving.preempted_streams).
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time

import numpy as np

from ..flags import get_flag
from ..obs import telemetry
from . import preempt as _preempt
from .paging import CacheExhaustedError
from .preempt import HostSwapBudget, pick_victim, preempt_policy

__all__ = ['Request', 'ServingEngine', 'DeadlineExceededError']

QUEUED, RUNNING, DONE, CANCELLED, FAILED = \
    'QUEUED', 'RUNNING', 'DONE', 'CANCELLED', 'FAILED'


class DeadlineExceededError(RuntimeError):
    """The request's end-to-end deadline_ms budget expired before it
    finished. Typed and NON-retryable (serving/replica.py special-cases
    it): retrying elsewhere can only spend more of a budget that is
    already gone. As a lane/queue failure it crosses poll() as a FAILED
    state whose error string leads with this class name — the fleet
    router string-matches it the same way it matches CacheExhausted."""

_submitted = telemetry.counter('serving.requests.submitted')
_admitted = telemetry.counter('serving.requests.admitted')
_completed = telemetry.counter('serving.requests.completed')
_cancelled = telemetry.counter('serving.requests.cancelled')
_rejected = telemetry.counter('serving.requests.rejected')
_failed = telemetry.counter('serving.requests.failed')
_tokens_out = telemetry.counter('serving.tokens_generated')
_decode_steps = telemetry.counter('serving.decode_steps')
_prefills = telemetry.counter('serving.prefills')
_queue_depth = telemetry.gauge('serving.queue_depth')
_occupancy = telemetry.gauge('serving.slot_occupancy')
_ttft = telemetry.histogram('serving.ttft')
_token_latency = telemetry.histogram('serving.token_latency')
_decode_batch = telemetry.histogram('serving.decode_batch')
_weight_swaps = telemetry.counter('serving.weight_swaps')
_swap_wait = telemetry.histogram('serving.swap_wait')
_cache_exhausted = telemetry.counter('serving.cache_exhausted')
_deadline_expired = telemetry.counter('serving.deadline_expired')


class _StepGate(object):
    """Writer-preferring read/write gate around engine steps.

    Every worker iteration (admission prefills + the decode step) runs
    as a READER; a weight install (ParamSubscriber via request_swap)
    runs as the SOLE WRITER. A waiting writer blocks new iterations
    from starting, drains the in-flight ones, runs between two steps,
    and releases — the ISSUE's step-boundary swap contract: in-flight
    decode steps finish on the old weights, the next step reads the
    new ones, and the writer's critical section is only the staged
    pointer swap (never a network pull)."""

    def __init__(self):
        self._mu = threading.Condition()
        self._readers = 0
        self._writers_waiting = 0
        self._writing = False

    @contextlib.contextmanager
    def read(self):
        with self._mu:
            while self._writing or self._writers_waiting:
                self._mu.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._mu:
                self._readers -= 1
                if not self._readers:
                    self._mu.notify_all()

    @contextlib.contextmanager
    def exclusive(self):
        with self._mu:
            self._writers_waiting += 1
            while self._writing or self._readers:
                self._mu.wait()
            self._writers_waiting -= 1
            self._writing = True
        try:
            yield
        finally:
            with self._mu:
                self._writing = False
                self._mu.notify_all()


class Request(object):
    """One generation request. tokens grows as the stream decodes;
    wait() blocks until a terminal state (DONE/CANCELLED/FAILED).
    priority is the SLO tier (higher = more important, 0 = the default
    lowest tier — the only tier queue-full admission rejects)."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens, eos_id, priority=0,
                 deadline_ms=None):
        self.id = next(Request._ids)
        self.prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.priority = int(priority)
        self.state = QUEUED
        self.tokens = []
        self.error = None
        self.snapshot = None          # swapped pages while preempted
        self.preempted_at = None      # set while waiting to resume
        self.submitted_at = time.perf_counter()
        # end-to-end budget, absolute against THIS process's clock from
        # arrival — None (the old-peer / no-key path) means no deadline
        self.deadline_at = None if deadline_ms is None \
            else self.submitted_at + float(deadline_ms) / 1000.0
        self.first_token_at = None
        self.done_at = None
        self._done = threading.Event()

    def _finish(self, state, error=None):
        self.state = state
        self.error = error
        self.done_at = time.perf_counter()
        self._done.set()

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    def result(self, timeout=None):
        """Block for the generated tokens; raises on FAILED, returns
        the partial stream on CANCELLED."""
        if not self.wait(timeout):
            raise TimeoutError('request %d still %s after %rs'
                               % (self.id, self.state, timeout))
        if self.state == FAILED:
            raise RuntimeError('request %d failed: %s'
                               % (self.id, self.error))
        return list(self.tokens)


class _Lane(object):
    """One occupied slot: the request plus the position its NEXT token
    will be appended at (== absolute position of the token being fed).
    `ready` is False while a paged stream is still prefilling in
    chunks — the lane occupies its slot but sits out decode steps.
    `last_active` (last accepted-token time) is the idleness key the
    preemption policy sorts victims by within a tier."""
    __slots__ = ('req', 'pos', 'tok', 'ready', 'last_active')

    def __init__(self, req, pos, tok, ready=True):
        self.req, self.pos, self.tok = req, pos, tok
        self.ready = ready
        self.last_active = time.perf_counter()


class ServingEngine(object):
    def __init__(self, predictor, workers=1, max_queue=None,
                 idle_wait=None):
        """predictor: a DecodePredictor (AnalysisPredictor
        .prepare_decoding()); workers > 1 adds clone()-shared-weight
        worker threads, each with its own slot pool."""
        self._predictors = [predictor]
        for _ in range(1, int(workers)):
            self._predictors.append(predictor.clone())
        self._max_queue = int(max_queue
                              or get_flag('serving_max_queue'))
        self._idle_wait = float(idle_wait
                                if idle_wait is not None
                                else get_flag('serving_idle_wait'))
        self._queues = {}             # priority tier -> deque
        self._cond = threading.Condition()
        self._running = False
        self._threads = []
        self._active_total = 0
        self._inflight = {}           # req.id -> RUNNING Request
        self._accepting = True        # False once a drain/stop began
        self._slo = None
        self._gate = _StepGate()
        self._swaps = 0
        self._slot_tokens = {}        # worker idx -> {slot: tokens held}
        self._swap_budget = HostSwapBudget()
        self._preempted = 0           # streams waiting to resume
        self._preemptions_n = 0
        self._resumes_n = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._running:
            return self
        self._running = True
        self._accepting = True
        # serving SLOs (obs/slo.py): when FLAGS_slo_rules is set, a
        # watchdog re-checks TTFT/token-latency percentiles and token
        # rates against the declared thresholds for the engine's
        # lifetime, emitting slo.breach events
        from ..obs import slo as _slo
        self._slo = _slo.watchdog_from_flags()
        if self._slo is not None:
            self._slo.start()
        self._threads = [
            threading.Thread(target=self._worker_loop, args=(i, p),
                             name='serving-worker-%d' % i, daemon=True)
            for i, p in enumerate(self._predictors)]
        for t in self._threads:
            t.start()
        return self

    def drain(self, timeout=None):
        """Block until no queued or running work remains, leaving the
        engine serving. Returns True once idle, False if `timeout`
        expired first (nothing is cancelled — the caller decides
        whether to escalate). On a never-started engine the queue has
        no one to drain it: returns immediately."""
        if not self._threads:
            return not self._qsize_locked() and not self._inflight
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            with self._cond:
                if not self._qsize_locked() and not self._inflight:
                    return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    def stop(self, drain=True, timeout=None):
        """drain=True finishes queued + running requests first;
        drain=False cancels everything still queued. A `timeout` bounds
        the drain: past it the stop ESCALATES — every still-queued and
        still-running request is cancelled (partial tokens stay
        readable) and the workers are joined with a bound instead of
        hanging forever on a stuck stream. Returns True for a clean
        drain, False when the escalation fired."""
        self._accepting = False
        clean = True
        if drain and timeout is not None:
            clean = self.drain(timeout)
        with self._cond:
            if not drain or not clean:
                for q in self._queues.values():
                    while q:
                        req = q.popleft()
                        self._forget_preempted(req)
                        req._finish(CANCELLED)
                        _cancelled.inc()
                if not clean:
                    # running lanes notice the CANCELLED state at the
                    # next step boundary and evict (cancel() semantics)
                    for req in list(self._inflight.values()):
                        if req.state == RUNNING:
                            req.state = CANCELLED
            self._running = False
            self._cond.notify_all()
        join_deadline = None if timeout is None \
            else time.monotonic() + max(5.0, timeout)
        for t in self._threads:
            t.join(None if join_deadline is None
                   else max(0.1, join_deadline - time.monotonic()))
            if t.is_alive():
                # a wedged decode step: the daemon thread dies with the
                # process — surfacing a False beats hanging the caller
                clean = False
        self._threads = []
        if self._slo is not None:
            # final check covers the tail between the last periodic
            # evaluation and drain
            self._slo.stop(final_check=True)
            self._slo = None
        return clean

    close = stop

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=not any(exc))

    # -- client surface ----------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               priority=0, deadline_ms=None):
        """priority: SLO tier, higher = more important (default 0 =
        the lowest tier). Tiers dequeue highest-first, and the
        queue-full rejection applies only to the lowest tier — shed
        rules cost low-tier latency, never high-tier admission.

        deadline_ms: optional end-to-end budget. An expired request is
        rejected at dequeue (before its prefill is wasted) and an
        expired lane is cancelled between decode steps with its pages
        freed — both FAILED with a typed, non-retryable
        DeadlineExceededError. None = no deadline."""
        prompt = np.asarray(prompt).reshape(-1)
        max_len = self._predictors[0].max_len
        if not 1 <= prompt.size <= max_len:
            _rejected.inc()
            raise ValueError('prompt length %d outside [1, %d] '
                             '(max_len)' % (prompt.size, max_len))
        if max_new_tokens < 1:
            _rejected.inc()
            raise ValueError('max_new_tokens must be >= 1')
        if deadline_ms is not None and float(deadline_ms) <= 0:
            _rejected.inc()
            _deadline_expired.inc()
            raise DeadlineExceededError(
                'deadline_ms %r already spent at submit'
                % (deadline_ms,))
        req = Request(prompt, max_new_tokens, eos_id,
                      priority=priority, deadline_ms=deadline_ms)
        with self._cond:
            if self._running and not self._accepting:
                _rejected.inc()
                raise RuntimeError(
                    'serving engine is draining — submission rejected')
            if req.priority <= 0 and \
                    self._qsize_locked() >= self._max_queue:
                _rejected.inc()
                raise RuntimeError('serving queue full (%d)'
                                   % self._max_queue)
            self._push_locked(req)
        _submitted.inc()
        return req

    def generate(self, prompt, max_new_tokens=16, eos_id=None,
                 timeout=None):
        return self.submit(prompt, max_new_tokens,
                           eos_id=eos_id).result(timeout)

    def cancel(self, req):
        """Mark a request cancelled; a queued one never runs, a running
        one is evicted at the next step boundary (its partial tokens
        remain readable)."""
        if req.state in (QUEUED, RUNNING):
            req.state = CANCELLED
        return req

    def request_swap(self, fn, label='weights'):
        """Run fn() with every worker quiesced at a step boundary and
        return its result. fn must be CHEAP (staged-pointer installs,
        not pulls): it holds up every decode lane while it runs. With
        the engine stopped there are no steps in flight and fn runs
        inline. The wait-for-boundary time lands in serving.swap_wait;
        serving.weight_swaps counts completed swaps."""
        t0 = time.perf_counter()
        if not self._threads:
            out = fn()
            self._swaps += 1
            _weight_swaps.inc()
            return out
        with self._gate.exclusive():
            _swap_wait.observe(time.perf_counter() - t0)
            out = fn()
            self._swaps += 1
            _weight_swaps.inc()
            return out

    # -- disaggregated page shipping (serving/disagg.py) -------------------
    def export_prefix(self, prompt):
        """Gather the longest resident full-page chain for `prompt`
        across workers into host copies (quiesced at a step boundary —
        save_pages reads device pools). None on a non-paged engine or
        a cold cache."""
        if not getattr(self._predictors[0], 'paged', False):
            return None

        def _gather():
            best = None
            for p in self._predictors:
                got = p.export_prefix(prompt)
                if got and (best is None
                            or len(got['keys']) > len(best['keys'])):
                    best = got
            return best
        return self.request_swap(_gather, label='export_prefix')

    def install_prefix(self, prompt, keys, data, skip=0):
        """Install shipped pages into worker 0's pool + prefix cache
        (quiesced — restore_pages functionally rewrites device pools).
        Streams admitted by other workers simply re-prefill locally;
        correctness never depends on the install. Returns (installed,
        deduped)."""
        if not getattr(self._predictors[0], 'paged', False):
            raise ValueError('install_prefix needs a paged engine')
        return self.request_swap(
            lambda: self._predictors[0].install_prefix(prompt, keys,
                                                       data, skip=skip),
            label='install_prefix')

    def resident_keys(self, prompt):
        """Worker 0's resident leading chain run for `prompt` (hex) —
        advisory, lock-free (see PagedDecodePredictor.resident_keys)."""
        p0 = self._predictors[0]
        if not getattr(p0, 'paged', False):
            return []
        return p0.resident_keys(prompt)

    def prefix_report(self):
        """Drain registered/evicted prefix-chain deltas from every
        worker (merged) — the replica's SRV_HEALTH contribution to the
        fleet prefix directory."""
        new, gone = [], []
        for p in self._predictors:
            if not getattr(p, 'paged', False):
                continue
            got = p.prefix_report()
            new.extend(got['new'])
            gone.extend(got['evicted'])
        return {'new': new, 'evicted': gone}

    def stats(self):
        with self._cond:
            depth = self._qsize_locked()
            preempted = self._preempted
        p0 = self._predictors[0]
        paged = getattr(p0, 'paged', False)
        slot_tokens = [dict(self._slot_tokens.get(i, {}))
                       for i in range(len(self._predictors))]
        out = {'queue_depth': depth, 'active': self._active_total,
               'workers': len(self._predictors),
               'slots_per_worker': p0.slots,
               'weight_swaps': self._swaps,
               # preempt-first capacity (serving/preempt.py): lifetime
               # preemptions/resumes, streams currently swapped out or
               # waiting to re-prefill, and host RAM held by swaps
               'preemptions': self._preemptions_n,
               'resumes': self._resumes_n,
               'preempted_streams': preempted,
               'swap_host_bytes': self._swap_budget.used_bytes,
               'paged': paged,
               # mesh-sharded serving (serving/mesh.py): '' and 1 on
               # the single-chip path
               'mesh_shape': getattr(p0, 'mesh_shape', ''),
               'mesh_devices': getattr(p0, 'mesh_devices', 1),
               # per-worker {slot: tokens held} — actual cache pressure,
               # so the fleet router's least-loaded dispatch can weigh
               # a worker near its token capacity over one holding the
               # same lane count of short streams
               'slot_tokens': slot_tokens,
               'cache_tokens': sum(sum(d.values()) for d in slot_tokens),
               'jit': p0.jit_cache_stats()}
        if paged:
            kv = {'pages_in_use': 0, 'pages_free': 0, 'prefix_hits': 0,
                  'prefix_misses': 0, 'prefix_pages': 0,
                  'prefix_tokens_reused': 0, 'prefix_entries': 0}
            for p in self._predictors:
                for key in kv:
                    kv[key] += p.pool_stats()[key]
            kv['page_tokens'] = p0.page_tokens
            kv['num_pages'] = p0.num_pages
            out['kv'] = kv
            out['cache_capacity'] = (len(self._predictors)
                                     * (p0.num_pages - 1) * p0.page_tokens)
        else:
            out['cache_capacity'] = (len(self._predictors)
                                     * p0.slots * p0.max_len)
        if getattr(p0, 'speculative', False):
            sp = [p.spec_stats() for p in self._predictors]
            drafted = sum(s['draft_tokens'] for s in sp)
            accepted = sum(s['accepted_tokens'] for s in sp)
            steps = sum(s['steps'] for s in sp)
            emitted = sum(s['effective_tokens_per_step'] * s['steps']
                          for s in sp)
            out['spec'] = {
                'spec_k': sp[0]['spec_k'],
                'k_live': sp[0]['k_live'],
                'steps': steps,
                'draft_tokens': drafted,
                'accepted_tokens': accepted,
                'rejected_tokens': drafted - accepted,
                'fallback_steps': sum(s['fallback_steps'] for s in sp),
                'accept_rate': (accepted / drafted if drafted else 0.0)}
            # tokens emitted per verify iteration — the fleet router's
            # effective-throughput weight (1.0 would be plain decode)
            out['effective_tokens_per_step'] = (emitted / steps
                                                if steps else 0.0)
            out['spec']['effective_tokens_per_step'] = \
                out['effective_tokens_per_step']
        return out

    # -- scheduler ---------------------------------------------------------
    def _qsize_locked(self):
        return sum(len(q) for q in self._queues.values())

    def _push_locked(self, req, front=False):
        """Enqueue into the request's own tier (front=True: a
        requeued exhaustion victim or preempted stream resumes ahead
        of its tier's waiting admissions — but never jumps a higher
        tier, which is always drained first)."""
        q = self._queues.get(req.priority)
        if q is None:
            q = self._queues[req.priority] = collections.deque()
        if front:
            q.appendleft(req)
        else:
            q.append(req)
        _queue_depth.set(self._qsize_locked())
        self._cond.notify_all()

    def _pop_next(self):
        with self._cond:
            for prio in sorted(self._queues, reverse=True):
                q = self._queues[prio]
                while q:
                    req = q.popleft()
                    _queue_depth.set(self._qsize_locked())
                    if req.state == CANCELLED:
                        self._forget_preempted(req)
                        req._finish(CANCELLED)
                        _cancelled.inc()
                        continue
                    if req.deadline_at is not None and \
                            time.perf_counter() > req.deadline_at:
                        # expired while queued: reject BEFORE wasting a
                        # prefill on tokens nobody is waiting for
                        self._forget_preempted(req)
                        req._finish(FAILED,
                                    error='DeadlineExceededError: '
                                          'expired in queue')
                        _failed.inc()
                        _deadline_expired.inc()
                        continue
                    return req
        return None

    # -- preemption (serving/preempt.py) -----------------------------------
    def _forget_preempted(self, req):
        """A preempted request leaving the queue for a terminal state:
        give back its host budget and the preempted-streams gauge."""
        snap, req.snapshot = req.snapshot, None
        if snap is not None:
            self._swap_budget.release(snap['nbytes'])
        if req.preempted_at is not None:
            req.preempted_at = None
            with self._cond:
                self._preempted -= 1
            _preempt.preempted_streams.set(self._preempted)

    def _resume(self, req):
        """Preempt -> back-in-a-slot accounting (the request is being
        re-admitted; its snapshot, if any, was already restored)."""
        if req.preempted_at is None:
            return
        _preempt.resume_latency.observe(time.perf_counter()
                                        - req.preempted_at)
        req.preempted_at = None
        with self._cond:
            self._preempted -= 1
            self._resumes_n += 1
        _preempt.preempted_streams.set(self._preempted)

    def _preempt_lane(self, pred, lanes, slot, wstate, policy):
        """Preempt one READY lane: swap its pages to pinned host memory
        (budget permitting) or drop them for re-prefill, release the
        slot, and requeue the request at the FRONT of its own tier.
        Admission then waits (cache_wait) until a live stream releases
        pages, so the victim cannot immediately steal back what it
        just gave up."""
        lane = lanes.pop(slot)
        req = lane.req
        snap = None
        if policy == 'swap':
            snap = pred.save_stream(slot)
            if self._swap_budget.reserve(snap['nbytes']):
                _preempt.swapped_pages.inc(snap['pages'])
                _preempt.swap_bytes.inc(snap['nbytes'])
            else:
                snap = None       # host budget dry: re-prefill instead
        pred.release(slot)
        self._inflight.pop(req.id, None)
        self._active_total -= 1
        with self._cond:
            self._preempted += 1
            self._preemptions_n += 1
            req.state = QUEUED
            req.snapshot = snap
            req.preempted_at = time.perf_counter()
            self._push_locked(req, front=True)
        _preempt.preemptions.inc()
        _preempt.preempted_streams.set(self._preempted)
        wstate['cache_wait'] = True

    def _finish_lane(self, lanes, slot, state, error=None, pred=None,
                     wstate=None):
        lane = lanes.pop(slot)
        self._inflight.pop(lane.req.id, None)
        lane.req._finish(state, error)
        self._active_total -= 1
        if pred is not None and getattr(pred, 'paged', False):
            # freed pages un-stick any admission waiting on the pool
            pred.release(slot)
            if wstate is not None:
                wstate['cache_wait'] = False
        if state == DONE:
            _completed.inc()
        elif state == CANCELLED:
            _cancelled.inc()
        else:
            _failed.inc()

    def _lane_accept(self, lanes, slot, tok, pred=None, wstate=None):
        """Record one generated token; returns False if the lane is
        done (eos / budget / cancelled) and was evicted."""
        lane = lanes[slot]
        req = lane.req
        if req.state == CANCELLED:
            self._finish_lane(lanes, slot, CANCELLED, pred=pred,
                              wstate=wstate)
            return False
        req.tokens.append(int(tok))
        _tokens_out.inc()
        if req.first_token_at is None:
            req.first_token_at = time.perf_counter()
            _ttft.observe(req.first_token_at - req.submitted_at)
        if len(req.tokens) >= req.max_new_tokens or \
                (req.eos_id is not None and int(tok) == req.eos_id):
            self._finish_lane(lanes, slot, DONE, pred=pred,
                              wstate=wstate)
            return False
        lane.tok = int(tok)
        lane.last_active = time.perf_counter()
        return True

    def _admit(self, pred, lanes):
        """Fill free slots from the queue; one prefill per admitted
        request (prefill_batch > 1 batches them)."""
        free = [s for s in range(pred.slots) if s not in lanes]
        batch = []
        while free:
            req = self._pop_next()
            if req is None:
                break
            req.state = RUNNING
            self._inflight[req.id] = req
            slot = free.pop(0)
            batch.append((req, slot))
            self._active_total += 1
            _admitted.inc()
        for i in range(0, len(batch), pred.prefill_batch):
            chunk = batch[i:i + pred.prefill_batch]
            try:
                ids = pred.prefill([r.prompt for r, _ in chunk],
                                   [s for _, s in chunk])
            except Exception as e:     # noqa: BLE001 — lane-fatal only
                for req, _slot in chunk:
                    self._inflight.pop(req.id, None)
                    req._finish(FAILED, error=repr(e))
                    self._active_total -= 1
                    _failed.inc()
                continue
            _prefills.inc(len(chunk))
            for (req, slot), tok in zip(chunk, ids):
                lanes[slot] = _Lane(req, pos=len(req.prompt),
                                    tok=int(tok))
                self._lane_accept(lanes, slot, int(tok))

    def _admit_paged(self, pred, lanes, prefilling, wstate):
        """Paged admission: open a stream per free slot (a prefix-cache
        match + read-only page adoption — allocates nothing, so
        admission itself can never exhaust the pool) and queue it for
        chunked prefill. While cache_wait is set, a requeued
        exhaustion victim is waiting for a live stream to release
        pages — admitting more streams would only deepen the hole.

        A resuming PREEMPTED stream takes one of two paths: a swap
        snapshot restores its pages device-side before the next decode
        step it joins (bit-exact — float32 bytes round-trip exactly);
        without one, the stream re-prefills (prompt + tokens so far),
        and the final chunk's output token IS its next stream token —
        the fleet-failover contract, equally bit-exact by greedy
        determinism."""
        if wstate['cache_wait'] and lanes:
            return
        wstate['cache_wait'] = False
        free = [s for s in range(pred.slots) if s not in lanes]
        while free:
            req = self._pop_next()
            if req is None:
                break
            slot = free.pop(0)
            # a resumed stream continues from its accumulated tokens;
            # a fresh one has none and seq is just its prompt
            seq = req.prompt + req.tokens
            if req.snapshot is not None:
                try:
                    pred.restore_stream(slot, req.snapshot, prompt=seq)
                except CacheExhaustedError:
                    if lanes:
                        # pool still too tight: back to the tier front
                        # until a live stream releases
                        with self._cond:
                            self._push_locked(req, front=True)
                        wstate['cache_wait'] = True
                        return
                    # nothing live will ever free pages for this
                    # snapshot: drop it and re-prefill instead (the
                    # pool may fit a chunked prefill it cannot fit
                    # whole)
                    self._swap_budget.release(req.snapshot['nbytes'])
                    req.snapshot = None
                except Exception as e:  # noqa: BLE001 — lane-fatal
                    self._forget_preempted(req)
                    req._finish(FAILED, error=repr(e))
                    _failed.inc()
                    continue
                else:
                    self._swap_budget.release(req.snapshot['nbytes'])
                    req.snapshot = None
                    self._resume(req)
                    req.state = RUNNING
                    self._inflight[req.id] = req
                    self._active_total += 1
                    lanes[slot] = _Lane(req, pos=len(seq) - 1,
                                        tok=req.tokens[-1])
                    _admitted.inc()
                    continue
            req.state = RUNNING
            self._inflight[req.id] = req
            self._active_total += 1
            try:
                pred.open_stream(slot, seq)
            except Exception as e:  # noqa: BLE001 — lane-fatal only
                self._forget_preempted(req)
                self._inflight.pop(req.id, None)
                req._finish(FAILED, error=repr(e))
                self._active_total -= 1
                _failed.inc()
                continue
            self._resume(req)
            lanes[slot] = _Lane(req, pos=len(seq), tok=0,
                                ready=False)
            prefilling.append(slot)
            _admitted.inc()

    def _prefill_tick(self, pred, lanes, prefilling, wstate):
        """Advance chunked prefill by AT MOST one chunk per engine
        iteration — the head-of-line bound: a 4k-token prompt costs
        the live decode lanes one chunk's latency per step, never a
        whole-prompt stall. Pool exhaustion mid-prefill first tries to
        PREEMPT a strictly lower-tier ready lane (the prefilling
        stream keeps its slot and retries the same chunk next
        iteration); with no lower-tier victim, it requeues at the
        front of its OWN tier — never jumping a higher tier's waiting
        admissions — and admission pauses until a live stream releases
        (with no live stream left to wait on, the request can never
        fit and fails with the typed error)."""
        while prefilling:
            slot = prefilling[0]
            lane = lanes.get(slot)
            if lane is None:
                prefilling.popleft()
                continue
            req = lane.req
            if req.state == CANCELLED:
                prefilling.popleft()
                self._finish_lane(lanes, slot, CANCELLED, pred=pred,
                                  wstate=wstate)
                continue
            if req.deadline_at is not None and \
                    time.perf_counter() > req.deadline_at:
                prefilling.popleft()
                self._finish_lane(lanes, slot, FAILED,
                                  error='DeadlineExceededError: '
                                        'expired mid-prefill',
                                  pred=pred, wstate=wstate)
                _deadline_expired.inc()
                continue
            try:
                out = pred.prefill_step(slot)
            except CacheExhaustedError as e:
                _cache_exhausted.inc()
                policy = preempt_policy()
                if policy != 'off':
                    victim = pick_victim(lanes, below=req.priority)
                    if victim is not None:
                        # a lower-tier stream gives way; this prefill
                        # keeps its slot and retries the same chunk
                        # next iteration
                        self._preempt_lane(pred, lanes, victim,
                                           wstate, policy)
                        return
                prefilling.popleft()
                lanes.pop(slot)
                pred.release(slot)
                self._inflight.pop(req.id, None)
                self._active_total -= 1
                if lanes:
                    req.state = QUEUED
                    with self._cond:
                        self._push_locked(req, front=True)
                    wstate['cache_wait'] = True
                else:
                    req._finish(FAILED,
                                error='CacheExhaustedError: %s' % e)
                    _failed.inc()
                return
            except Exception as e:  # noqa: BLE001 — lane-fatal only
                prefilling.popleft()
                self._finish_lane(lanes, slot, FAILED, error=repr(e),
                                  pred=pred, wstate=wstate)
                return
            _prefills.inc()
            if out is None:
                return               # more chunks remain — next iteration
            prefilling.popleft()
            lane.ready = True
            self._lane_accept(lanes, slot, int(out), pred=pred,
                              wstate=wstate)
            return

    def _worker_loop(self, wid, pred):
        paged = getattr(pred, 'paged', False)
        # a speculative predictor's step is one draft->verify iteration
        # (serving/speculative.py): same feed ABI, but each live lane
        # gets 1..k+1 tokens back instead of exactly one
        speculative = getattr(pred, 'speculative', False)
        lanes = {}                       # slot -> _Lane
        prefilling = collections.deque()  # paged: slots mid-prefill
        wstate = {'cache_wait': False}
        tokens = np.zeros((pred.slots,), np.int64)
        positions = np.zeros((pred.slots,), np.int32)
        while True:
            with self._cond:
                while self._running and not self._qsize_locked() \
                        and not lanes:
                    self._cond.wait(self._idle_wait)
                if not self._running and not self._qsize_locked() \
                        and not lanes:
                    return
            # one gate-read section per iteration: a waiting weight
            # swap (request_swap) runs between iterations — i.e. at a
            # step boundary — never under a prefill or decode step
            with self._gate.read():
                if paged:
                    self._admit_paged(pred, lanes, prefilling, wstate)
                    self._prefill_tick(pred, lanes, prefilling, wstate)
                else:
                    self._admit(pred, lanes)
                _occupancy.set(self._active_total)
                self._slot_tokens[wid] = {s: ln.pos
                                          for s, ln in lanes.items()}
                # deadline check at the step boundary: an expired ready
                # lane is evicted (pages freed) before it buys another
                # decode step. Prefilling lanes are checked at the
                # prefill-queue head (_prefill_tick), matching how
                # cancellation reaches them.
                now = time.perf_counter()
                for slot, ln in list(lanes.items()):
                    if ln.ready and ln.req.deadline_at is not None \
                            and now > ln.req.deadline_at:
                        self._finish_lane(
                            lanes, slot, FAILED,
                            error='DeadlineExceededError: expired '
                                  'mid-decode',
                            pred=pred, wstate=wstate)
                        _deadline_expired.inc()
                ready = [s for s, ln in lanes.items() if ln.ready]
                if not ready:
                    continue
                for slot in ready:
                    tokens[slot] = lanes[slot].tok
                    positions[slot] = lanes[slot].pos
                t0 = time.perf_counter()
                try:
                    if speculative:
                        emitted = pred.spec_step(tokens, positions)
                    else:
                        ids = pred.decode_step(tokens, positions)
                except CacheExhaustedError as e:
                    # preempt-first (serving/preempt.py): instead of
                    # failing the named victims, the lowest-tier
                    # longest-idle stream gives its pages back (swap or
                    # drop) and every survivor retries the IDENTICAL
                    # step next iteration — the transactional rollback
                    # already undid this call's allocations, so the
                    # retry is bit-exact. policy 'off' restores the
                    # legacy typed shed (the fleet router retries it
                    # cross-replica).
                    _cache_exhausted.inc()
                    policy = preempt_policy()
                    preempted = False
                    if policy != 'off':
                        for slot in list(e.slots):
                            lane = lanes.get(slot)
                            if lane is not None and \
                                    lane.pos + 1 > pred.window:
                                # outgrew its own page window: no
                                # preemption can ever make it fit
                                self._finish_lane(
                                    lanes, slot, FAILED,
                                    error='CacheExhaustedError: %s' % e,
                                    pred=pred, wstate=wstate)
                        victim = pick_victim(lanes)
                        if victim is not None:
                            self._preempt_lane(pred, lanes, victim,
                                               wstate, policy)
                            preempted = True
                    if not preempted:
                        for slot in e.slots:
                            if slot in lanes:
                                self._finish_lane(
                                    lanes, slot, FAILED,
                                    error='CacheExhaustedError: %s' % e,
                                    pred=pred, wstate=wstate)
                    continue
                except Exception as e:   # noqa: BLE001 — engine survives
                    for slot in ready:
                        if slot in lanes:
                            self._finish_lane(lanes, slot, FAILED,
                                              error=repr(e), pred=pred,
                                              wstate=wstate)
                    continue
                dt = time.perf_counter() - t0
                _decode_steps.inc()
                _token_latency.observe(dt)
                _decode_batch.observe(len(ready))
                if speculative:
                    # per-slot mixed accept lengths in the SAME
                    # iteration: each lane consumes its own emitted
                    # prefix, stopping early on eos/budget/cancel
                    for slot in ready:
                        for tok in emitted.get(slot, ()):
                            lanes[slot].pos += 1
                            if not self._lane_accept(lanes, slot,
                                                     int(tok),
                                                     pred=pred,
                                                     wstate=wstate):
                                break
                else:
                    for slot in ready:
                        lanes[slot].pos += 1
                        self._lane_accept(lanes, slot, int(ids[slot]),
                                          pred=pred, wstate=wstate)
                _occupancy.set(self._active_total)
                # re-snapshot after evictions so an idle worker reports
                # zero held tokens, not its last busy state
                self._slot_tokens[wid] = {s: ln.pos
                                          for s, ln in lanes.items()}
