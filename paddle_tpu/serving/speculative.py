"""Speculative decoding over the paged KV cache: draft k, verify once.

A small DRAFT model proposes `k` tokens per stream with k cheap
sequential decode steps, then the TARGET model scores all k+1 proposed
positions for every slot in ONE batched verify pass
(models/transformer.py build_verify_program — the paged prefill program
generalized to a fixed K1-row batch over the slot pool). Greedy
acceptance per slot: the longest prefix of the draft chain that matches
the target's own greedy choices is committed, plus the target's next
token after the match (the free bonus token), so every iteration emits
between 1 and k+1 tokens per stream and the emitted stream is
TOKEN-FOR-TOKEN IDENTICAL to plain greedy decode — speculation changes
throughput, never output (tests/test_speculative.py).

Why no device-side rollback: K/V validity is positional masking
(j <= position), and every program appends before it gathers within a
layer. A rejected proposal's K/V rows are garbage parked at positions
ahead of the committed length; the next iteration REWRITES those
positions before any mask ever validates them. So acceptance is pure
host bookkeeping (table.length), and the only transactional state is
PR-12's page machinery: at most ONE copy-on-write per slot per verify
(only the shared frontier page can fork — pages grown for proposals
are born private), rolled back with the same deferred-unref discipline
when the pool runs dry mid-verify, after which the iteration retries
as one plain decode step (spec.fallback_steps).

The draft is either an explicit smaller LM (its own ProgramDesc and
weight scope) or the default SELF-draft: the target truncated to its
first FLAGS_spec_draft_layers transformer blocks — the truncated
spec's parameter names are a subset of the target's, so the same
pinned weights serve both models with zero extra weight HBM. Either
way the draft runs the full paged-cache machinery (its own PagePool /
PrefixCache / page tables) in its own child Scope.

k adapts per predictor between 1 and FLAGS_spec_k from the rolling
accept rate (a deterministic rule — adaptation shifts the draft/verify
work split, never the emitted tokens).

Telemetry: spec.accept_rate histogram, spec.draft_tokens /
spec.accepted_tokens / spec.rejected_tokens / spec.fallback_steps
counters, serving.effective_tokens_per_step gauge.
"""
from __future__ import annotations

import numpy as np

from ..flags import get_flag
from ..obs import telemetry
from .paged import PagedDecodePredictor
from .paging import CacheExhaustedError

__all__ = ['DraftModel', 'SpeculativeDecodePredictor']

_accept_rate = telemetry.histogram('spec.accept_rate')
_draft_tokens = telemetry.counter('spec.draft_tokens')
_accepted_tokens = telemetry.counter('spec.accepted_tokens')
_rejected_tokens = telemetry.counter('spec.rejected_tokens')
_fallback_steps = telemetry.counter('spec.fallback_steps')
_effective_tps = telemetry.gauge('serving.effective_tokens_per_step')

# adaptive k: evaluate the rolling accept rate every WINDOW proposed
# tokens; widen k above RAISE, narrow below LOWER (floor 1 — plain
# decode is spec_k=0, a different predictor, not an adaptation state)
_ADAPT_WINDOW = 32
_ADAPT_RAISE = 0.8
_ADAPT_LOWER = 0.4


class DraftModel(PagedDecodePredictor):
    """The proposer: a PagedDecodePredictor over the draft pair from
    transpile_spec — its own PagePool / PrefixCache / page tables in
    its own child Scope, its own compiled prefill + decode programs.
    For a self-draft the parent weight scope is the TARGET's, and the
    draft's parameter names resolve to the target's own pinned
    weights."""

    def __init__(self, predictor, pair=None, _clone_of=None, mesh=None):
        PagedDecodePredictor.__init__(self, predictor, pair=pair,
                                      _clone_of=_clone_of, mesh=mesh)

    def clone(self):
        return DraftModel(self._base, _clone_of=self)


class SpeculativeDecodePredictor(PagedDecodePredictor):
    """PagedDecodePredictor wrapped with draft/verify speculation.

    The target-side surface (open_stream / prefill_step / decode_step /
    release / reset / clone) is inherited; speculation adds

        spec_step(tokens, positions) -> {slot: [emitted tokens]}

    one draft->verify iteration over every live stream, emitting 1 to
    k+1 tokens per slot with per-slot mixed accept lengths in the same
    iteration. decode_step stays the plain single-token path (the
    mid-verify exhaustion fallback runs through it); generate() drives
    spec_step so the solo parity path exercises speculation end to
    end."""

    speculative = True

    def __init__(self, predictor, slots=None, spec_k=None,
                 draft_layers=None, draft_predictor=None,
                 page_tokens=None, kv_pages=None, prefill_chunk=None,
                 _clone_of=None, mesh=None):
        if _clone_of is not None:
            self._spair = _clone_of._spair
            self._draft = _clone_of._draft.clone()
            PagedDecodePredictor.__init__(self, predictor,
                                          _clone_of=_clone_of)
            return
        from ..transpiler.decode_transpiler import DecodeTranspiler
        spair = DecodeTranspiler().transpile_spec(
            predictor._program,
            draft_program=(draft_predictor._program
                           if draft_predictor is not None else None),
            slots=int(slots or get_flag('serving_slots')),
            spec_k=spec_k, draft_layers=draft_layers,
            page_tokens=page_tokens, kv_pages=kv_pages,
            prefill_chunk=prefill_chunk)
        self._spair = spair
        # draft and target share one mesh: the self-draft runs the SAME
        # pinned (possibly column-sharded) weights, so its programs
        # must compile over the same device set
        self._draft = DraftModel(draft_predictor or predictor,
                                 pair=spair.draft, mesh=mesh)
        PagedDecodePredictor.__init__(self, predictor, pair=spair.target,
                                      mesh=mesh)

    # -- introspection -----------------------------------------------------
    @property
    def spec_k(self):
        return self._spair.spec_k

    @property
    def k_live(self):
        """The adaptive k currently in force (1..spec_k)."""
        return self._k_live

    @property
    def draft(self):
        return self._draft

    def spec_stats(self):
        """Cumulative speculation accounting since reset() — the
        LMServer.stats() / SRV_HEALTH surface the fleet router's
        effective-throughput weighting reads."""
        drafted = self._stat_drafted
        steps = self._stat_steps
        return {'spec_k': self.spec_k,
                'k_live': self._k_live,
                'steps': steps,
                'draft_tokens': drafted,
                'accepted_tokens': self._stat_accepted,
                'rejected_tokens': drafted - self._stat_accepted,
                'fallback_steps': self._stat_fallbacks,
                'accept_rate': (self._stat_accepted / drafted
                                if drafted else 0.0),
                # per slot-step so 1.0 == plain decode regardless of
                # how many lanes were live each iteration
                'effective_tokens_per_step':
                    (self._stat_emitted / self._stat_slot_steps
                     if self._stat_slot_steps else 0.0)}

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        PagedDecodePredictor.reset(self)
        draft = getattr(self, '_draft', None)
        if draft is not None:
            draft.reset()
        self._draft_dead = set()
        self._k_live = self._spair.spec_k
        self._win_proposed = 0
        self._win_accepted = 0
        self._stat_steps = 0
        self._stat_slot_steps = 0
        self._stat_drafted = 0
        self._stat_accepted = 0
        self._stat_emitted = 0
        self._stat_fallbacks = 0

    def clone(self):
        return SpeculativeDecodePredictor(self._base, _clone_of=self)

    # -- streams -----------------------------------------------------------
    def open_stream(self, slot, prompt):
        info = PagedDecodePredictor.open_stream(self, slot, prompt)
        try:
            self._draft.open_stream(slot, prompt)
            self._draft_dead.discard(slot)
        except (CacheExhaustedError, RuntimeError):
            # target stream stands; the slot just decodes unassisted
            self._draft_dead.add(slot)
        return info

    def release(self, slot):
        PagedDecodePredictor.release(self, slot)
        self._draft.release(slot)
        self._draft_dead.discard(int(slot))

    def restore_stream(self, slot, snapshot, prompt=None):
        """Resume a preempted stream (serving/preempt.py): the TARGET
        pages restore bit-exact from the snapshot; the draft cache was
        dropped at preemption, so it re-prefills from the committed
        sequence (prompt + tokens so far) — its last position is later
        re-fed by the chain as an identical K/V rewrite, the same
        safe idiom as a frozen chain slot. A draft that cannot fit
        leaves the slot decoding unassisted (plain decode, exactly the
        mid-verify exhaustion escape), which never changes the emitted
        tokens — verify trusts only the target."""
        slot = int(slot)
        PagedDecodePredictor.restore_stream(self, slot, snapshot,
                                            prompt=prompt)
        self._draft_dead.add(slot)
        if prompt is None:
            return
        try:
            self._draft.open_stream(slot, prompt)
            while self._draft.prefill_step(slot) is None:
                pass
        except (CacheExhaustedError, RuntimeError):
            self._draft.release(slot)
            return
        self._draft_dead.discard(slot)

    def prefill_step(self, slot, return_logits=False):
        out = PagedDecodePredictor.prefill_step(self, slot,
                                                return_logits)
        if out is None:
            return None
        # target prompt complete: bring the draft cache up in full (its
        # chunks are a draft_layers-deep fraction of the target's work)
        slot = int(slot)
        if slot not in self._draft_dead:
            try:
                while self._draft.prefill_step(slot) is None:
                    pass
            except CacheExhaustedError:
                self._draft.release(slot)
                self._draft_dead.add(slot)
        return out

    # -- speculation -------------------------------------------------------
    def _draft_chain(self, live, tokens, positions, budget):
        """Run up to k draft decode steps and return {slot: proposals}.
        Every open draft stream is fed a committed (token, position)
        pair each step — a slot past its budget freezes on its last
        pair, an identical K/V rewrite, so no draft write is ever
        uncommitted garbage at a position another row still reads."""
        props = {s: [] for s in live}
        chain = [s for s in live
                 if s not in self._draft_dead and budget[s] > 0]
        if not chain:
            return props
        S = self.slots
        cur_tok = {s: int(tokens[s]) for s in chain}
        cur_pos = {s: int(positions[s]) for s in chain}
        dt = np.zeros((S,), np.int64)
        dp = np.zeros((S,), np.int32)
        for _ in range(max(budget[s] for s in chain)):
            for s in chain:
                dt[s] = cur_tok[s]
                dp[s] = cur_pos[s]
            try:
                ids = self._draft.decode_step(dt, dp)
            except CacheExhaustedError:
                break                    # verify what we already have
            for s in chain:
                if len(props[s]) < budget[s]:
                    nxt = int(ids[s])
                    props[s].append(nxt)
                    cur_tok[s] = nxt
                    cur_pos[s] += 1
        return props

    def _draft_sync(self, gaps, live, tokens, positions):
        """Feed the draft the one token per fully-accepting slot it
        never saw (the chain proposes q_k without consuming it). Other
        draft streams freeze on their base pair — identical rewrites.
        A failure here only costs future accept rate: verify never
        trusts the draft."""
        S = self.slots
        dt = np.zeros((S,), np.int64)
        dp = np.zeros((S,), np.int32)
        for s in live:
            dt[s] = int(tokens[s])
            dp[s] = int(positions[s])
        for s, tok, pos in gaps:
            dt[s] = tok
            dp[s] = pos
        try:
            self._draft.decode_step(dt, dp)
        except CacheExhaustedError:
            pass

    def _adapt(self, proposed, accepted):
        self._win_proposed += proposed
        self._win_accepted += accepted
        if self._win_proposed < _ADAPT_WINDOW:
            return
        rate = self._win_accepted / self._win_proposed
        if rate >= _ADAPT_RAISE:
            self._k_live = min(self.spec_k, self._k_live + 1)
        elif rate < _ADAPT_LOWER:
            self._k_live = max(1, self._k_live - 1)
        self._win_proposed = self._win_accepted = 0

    def spec_step(self, tokens, positions):
        """One draft->verify iteration over every live stream.

        tokens [slots] (each stream's last emitted token), positions
        [slots] (its absolute position) — the decode_step ABI. Returns
        {slot: [emitted tokens]} with 1..k+1 tokens per live slot, the
        exact prefix the plain greedy path would have produced. On
        mid-verify CacheExhaustedError the whole speculation is rolled
        back (PR-12 deferred-unref discipline: COW sources were not
        dropped yet) and the iteration retries as ONE plain decode
        step; if even that cannot grow, decode_step's own typed error
        propagates with the victim slots named."""
        S, P, pt = self.slots, self.pages_per_slot, self.page_tokens
        tokens = np.asarray(tokens, np.int64).reshape(S)
        positions = np.asarray(positions, np.int32).reshape(S)
        live = [s for s in sorted(self._tables)
                if s not in self._pending]
        if not live:
            return {}
        # per-slot proposal budget: the adaptive k, clamped so the
        # bonus position stays inside the window (a stream at its last
        # position verifies just its base row — a plain decode step in
        # verify clothing)
        budget = {s: (0 if s in self._draft_dead else
                      max(0, min(self._k_live,
                                 self.max_len - 1 - int(positions[s]))))
                  for s in live}
        props = self._draft_chain(live, tokens, positions, budget)

        K1 = self.spec_k + 1
        sentinel = P * pt                  # out of range -> null page
        vtok = np.zeros((S, K1, 1), np.int64)
        vpos = np.full((S, K1), sentinel, np.int32)
        table_feed = np.zeros((S, P), np.int32)
        cow_src = np.zeros((S,), np.int32)
        cow_dst = np.zeros((S,), np.int32)
        cows, grows, failed = [], [], []
        n_of = {}
        for s in live:
            table = self._tables[s]
            pos = int(positions[s])
            n = min(len(props[s]), budget[s])
            n_of[s] = n
            before = len(table.pages)
            try:
                pair = table.cow_for_append(pos)
                if pair is not None:
                    cows.append((table, pos // pt, pair))
                table.ensure(pos + n + 1)
            except CacheExhaustedError:
                failed.append(s)
                continue
            if len(table.pages) > before:
                grows.append((table, before))
            table.row(table_feed[s])
            vtok[s, 0, 0] = int(tokens[s])
            for r in range(n):
                vtok[s, r + 1, 0] = props[s][r]
            vpos[s, :n + 1] = pos + np.arange(n + 1, dtype=np.int32)
            if pair is not None:
                cow_src[s], cow_dst[s] = pair
        if failed:
            # mid-verify exhaustion: undo this call's COWs and grows
            # (device untouched — the program never ran) and retry as a
            # plain decode step. decode_step re-forks the same frontier
            # pages deterministically, so the retry is bit-exact.
            self._rollback(cows, grows)
            self._update_gauges()
            _fallback_steps.inc()
            self._stat_fallbacks += 1
            ids = PagedDecodePredictor.decode_step(self, tokens,
                                                   positions)
            out = {s: [int(ids[s])] for s in live}
            self._account(out, {s: 0 for s in live},
                          {s: 0 for s in live})
            return out

        _logits, ids = self._exe.run(
            self._spair.verify_program,
            feed={'verify_tokens': vtok,
                  'verify_positions': vpos,
                  'verify_page_table': table_feed,
                  'verify_cow_src': cow_src,
                  'verify_cow_dst': cow_dst},
            fetch_list=self._spair.verify_fetches,
            scope=self._scope, return_numpy=False)
        ids = np.asarray(ids)              # [S, K1] target greedy
        for table, _idx, (src, _dst) in cows:
            table.pool.unref(src)

        out, accepts, gaps = {}, {}, []
        for s in live:
            n, pos = n_of[s], int(positions[s])
            a = 0
            while a < n and props[s][a] == int(ids[s, a]):
                a += 1
            out[s] = props[s][:a] + [int(ids[s, a])]
            accepts[s] = a
            table = self._tables[s]
            table.length = max(table.length, pos + a + 1)
            if n and a == n:
                # full accept: the chain never fed its own last
                # proposal — close the draft cache gap at pos + n
                gaps.append((s, props[s][n - 1], pos + n))
        if gaps:
            self._draft_sync(gaps, live, tokens, positions)
        self._update_gauges()
        self._account(out, n_of, accepts)
        return out

    def _account(self, out, proposed, accepted):
        emitted = sum(len(v) for v in out.values())
        n_prop = sum(proposed.values())
        n_acc = sum(accepted.values())
        self._stat_steps += 1
        self._stat_emitted += emitted
        self._stat_slot_steps += len(out)
        self._stat_drafted += n_prop
        self._stat_accepted += n_acc
        if n_prop:
            _draft_tokens.inc(n_prop)
            _accepted_tokens.inc(n_acc)
            _rejected_tokens.inc(n_prop - n_acc)
            _accept_rate.observe(n_acc / n_prop)
            self._adapt(n_prop, n_acc)
        if out:
            _effective_tps.set(emitted / len(out))

    # -- solo path ---------------------------------------------------------
    def generate(self, prompt, max_new_tokens, eos_id=None, slot=0):
        """Solo greedy generation through the speculative path — same
        contract (and, by the acceptance rule, same output) as the
        plain predictors' generate()."""
        slot = int(slot)
        if slot in self._tables:
            self.release(slot)
        self.open_stream(slot, prompt)
        tok = None
        while tok is None:
            tok = self.prefill_step(slot)
        tok = int(tok)
        out = [tok]
        pos = len(np.asarray(prompt).reshape(-1))
        toks = np.zeros((self.slots,), np.int64)
        poss = np.zeros((self.slots,), np.int32)
        while len(out) < max_new_tokens and tok != eos_id:
            toks[slot] = tok
            poss[slot] = pos
            for t in self.spec_step(toks, poss)[slot]:
                tok = int(t)
                out.append(tok)
                pos += 1
                if len(out) >= max_new_tokens or tok == eos_id:
                    break
        return out
