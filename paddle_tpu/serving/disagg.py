"""Disaggregated prefill/decode serving: KV pages as wire objects.

The DistServe/Mooncake split re-based onto this repo's paged cache: a
PREFILL TIER of replicas runs the compute-bound prompt pass and a
DECODE TIER runs the HBM-bound token loop, so the two capacities scale
independently and a long prompt never steals step time from live
decode lanes. The unit of transfer is the page — fixed-size,
refcounted, and content-addressed by the sha1 hash chain
(serving/paging.py) — so a shipment is just "these chain keys, these
float32 rows" and a receiver can verify, dedup, and install it with
machinery that already exists (PagePool.restore_pages +
PrefixCache.extend_chain).

The flow is DECODE-PULL. The router dispatches a stream to a decode
replica with meta['prefill_from'] naming a prefill peer; the decode
replica acks immediately and a ship thread:

    1. computes its 'have' list (resident chain keys for the prompt) —
       a full local hit skips the wire entirely;
    2. sends SRV_PAGE_FETCH (prompt + have) to the prefill peer, which
       prefills on a cache miss (once per unique prefix fleet-wide —
       later fetches hit its PrefixCache) and replies with one
       SRV_PAGES frame carrying only the pages the requester lacked;
    3. installs the shipment at a step boundary and submits the stream
       locally with the REMAINING deadline budget — TTFT is ship time,
       not prefill time.

Every failure mode — peer dead, graying mid-ship (the socket timeout
is FLAGS_disagg_ship_timeout), corrupt frame, key mismatch, pool
exhaustion — degrades to LOCAL RE-PREFILL on the decode replica,
bit-exact with the shipped path by greedy determinism. Nothing on this
path is load-bearing for correctness; it only moves where the prefill
FLOPs are spent.

Telemetry: disagg.pages_shipped / disagg.ship_bytes /
disagg.pages_installed / disagg.pages_deduped counters,
disagg.local_reprefills (fallbacks taken), disagg.ship_latency
histogram (fetch + install seconds).
"""
from __future__ import annotations

import socket
import time

import numpy as np

from ..distributed import wire
from ..flags import get_flag
from ..obs import telemetry

__all__ = ['ShipError', 'pack_pages', 'unpack_rows', 'install_shipment',
           'serve_page_fetch', 'fetch_and_install']

_pages_shipped = telemetry.counter('disagg.pages_shipped')
_ship_bytes = telemetry.counter('disagg.ship_bytes')
_pages_installed = telemetry.counter('disagg.pages_installed')
_pages_deduped = telemetry.counter('disagg.pages_deduped')
_local_reprefills = telemetry.counter('disagg.local_reprefills')
_ship_latency = telemetry.histogram('disagg.ship_latency')


class ShipError(RuntimeError):
    """A page ship failed (peer dead/slow, frame corrupt, keys refused,
    budget spent). Always recoverable: the caller re-prefills locally
    and the stream proceeds bit-exact."""


def pack_pages(prompt, export, have=()):
    """Build the SRV_PAGES (meta, value) pair from an
    LMServer.export_prefix() result, omitting the leading pages the
    receiver's `have` key list already holds. meta['keys'] is the FULL
    chain run (receiver re-verifies it against its own hash of the
    prompt); meta['skip'] counts the omitted leading rows; the value is
    one float32 [pools, shipped_pages, page_tokens, ...] array (None
    when everything deduped)."""
    keys = list(export['keys'])
    skip = 0
    for mine, theirs in zip(keys, have):
        if mine != theirs:
            break
        skip += 1
    meta = {'keys': keys, 'skip': skip,
            'prompt': [int(t) for t in prompt],
            'page_tokens': int(export['tokens'] // max(1, len(keys)))}
    if skip >= len(keys):
        return meta, None
    value = np.stack([np.asarray(rows[skip:], np.float32)
                      for rows in export['data']])
    return meta, value


def unpack_rows(meta, value):
    """The shipped per-pool row arrays from an SRV_PAGES frame — [] when
    the frame was a pure dedup ack."""
    if value is None:
        return []
    arr = np.asarray(value, np.float32)
    return [arr[i] for i in range(arr.shape[0])]


def install_shipment(srv, meta, value):
    """Install one SRV_PAGES frame into `srv` (an LMServer). Returns
    (installed, deduped) page counts. ValueError (keys refused) and
    CacheExhaustedError propagate — the replica's dispatch crosses them
    to the pusher as REPLY_ERR with the usual retryable split."""
    prompt = [int(t) for t in meta['prompt']]
    keys = list(meta.get('keys') or ())
    installed, deduped = srv.install_prefix(
        prompt, keys, unpack_rows(meta, value),
        skip=int(meta.get('skip', 0)))
    _pages_installed.inc(installed)
    _pages_deduped.inc(deduped)
    return installed, deduped


def serve_page_fetch(srv, meta, value):
    """The prefill tier's half: answer one SRV_PAGE_FETCH with the
    (meta, value) of the SRV_PAGES reply. Prefills the prompt locally
    when its pages are not already cached — srv.submit with
    max_new_tokens=1 registers every full prompt page with the
    PrefixCache, so the SECOND fetch of the same prefix ships straight
    from cache (prefill once per unique prefix fleet-wide). A
    deadline_ms in the fetch meta bounds the prefill wait; on expiry
    the typed DeadlineExceededError crosses back as non-retryable and
    the requester eats the remaining budget locally."""
    prompt = [int(t) for t in np.asarray(value).reshape(-1)]
    have = [str(k) for k in meta.get('have') or ()]
    export = srv.export_prefix(prompt)
    full = (len(prompt) - 1) // max(1, _page_tokens(srv))
    if full > 0 and (export is None or len(export['keys']) < full):
        # cache miss (or a partially evicted chain): run the prefill —
        # one generated token is the cheapest complete prefill, and
        # registration happens on the final prefill chunk
        ddl = meta.get('deadline_ms')
        handle = srv.submit(prompt, max_new_tokens=1,
                            deadline_ms=None if ddl is None
                            else float(ddl))
        srv.result(handle)
        export = srv.export_prefix(prompt)
    if export is None:
        # sub-page prompt (or the pool evicted everything under
        # pressure): nothing shippable, the requester prefills locally
        return ({'keys': [], 'skip': 0, 'prompt': prompt,
                 'page_tokens': _page_tokens(srv)}, None)
    rmeta, rvalue = pack_pages(prompt, export, have=have)
    shipped = len(rmeta['keys']) - rmeta['skip']
    _pages_shipped.inc(shipped)
    _pages_deduped.inc(rmeta['skip'])
    if rvalue is not None:
        _ship_bytes.inc(int(rvalue.nbytes))
    return rmeta, rvalue


def _page_tokens(srv):
    stats = srv.stats().get('kv') or {}
    return int(stats.get('page_tokens') or 0)


def fetch_and_install(srv, endpoint, prompt, deadline_at=None,
                      timeout=None):
    """The decode tier's half: pull `prompt`'s pages from the prefill
    replica at `endpoint` and install them into `srv`. Returns
    {'installed', 'deduped', 'fetched', 'bytes'}; raises ShipError on
    ANY failure (the caller falls back to local prefill). `deadline_at`
    (absolute perf_counter, from the stream's submit meta) is deducted
    at every stage — the fetch forwards only the REMAINING milliseconds
    and the socket never waits past min(remaining,
    FLAGS_disagg_ship_timeout)."""
    t0 = time.perf_counter()
    budget = float(timeout if timeout is not None
                   else get_flag('disagg_ship_timeout'))
    prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
    have = srv.resident_keys(prompt)
    full = (len(prompt) - 1) // max(1, _page_tokens(srv))
    if len(have) >= full:
        # the whole shippable chain is already local — zero wire bytes
        return {'installed': 0, 'deduped': full, 'fetched': False,
                'bytes': 0}
    fmeta = {'have': have}
    if deadline_at is not None:
        remaining = deadline_at - time.perf_counter()
        if remaining <= 0:
            raise ShipError('deadline spent before the page fetch')
        fmeta['deadline_ms'] = max(1.0, remaining * 1000.0)
        budget = min(budget, remaining)
    host, port = endpoint.rsplit(':', 1)
    sock = None
    try:
        sock = socket.create_connection(
            (host, int(port)),
            timeout=min(budget, float(get_flag('fleet_connect_timeout'))))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(budget)
        wire.write_msg(sock, wire.SRV_PAGE_FETCH, fmeta,
                       np.asarray(prompt, np.int64))
        rt, rmeta, rvalue = wire.read_msg(sock)
    except (ConnectionError, OSError) as e:
        raise ShipError('page fetch from %s failed: %s' % (endpoint, e))
    finally:
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
    if rt == wire.REPLY_ERR:
        raise ShipError('prefill peer %s refused the fetch: %s'
                        % (endpoint, rmeta.get('error')))
    if rt != wire.SRV_PAGES:
        raise ShipError('prefill peer %s answered msg type %d, expected '
                        'SRV_PAGES' % (endpoint, rt))
    if deadline_at is not None and time.perf_counter() >= deadline_at:
        raise ShipError('deadline spent during the page fetch')
    try:
        installed, deduped = install_shipment(srv, rmeta, rvalue)
    except (ValueError, RuntimeError) as e:
        raise ShipError('shipment from %s refused: %s' % (endpoint, e))
    _ship_latency.observe(time.perf_counter() - t0)
    nbytes = 0 if rvalue is None else int(np.asarray(rvalue).nbytes)
    return {'installed': installed, 'deduped': deduped, 'fetched': True,
            'bytes': nbytes}


def count_local_reprefill():
    """Bump disagg.local_reprefills — the ship path's fallback taken
    (replica.py calls this when a ship fails and the stream prefills
    locally instead)."""
    _local_reprefills.inc()
