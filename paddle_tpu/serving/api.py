"""LMServer: the user-facing serving surface.

The reference inference/api contract (CreatePaddlePredictor -> Run)
re-shaped for token streams: construct from a saved-model dir (or an
existing AnalysisPredictor), then either block in generate() or go
async with submit()/poll()/result()/cancel(). One ServingEngine runs
underneath; workers share weights through Predictor clone() semantics.

    with LMServer(model_dir, place, slots=8) as srv:
        out = srv.generate([1, 2, 3], max_new_tokens=32, eos_id=2)
        h = srv.submit([4, 5], max_new_tokens=8)
        ...
        tokens = srv.result(h)
"""
from __future__ import annotations

from .engine import ServingEngine

__all__ = ['LMServer']


class LMServer(object):
    def __init__(self, model_dir_or_predictor, place=None, slots=None,
                 prefill_batch=None, workers=1, max_queue=None,
                 paged=False, page_tokens=None, kv_pages=None,
                 prefill_chunk=None, speculative=False, spec_k=None,
                 draft_layers=None, mesh=None):
        """model_dir_or_predictor: a save_inference_model directory, an
        AnalysisPredictor, or an already-prepared DecodePredictor.
        paged=True serves from the page-pool cache (serving/paged.py):
        copy-on-write prefix sharing plus chunked prefill, sized by
        page_tokens / kv_pages / prefill_chunk (each None defaults
        from FLAGS_serving_*). speculative=True (implies paged) serves
        through draft/verify speculation (serving/speculative.py);
        spec_k / draft_layers default from FLAGS_spec_*. mesh shards
        the decode programs GSPMD over a device mesh ('tp=2'; None =
        read FLAGS_serve_mesh_shape, '' = single-chip) with greedy
        output bit-exact vs single-chip (serving/mesh.py)."""
        from .decode import DecodePredictor
        obj = model_dir_or_predictor
        if isinstance(obj, DecodePredictor):
            dec = obj
        else:
            if isinstance(obj, str):
                from ..inference import AnalysisConfig, AnalysisPredictor
                obj = AnalysisPredictor(AnalysisConfig(obj, place=place))
            if speculative:
                dec = obj.prepare_decoding(slots=slots, speculative=True,
                                           spec_k=spec_k,
                                           draft_layers=draft_layers,
                                           page_tokens=page_tokens,
                                           kv_pages=kv_pages,
                                           prefill_chunk=prefill_chunk,
                                           mesh=mesh)
            elif paged:
                dec = obj.prepare_decoding(slots=slots, paged=True,
                                           page_tokens=page_tokens,
                                           kv_pages=kv_pages,
                                           prefill_chunk=prefill_chunk,
                                           mesh=mesh)
            else:
                dec = obj.prepare_decoding(slots=slots,
                                           prefill_batch=prefill_batch,
                                           mesh=mesh)
        self._decode = dec
        self._engine = ServingEngine(dec, workers=workers,
                                     max_queue=max_queue)
        self._requests = {}
        self._subscriber = None
        self._engine.start()

    # -- online refresh ----------------------------------------------------
    def enable_refresh(self, endpoints, subscriber_id=0, poll_secs=None,
                       pull_timeout=None, start=True, paused=False):
        """Attach a ParamSubscriber (paddle_tpu/online/): serving
        tracks the pserver fleet's published param versions and
        installs fresh weights at decode step boundaries. Returns the
        subscriber (started unless start=False). paused=True starts the
        poll loop but freezes automatic installs — the fleet-replica
        posture, where only an orchestrator-driven refresh_once() (a
        rolling deploy's SRV_REFRESH) installs, while staleness keeps
        being measured."""
        if self._subscriber is not None:
            return self._subscriber
        from ..online import ParamSubscriber
        self._subscriber = ParamSubscriber(
            endpoints, self._decode, engine=self._engine,
            subscriber_id=subscriber_id, poll_secs=poll_secs,
            pull_timeout=pull_timeout)
        if start:
            self._subscriber.start()
        if paused:
            self._subscriber.pause()
        return self._subscriber

    @property
    def subscriber(self):
        """The attached ParamSubscriber, or None."""
        return self._subscriber

    def refresh_once(self):
        """One orchestrator-driven refresh (pull + verify + install at
        a step boundary); returns the installed version. Raises
        RuntimeError when no refresh machinery is attached, RefreshError
        (old weights untouched) on a failed pull."""
        if self._subscriber is None:
            raise RuntimeError('no refresh attached — call '
                               'enable_refresh(endpoints) first')
        return self._subscriber.refresh_once()

    # -- blocking ----------------------------------------------------------
    def generate(self, prompt, max_new_tokens=16, eos_id=None,
                 timeout=None):
        """Greedy-decode and return the generated token ids."""
        return self._engine.generate(prompt, max_new_tokens,
                                     eos_id=eos_id, timeout=timeout)

    # -- async -------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               priority=0, deadline_ms=None):
        """Enqueue; returns an opaque handle for poll()/result().
        priority is the SLO tier (higher = more important, 0 = the
        default lowest tier — the only tier admission ever rejects),
        deadline_ms the optional end-to-end budget (None = no deadline;
        see ServingEngine.submit for the expiry semantics)."""
        req = self._engine.submit(prompt, max_new_tokens, eos_id=eos_id,
                                  priority=priority,
                                  deadline_ms=deadline_ms)
        self._requests[req.id] = req
        return req.id

    def _req(self, handle):
        try:
            return self._requests[handle]
        except KeyError:
            raise KeyError('unknown request handle %r' % (handle,))

    def poll(self, handle):
        """Non-blocking progress snapshot: {'state', 'tokens'} — tokens
        is the stream generated SO FAR, safe to read mid-decode. A
        FAILED stream carries 'error' too, so the failure class (e.g.
        a typed DeadlineExceededError) survives the SRV_POLL hop to
        the router; peers that predate the key simply ignore it."""
        req = self._req(handle)
        out = {'state': req.state, 'tokens': list(req.tokens)}
        if req.error is not None:
            out['error'] = str(req.error)
        return out

    def result(self, handle, timeout=None):
        """Block for the final token stream (see Request.result)."""
        return self._req(handle).result(timeout)

    def cancel(self, handle):
        self._engine.cancel(self._req(handle))

    # -- disaggregated page shipping (serving/disagg.py) -------------------
    @property
    def paged(self):
        """True when serving from the page-pool cache — the only mode
        page shipping and the fleet prefix directory apply to."""
        return bool(getattr(self._decode, 'paged', False))

    def export_prefix(self, prompt):
        """Longest resident full-page chain for `prompt` as host copies
        (see ServingEngine.export_prefix); None when non-paged or cold."""
        return self._engine.export_prefix(prompt)

    def install_prefix(self, prompt, keys, data, skip=0):
        """Install a shipped page run (see ServingEngine.install_prefix);
        returns (installed, deduped) page counts."""
        return self._engine.install_prefix(prompt, keys, data, skip=skip)

    def resident_keys(self, prompt):
        """Hex keys of the locally resident leading chain run for
        `prompt` — the 'have' list a page fetch advertises."""
        return self._engine.resident_keys(prompt)

    def prefix_report(self):
        """Drain {'new', 'evicted'} prefix-chain hex keys since the
        last call — the SRV_HEALTH directory delta."""
        return self._engine.prefix_report()

    # -- ops ---------------------------------------------------------------
    @property
    def max_len(self):
        """Context-window bound: prompt + generated tokens per stream."""
        return self._decode.max_len

    def param_digests(self):
        """{param name: crc32 of its wire payload} for every served
        weight — what a rolling deploy's convergence check compares
        against the pserver manifest."""
        return self._decode.param_digests()

    def drain(self, timeout=None):
        """Wait for queued + running streams to finish WITHOUT closing;
        True once idle, False when `timeout` expired first."""
        return self._engine.drain(timeout)

    def stats(self):
        """Engine stats plus the online-refresh position: param_version
        (installed; None before any refresh machinery is attached) and
        staleness_rounds (rounds behind the newest published version)."""
        out = self._engine.stats()
        if self._subscriber is not None:
            sub = self._subscriber.stats()
            out['param_version'] = sub['installed_version']
            out['staleness_rounds'] = sub['staleness_rounds']
            out['refreshes'] = sub['refreshes']
            out['refresh_failures'] = sub['failures']
        else:
            out['param_version'] = None
            out['staleness_rounds'] = None
        return out

    def close(self, drain=True, timeout=None):
        """drain=True waits for in-flight streams; a `timeout` bounds
        the wait and then escalates to cancel-and-close instead of
        hanging forever on a stuck stream (ServingEngine.stop). Returns
        True for a clean drain, False when the escalation fired."""
        if self._subscriber is not None:
            self._subscriber.stop()
            self._subscriber = None
        return self._engine.stop(drain=drain, timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
