"""ReplicaServer: one LMServer behind the wire, fleet-addressable.

The serving half of the fleet topology (serving/fleet.py): a thin
threaded TCP server — same framing, accept loop and reply conventions
as distributed/rpc.PSServer — dispatching the SRV_* message types into
a local LMServer. A FleetRouter talks to N of these:

  SRV_SUBMIT   open a stream (rid, prompt ids, budget, eos)
  SRV_POLL     batched progress of many rids -> {state, tokens}
  SRV_CANCEL   cancel one stream
  SRV_HEALTH   liveness + load probe (queue depth, active, capacity,
               param version, draining; optional param digests)
  SRV_DRAIN    admission fence on/off (rolling-deploy drain step)
  SRV_REFRESH  orchestrator-driven ParamSubscriber.refresh_once()
  SRV_PAGES    install a pushed KV-page shipment (serving/disagg.py);
               ack carries {installed, deduped}
  SRV_PAGE_FETCH  prefill the meta-described prompt (cache hit = free)
               and reply with an SRV_PAGES frame — the prefill tier's
               serving surface
  COMPLETE     clean shutdown (the tools/serve_replica.py exit path)

A SUBMIT whose meta names a prefill peer ('prefill_from') is acked
immediately and a ship thread pulls the prompt's pages from that peer
before the local submit (disagg.fetch_and_install) — the stream polls
as QUEUED while shipping, and ANY ship failure falls back to local
re-prefill with the remaining deadline budget (bit-exact by greedy
determinism).

Error classification crosses the wire like the pserver's: a reply
REPLY_ERR with retryable=True (queue full, draining, a failed-but-
retryable refresh) invites the router to try elsewhere/later; anything
else is stream-fatal. Every reply echoes the request's seq.

Stream state is process-local: a kill-9'd replica loses its rids, and
its restarted incarnation answers SRV_POLL for them with UNKNOWN — the
router's failover treats both the dead connection and the UNKNOWN
answer as the same signal and re-prefills the stream elsewhere.
"""
from __future__ import annotations

import socket
import threading
import time

import numpy as np

from ..distributed import wire
from . import disagg

__all__ = ['ReplicaServer']

UNKNOWN = 'UNKNOWN'


class _ShippingStream(object):
    """Placeholder handle for a stream whose pages are still in flight
    from the prefill tier: polls as QUEUED, flips to the real LMServer
    handle (or a dead-letter FAILED) when the ship thread finishes.
    Cancellation is a flag the ship thread honors before the local
    submit."""

    __slots__ = ('cancelled', 'error')

    def __init__(self):
        self.cancelled = False
        self.error = None

    def poll(self):
        if self.error is not None:
            return {'state': 'FAILED', 'tokens': [],
                    'error': self.error}
        if self.cancelled:
            return {'state': 'CANCELLED', 'tokens': []}
        return {'state': 'QUEUED', 'tokens': []}


class ReplicaServer(object):
    def __init__(self, server, endpoint='127.0.0.1:0',
                 bind_retry_secs=30.0):
        """server: the LMServer to expose. Binds immediately (with the
        PSServer restart-race retry) so `.port` is known before
        serve_forever()."""
        self._srv = server
        host, port = endpoint.rsplit(':', 1)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        deadline = time.monotonic() + bind_retry_secs
        while True:
            try:
                self._lsock.bind((host, int(port)))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._done = threading.Event()
        self._threads = []
        self._lock = threading.Lock()
        self._streams = {}            # rid -> LMServer handle
        self._draining = False
        # disaggregated-serving counters (SRV_HEALTH feeds these to the
        # router's fleet.* aggregates)
        self._pages_shipped_n = 0     # prefill side: rows sent
        self._ship_bytes_n = 0
        self._pages_installed_n = 0   # decode side: rows grafted
        self._pages_deduped_n = 0
        self._local_reprefills_n = 0  # ship failures eaten locally

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        accept_t = threading.Thread(target=self._accept_loop,
                                    daemon=True)
        accept_t.start()
        self._done.wait()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def shutdown(self):
        self._done.set()

    def _accept_loop(self):
        while not self._done.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # -- dispatch ----------------------------------------------------------
    def _serve_conn(self, conn):
        try:
            while True:
                msg_type, meta, value = wire.read_msg(conn)
                ack = {'seq': meta['seq']} if 'seq' in meta else {}
                try:
                    self._dispatch(conn, msg_type, meta, value, ack)
                except (ConnectionError, OSError):
                    return
                except Exception as e:   # noqa: BLE001 — cross the wire
                    err = dict(ack)
                    err.update({'error': str(e),
                                'retryable': _retryable(e)})
                    wire.write_msg(conn, wire.REPLY_ERR, err)
        except (ConnectionError, OSError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, msg_type, meta, value, ack):
        if msg_type == wire.SRV_SUBMIT:
            self._on_submit(conn, meta, value, ack)
        elif msg_type == wire.SRV_POLL:
            self._on_poll(conn, meta, ack)
        elif msg_type == wire.SRV_CANCEL:
            with self._lock:
                handle = self._streams.get(meta['rid'])
            if isinstance(handle, _ShippingStream):
                handle.cancelled = True
            elif handle is not None:
                self._srv.cancel(handle)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.SRV_PAGES:
            installed, deduped = disagg.install_shipment(self._srv,
                                                         meta, value)
            with self._lock:
                self._pages_installed_n += installed
                self._pages_deduped_n += deduped
            reply = dict(ack)
            reply.update({'installed': installed, 'deduped': deduped})
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.SRV_PAGE_FETCH:
            rmeta, rvalue = disagg.serve_page_fetch(self._srv, meta,
                                                    value)
            with self._lock:
                self._pages_shipped_n += (len(rmeta['keys'])
                                          - rmeta['skip'])
                if rvalue is not None:
                    self._ship_bytes_n += int(rvalue.nbytes)
            reply = dict(ack)
            reply.update(rmeta)
            wire.write_msg(conn, wire.SRV_PAGES, reply, rvalue)
        elif msg_type == wire.SRV_HEALTH:
            reply = dict(ack)
            reply.update(self._health(bool(meta.get('digests'))))
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.SRV_DRAIN:
            self._draining = bool(meta.get('on', True))
            reply = dict(ack)
            reply['draining'] = self._draining
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.SRV_REFRESH:
            if self._srv.subscriber is None:
                err = dict(ack)
                err.update({'error': 'no refresh attached — replica '
                                     'launched without pserver '
                                     'endpoints', 'retryable': False})
                wire.write_msg(conn, wire.REPLY_ERR, err)
                return
            version = self._srv.refresh_once()
            reply = dict(ack)
            reply['param_version'] = int(version)
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.COMPLETE:
            wire.write_msg(conn, wire.REPLY_OK, ack)
            self.shutdown()
        else:
            err = dict(ack)
            err.update({'error': 'replica cannot serve msg type %d'
                                 % msg_type, 'retryable': False})
            wire.write_msg(conn, wire.REPLY_ERR, err)

    def _on_submit(self, conn, meta, value, ack):
        rid = meta['rid']
        if self._draining:
            err = dict(ack)
            err.update({'error': 'replica draining', 'retryable': True})
            wire.write_msg(conn, wire.REPLY_ERR, err)
            return
        prompt = [int(t) for t in np.asarray(value).reshape(-1)]
        # deadline_ms rides the meta only when the peer set one — an
        # old router's meta simply lacks the key and decodes to None
        ddl = meta.get('deadline_ms')
        peer = meta.get('prefill_from')
        if peer and getattr(self._srv, 'paged', False):
            # disaggregated dispatch: ack now, ship pages off-thread,
            # submit locally when they land (or when the ship fails —
            # local re-prefill, bit-exact by greedy determinism). The
            # deadline clock starts HERE so every downstream stage
            # deducts elapsed time from one absolute budget.
            deadline_at = (None if ddl is None
                           else time.perf_counter() + float(ddl) / 1000.0)
            sentinel = _ShippingStream()
            with self._lock:
                self._streams[rid] = sentinel
            t = threading.Thread(
                target=self._ship_and_submit,
                args=(rid, sentinel, str(peer), prompt, meta,
                      deadline_at),
                daemon=True)
            t.start()
            wire.write_msg(conn, wire.REPLY_OK, ack)
            return
        handle = self._srv.submit(prompt,
                                  max_new_tokens=int(meta['mnt']),
                                  eos_id=meta.get('eos'),
                                  priority=int(meta.get('prio', 0)),
                                  deadline_ms=None if ddl is None
                                  else float(ddl))
        with self._lock:
            self._streams[rid] = handle
        wire.write_msg(conn, wire.REPLY_OK, ack)

    def _ship_and_submit(self, rid, sentinel, peer, prompt, meta,
                         deadline_at):
        """Ship-thread body: fetch + install the prompt's pages from
        the prefill peer, then run the normal local submit with the
        REMAINING deadline. A dead/gray/slow peer, a refused shipment,
        or a spent budget all converge on the same fallback — submit
        locally anyway; only a failure of the LOCAL submit dead-letters
        the stream (the router sees FAILED with the error string)."""
        try:
            disagg.fetch_and_install(self._srv, peer, prompt,
                                     deadline_at=deadline_at)
        except Exception:  # noqa: BLE001 — every ship failure falls back
            disagg.count_local_reprefill()
            with self._lock:
                self._local_reprefills_n += 1
        if sentinel.cancelled:
            return
        remaining = (None if deadline_at is None
                     else max(1.0, (deadline_at - time.perf_counter())
                              * 1000.0))
        try:
            handle = self._srv.submit(prompt,
                                      max_new_tokens=int(meta['mnt']),
                                      eos_id=meta.get('eos'),
                                      priority=int(meta.get('prio', 0)),
                                      deadline_ms=remaining)
        except Exception as e:  # noqa: BLE001 — dead-letter for the poll
            sentinel.error = str(e)
            return
        with self._lock:
            if sentinel.cancelled:
                self._srv.cancel(handle)
                return
            self._streams[rid] = handle

    def _on_poll(self, conn, meta, ack):
        out = {}
        for rid in meta.get('rids', ()):
            with self._lock:
                handle = self._streams.get(rid)
            if handle is None:
                out[rid] = {'state': UNKNOWN, 'tokens': []}
            elif isinstance(handle, _ShippingStream):
                out[rid] = handle.poll()
            else:
                out[rid] = self._srv.poll(handle)
        reply = dict(ack)
        reply['streams'] = out
        wire.write_msg(conn, wire.REPLY_OK, reply)

    def _health(self, with_digests):
        stats = self._srv.stats()
        out = {'queue_depth': stats['queue_depth'],
               'active': stats['active'],
               'workers': stats['workers'],
               'capacity': stats['workers'] * stats['slots_per_worker'],
               'max_len': self._srv.max_len,
               'param_version': stats.get('param_version'),
               'staleness_rounds': stats.get('staleness_rounds'),
               # paged-cache pressure: tokens held across live slots vs
               # total cache capacity — the router weighs this beyond
               # lane counts (a worker full of 4k streams is hotter
               # than one full of 16-token streams)
               'cache_tokens': stats.get('cache_tokens', 0),
               'cache_capacity': stats.get('cache_capacity'),
               # speculative replicas emit >1 token per step on
               # average: the router divides its load score by this so
               # a high-accept-rate replica looks proportionally roomier
               'effective_tokens_per_step':
                   stats.get('effective_tokens_per_step'),
               'spec_accept_rate':
                   stats.get('spec', {}).get('accept_rate'),
               # preempt-first capacity (serving/preempt.py): lifetime
               # preemptions plus streams currently swapped out and
               # waiting to resume — the router's dispatch score
               # treats waiting preempted streams as cache pressure
               'preemptions': stats.get('preemptions', 0),
               'preempted_streams': stats.get('preempted_streams', 0),
               # mesh-sharded serving: the axis spec ('' = single-chip)
               # and chip count this replica's SPMD programs span — the
               # fleet surfaces both so per-chip throughput is auditable
               'mesh_shape': stats.get('mesh_shape', ''),
               'mesh_devices': stats.get('mesh_devices', 1),
               'draining': self._draining}
        with self._lock:
            out['pages_shipped'] = self._pages_shipped_n
            out['ship_bytes'] = self._ship_bytes_n
            out['pages_installed'] = self._pages_installed_n
            out['pages_deduped'] = self._pages_deduped_n
            out['local_reprefills'] = self._local_reprefills_n
        kv = stats.get('kv')
        if kv:
            # prefix-cache truth for the router's fleet directory: the
            # counters seed fleet.prefix_hit_rate, the drained new/
            # evicted key deltas reconcile the directory against what
            # is ACTUALLY resident here (not router dispatch guesses)
            out['page_tokens'] = kv.get('page_tokens')
            out['prefix_entries'] = kv.get('prefix_entries', 0)
            out['prefix_hits'] = kv.get('prefix_hits', 0)
            out['prefix_misses'] = kv.get('prefix_misses', 0)
            out['prefix_pages'] = kv.get('prefix_pages', 0)
            report = self._srv.prefix_report()
            out['prefix_new'] = report['new']
            out['prefix_evicted'] = report['evicted']
        if with_digests:
            out['digests'] = self._srv.param_digests()
        return out


def _retryable(e):
    """queue-full / draining / a retryable refresh invite the router to
    come back; a bad prompt, a missing subscriber, or a spent deadline
    is stream-fatal — retrying a DeadlineExceededError elsewhere can
    only burn more of a budget that is already gone."""
    from ..online.subscriber import RefreshError
    from .engine import DeadlineExceededError
    if isinstance(e, RefreshError):
        return True
    if isinstance(e, DeadlineExceededError):
        return False
    return isinstance(e, RuntimeError) and not isinstance(e, ValueError)
