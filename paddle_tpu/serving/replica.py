"""ReplicaServer: one LMServer behind the wire, fleet-addressable.

The serving half of the fleet topology (serving/fleet.py): a thin
threaded TCP server — same framing, accept loop and reply conventions
as distributed/rpc.PSServer — dispatching the SRV_* message types into
a local LMServer. A FleetRouter talks to N of these:

  SRV_SUBMIT   open a stream (rid, prompt ids, budget, eos)
  SRV_POLL     batched progress of many rids -> {state, tokens}
  SRV_CANCEL   cancel one stream
  SRV_HEALTH   liveness + load probe (queue depth, active, capacity,
               param version, draining; optional param digests)
  SRV_DRAIN    admission fence on/off (rolling-deploy drain step)
  SRV_REFRESH  orchestrator-driven ParamSubscriber.refresh_once()
  COMPLETE     clean shutdown (the tools/serve_replica.py exit path)

Error classification crosses the wire like the pserver's: a reply
REPLY_ERR with retryable=True (queue full, draining, a failed-but-
retryable refresh) invites the router to try elsewhere/later; anything
else is stream-fatal. Every reply echoes the request's seq.

Stream state is process-local: a kill-9'd replica loses its rids, and
its restarted incarnation answers SRV_POLL for them with UNKNOWN — the
router's failover treats both the dead connection and the UNKNOWN
answer as the same signal and re-prefills the stream elsewhere.
"""
from __future__ import annotations

import socket
import threading
import time

import numpy as np

from ..distributed import wire

__all__ = ['ReplicaServer']

UNKNOWN = 'UNKNOWN'


class ReplicaServer(object):
    def __init__(self, server, endpoint='127.0.0.1:0',
                 bind_retry_secs=30.0):
        """server: the LMServer to expose. Binds immediately (with the
        PSServer restart-race retry) so `.port` is known before
        serve_forever()."""
        self._srv = server
        host, port = endpoint.rsplit(':', 1)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        deadline = time.monotonic() + bind_retry_secs
        while True:
            try:
                self._lsock.bind((host, int(port)))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._done = threading.Event()
        self._threads = []
        self._lock = threading.Lock()
        self._streams = {}            # rid -> LMServer handle
        self._draining = False

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        accept_t = threading.Thread(target=self._accept_loop,
                                    daemon=True)
        accept_t.start()
        self._done.wait()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def shutdown(self):
        self._done.set()

    def _accept_loop(self):
        while not self._done.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # -- dispatch ----------------------------------------------------------
    def _serve_conn(self, conn):
        try:
            while True:
                msg_type, meta, value = wire.read_msg(conn)
                ack = {'seq': meta['seq']} if 'seq' in meta else {}
                try:
                    self._dispatch(conn, msg_type, meta, value, ack)
                except (ConnectionError, OSError):
                    return
                except Exception as e:   # noqa: BLE001 — cross the wire
                    err = dict(ack)
                    err.update({'error': str(e),
                                'retryable': _retryable(e)})
                    wire.write_msg(conn, wire.REPLY_ERR, err)
        except (ConnectionError, OSError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, msg_type, meta, value, ack):
        if msg_type == wire.SRV_SUBMIT:
            self._on_submit(conn, meta, value, ack)
        elif msg_type == wire.SRV_POLL:
            self._on_poll(conn, meta, ack)
        elif msg_type == wire.SRV_CANCEL:
            with self._lock:
                handle = self._streams.get(meta['rid'])
            if handle is not None:
                self._srv.cancel(handle)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.SRV_HEALTH:
            reply = dict(ack)
            reply.update(self._health(bool(meta.get('digests'))))
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.SRV_DRAIN:
            self._draining = bool(meta.get('on', True))
            reply = dict(ack)
            reply['draining'] = self._draining
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.SRV_REFRESH:
            if self._srv.subscriber is None:
                err = dict(ack)
                err.update({'error': 'no refresh attached — replica '
                                     'launched without pserver '
                                     'endpoints', 'retryable': False})
                wire.write_msg(conn, wire.REPLY_ERR, err)
                return
            version = self._srv.refresh_once()
            reply = dict(ack)
            reply['param_version'] = int(version)
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.COMPLETE:
            wire.write_msg(conn, wire.REPLY_OK, ack)
            self.shutdown()
        else:
            err = dict(ack)
            err.update({'error': 'replica cannot serve msg type %d'
                                 % msg_type, 'retryable': False})
            wire.write_msg(conn, wire.REPLY_ERR, err)

    def _on_submit(self, conn, meta, value, ack):
        rid = meta['rid']
        if self._draining:
            err = dict(ack)
            err.update({'error': 'replica draining', 'retryable': True})
            wire.write_msg(conn, wire.REPLY_ERR, err)
            return
        prompt = [int(t) for t in np.asarray(value).reshape(-1)]
        # deadline_ms rides the meta only when the peer set one — an
        # old router's meta simply lacks the key and decodes to None
        ddl = meta.get('deadline_ms')
        handle = self._srv.submit(prompt,
                                  max_new_tokens=int(meta['mnt']),
                                  eos_id=meta.get('eos'),
                                  priority=int(meta.get('prio', 0)),
                                  deadline_ms=None if ddl is None
                                  else float(ddl))
        with self._lock:
            self._streams[rid] = handle
        wire.write_msg(conn, wire.REPLY_OK, ack)

    def _on_poll(self, conn, meta, ack):
        out = {}
        for rid in meta.get('rids', ()):
            with self._lock:
                handle = self._streams.get(rid)
            if handle is None:
                out[rid] = {'state': UNKNOWN, 'tokens': []}
            else:
                out[rid] = self._srv.poll(handle)
        reply = dict(ack)
        reply['streams'] = out
        wire.write_msg(conn, wire.REPLY_OK, reply)

    def _health(self, with_digests):
        stats = self._srv.stats()
        out = {'queue_depth': stats['queue_depth'],
               'active': stats['active'],
               'workers': stats['workers'],
               'capacity': stats['workers'] * stats['slots_per_worker'],
               'max_len': self._srv.max_len,
               'param_version': stats.get('param_version'),
               'staleness_rounds': stats.get('staleness_rounds'),
               # paged-cache pressure: tokens held across live slots vs
               # total cache capacity — the router weighs this beyond
               # lane counts (a worker full of 4k streams is hotter
               # than one full of 16-token streams)
               'cache_tokens': stats.get('cache_tokens', 0),
               'cache_capacity': stats.get('cache_capacity'),
               # speculative replicas emit >1 token per step on
               # average: the router divides its load score by this so
               # a high-accept-rate replica looks proportionally roomier
               'effective_tokens_per_step':
                   stats.get('effective_tokens_per_step'),
               'spec_accept_rate':
                   stats.get('spec', {}).get('accept_rate'),
               # preempt-first capacity (serving/preempt.py): lifetime
               # preemptions plus streams currently swapped out and
               # waiting to resume — the router's dispatch score
               # treats waiting preempted streams as cache pressure
               'preemptions': stats.get('preemptions', 0),
               'preempted_streams': stats.get('preempted_streams', 0),
               'draining': self._draining}
        if with_digests:
            out['digests'] = self._srv.param_digests()
        return out


def _retryable(e):
    """queue-full / draining / a retryable refresh invite the router to
    come back; a bad prompt, a missing subscriber, or a spent deadline
    is stream-fatal — retrying a DeadlineExceededError elsewhere can
    only burn more of a budget that is already gone."""
    from ..online.subscriber import RefreshError
    from .engine import DeadlineExceededError
    if isinstance(e, RefreshError):
        return True
    if isinstance(e, DeadlineExceededError):
        return False
    return isinstance(e, RuntimeError) and not isinstance(e, ValueError)
