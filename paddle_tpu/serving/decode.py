"""DecodePredictor: cached prefill/decode execution over a slot pool.

Scope layout is the whole trick:

    base Predictor Scope (weights, device-resident, shared)
        └── this DecodePredictor's child Scope (K/V ring caches)

Weights are pinned to device ONCE in the parent scope at construction;
every clone() gets a fresh child scope (private cache state, zeroed)
over the same parent, so N serving workers share one copy of the
weights in HBM — the reference PaddlePredictor::Clone contract extended
to runtime state. Both programs are static-shape, so each compiles
exactly once through the executor's whole-block jit cache and the cache
buffers ride the executor's donation path (in-place update on device).
"""
from __future__ import annotations

import numpy as np

from ..executor import Executor, Scope
from ..flags import get_flag

__all__ = ['DecodePredictor']


class DecodePredictor(object):
    def __init__(self, predictor, slots=None, prefill_batch=None,
                 _clone_of=None, mesh=None):
        """predictor: a (loaded) Predictor/AnalysisPredictor whose
        program is a decoder-only LM; prefer
        AnalysisPredictor.prepare_decoding() over calling this
        directly. slots / prefill_batch default to FLAGS_serving_slots
        / FLAGS_serving_prefill_batch. mesh (None = read
        FLAGS_serve_mesh_shape; '' = single-chip) makes every program
        ONE GSPMD SPMD program over the mesh — K/V state shards on
        heads, weights per DecodeSpec.serve_param_specs, greedy decode
        stays bit-exact vs single-chip (serving/mesh.py)."""
        self._base = predictor
        if _clone_of is not None:
            self._pair = _clone_of._pair
            self._weight_scope = _clone_of._weight_scope
            self._mesh = _clone_of._mesh
            self._mesh_shape = _clone_of._mesh_shape
        else:
            from .mesh import serving_mesh
            slots = int(slots or get_flag('serving_slots'))
            prefill_batch = int(prefill_batch
                                or get_flag('serving_prefill_batch'))
            self._pair = self._transpile(predictor, slots, prefill_batch)
            self._weight_scope = predictor._scope
            self._mesh, self._mesh_shape = serving_mesh(mesh)
            self._pair.spec.mesh = self._mesh_shape
        self._exe = self._make_executor(predictor._place)
        if _clone_of is None:
            self._pin_weights()
        self._scope = Scope(parent=self._weight_scope)
        self.reset()

    def _transpile(self, predictor, slots, prefill_batch):
        from ..transpiler.decode_transpiler import DecodeTranspiler
        return DecodeTranspiler().transpile(
            predictor._program, slots=slots,
            prefill_batch=prefill_batch)

    def _make_executor(self, place):
        if self._mesh is None:
            return Executor(place)
        from .mesh import MeshDecodeExecutor
        return MeshDecodeExecutor(place, self._mesh,
                                  self._cache_shardings())

    def _cache_shardings(self):
        """{K/V state var name: NamedSharding} — heads axis over tp,
        adapted by fit_spec (heads % tp != 0 falls back to replicated,
        never errors). Shape dim 2 is H for both the dense ring and the
        page pool, so one spec covers both pair kinds."""
        if self._mesh is None:
            return {}
        from ..parallel.mesh import fit_spec, named_sharding
        pair = self._pair
        shape = (pair.pool_shape if pair.paged
                 else pair.spec.cache_shape(pair.slots))
        spec = fit_spec(pair.spec.cache_spec(), shape, self._mesh)
        sh = named_sharding(self._mesh, spec)
        return {n: sh for n in pair.cache_names}

    def _param_shardings(self):
        """{param name: NamedSharding} for the mesh: column-style specs
        from serve_param_specs, replicated for everything else."""
        from ..parallel.mesh import fit_spec, named_sharding
        serve = self._pair.spec.serve_param_specs()
        out = {}
        for name in self._pair.spec.param_names():
            spec = serve.get(name)
            if spec is not None:
                val = self._weight_scope.find_var(name)
                shape = getattr(val, 'shape', None)
                spec = fit_spec(spec, shape, self._mesh) \
                    if shape is not None else None
            out[name] = named_sharding(self._mesh, spec)
        return out

    # -- mesh introspection ------------------------------------------------
    @property
    def mesh_shape(self):
        """'tp=2'-style axis spec ('' = single-chip) — surfaced through
        ServingEngine.stats() and SRV_HEALTH."""
        return self._mesh_shape

    @property
    def mesh_devices(self):
        return int(self._mesh.devices.size) if self._mesh is not None \
            else 1

    # -- introspection -----------------------------------------------------
    @property
    def slots(self):
        return self._pair.slots

    @property
    def prefill_batch(self):
        return self._pair.prefill_batch

    @property
    def max_len(self):
        return self._pair.spec.max_len

    @property
    def vocab(self):
        return self._pair.spec.vocab

    def jit_cache_stats(self):
        return self._exe.jit_cache_stats()

    # -- lifecycle ---------------------------------------------------------
    def _pin_weights(self):
        """Pin every referenced parameter to device in the PARENT scope
        before any child scope exists — otherwise the executor's lazy
        pin would write per-worker device copies into each child,
        duplicating the model in HBM once per clone.

        On a mesh this also covers already-device-resident arrays (a
        predictor that ran before prepare_decoding leaves params
        committed to one chip): device_put reshards them onto their
        serve NamedSharding, so the executor's single-device lazy-pin
        path never fires for a mesh weight."""
        import jax
        block = self._pair.decode_program.global_block()
        shardings = self._param_shardings() if self._mesh is not None \
            else None
        for name in self._pair.spec.param_names():
            val = self._weight_scope.find_var(name)
            if val is None:
                raise RuntimeError(
                    'decode transpile references param %r that is not '
                    'in the predictor scope — was the model loaded with '
                    'load_params=True?' % name)
            if isinstance(val, np.ndarray) and \
                    val.dtype in (np.int64, np.uint64, np.float64):
                continue
            var = block.vars.get(name)
            if var is None or not var.persistable:
                continue
            if shardings is not None:
                self._weight_scope.set_var(
                    name, jax.device_put(val, shardings[name]))
            elif isinstance(val, np.ndarray):
                self._weight_scope.set_var(
                    name, jax.device_put(val, self._exe.device))

    def load_sharded(self, ckpt_dir, mesh=None):
        """Replace the weights from a sharded checkpoint root
        (checkpoint/sharded.py two-generation layout): each referenced
        param is assembled from the shard files of the last committed,
        digest-verified generation and resharded onto `mesh` (default:
        this predictor's serving mesh, else pinned whole to its
        device) — serving can roll to a checkpoint saved on ANY
        training topology; train-on-n/serve-on-m is a pure reshard. On
        a mesh the params land under their SERVE specs (column-style
        only; the checkpoint's recorded training spec is deliberately
        overridden — a row-sharded restore would break the bit-exact
        decode contract). Cache vars are runtime state, never
        checkpointed, never touched here. Raises if no generation is
        loadable or a referenced param is absent."""
        import jax
        from ..checkpoint import restore as restore_mod
        ckpt = restore_mod.load_checkpoint(ckpt_dir)
        if ckpt is None:
            raise RuntimeError(
                'no committed checkpoint generation under %r' % ckpt_dir)
        if mesh is None:
            mesh = self._mesh
        serve = self._pair.spec.serve_param_specs()
        cache_names = set(self._pair.cache_names)
        for name in self._pair.spec.param_names():
            if name in cache_names:
                continue
            if name not in ckpt:
                raise RuntimeError(
                    'sharded checkpoint %s (generation %d) is missing '
                    'param %r' % (ckpt.dirname, ckpt.generation, name))
            if mesh is not None:
                # spec=() (not None): None would fall back to the spec
                # RECORDED at save — the training layout, not the
                # bit-exact serve layout
                val = ckpt.as_jax(name, mesh,
                                  spec=serve.get(name, ()))
            else:
                val = jax.device_put(ckpt.read(name), self._exe.device)
            self._weight_scope.set_var(name, val)

    def param_names(self):
        """The refreshable weight names: every transpile-referenced
        param minus the runtime cache vars (which are per-worker state,
        never shipped by a parameter server)."""
        cache_names = set(self._pair.cache_names)
        return [n for n in self._pair.spec.param_names()
                if n not in cache_names]

    def param_digests(self):
        """{name: crc32 of the param's wire payload} over the served
        weights — the same digest a pserver stamps into its manifest,
        so a fleet deploy can prove a replica converged to a published
        version without shipping the bytes again."""
        from ..distributed import wire
        from ..integrity import crc32
        out = {}
        for name in self.param_names():
            val = np.asarray(self._weight_scope.find_var(name))
            out[name] = crc32(wire._payload_of(val)[1])
        return out

    def stage_weights(self, params):
        """Stage a {name: host array} weight update for install: names
        are validated against the decode programs' param set, shapes
        against the currently pinned values, and every array is
        device_put OFF the decode path — the expensive half of a
        refresh. Returns an opaque staged dict for install_weights.
        Raises (installing nothing) on an unknown name or a shape
        mismatch."""
        import jax
        known = set(self.param_names())
        shardings = self._param_shardings() if self._mesh is not None \
            else None
        staged = {}
        for name, val in params.items():
            if name not in known:
                raise KeyError(
                    'refresh carries unknown param %r (this predictor '
                    'serves %d params)' % (name, len(known)))
            arr = np.ascontiguousarray(val)
            cur = self._weight_scope.find_var(name)
            cur_shape = getattr(cur, 'shape', None)
            if cur_shape is not None and tuple(cur_shape) != arr.shape:
                raise ValueError(
                    'refresh shape mismatch for %r: got %r, serving %r'
                    % (name, arr.shape, tuple(cur_shape)))
            if shardings is not None:
                staged[name] = jax.device_put(arr, shardings[name])
            else:
                staged[name] = jax.device_put(arr, self._exe.device)
        return staged

    def install_weights(self, staged):
        """Swap staged device arrays into the PARENT weight scope — a
        few dict-pointer writes, cheap enough to run under the serving
        engine's step-boundary swap gate. Every clone sees the new
        weights on its next step (shared parent scope); in-flight steps
        already read the old arrays."""
        for name, val in staged.items():
            self._weight_scope.set_var(name, val)

    def reset(self):
        """Zero every ring cache (all slots forget everything). On a
        mesh the zeros are placed under the heads-sharded pin up front,
        so the first step compiles against the steady-state layout."""
        shape = self._pair.spec.cache_shape(self.slots)
        for name in self._pair.cache_names:
            self._scope.set_var(name, self._place_cache(
                name, np.zeros(shape, np.float32)))

    def _place_cache(self, name, value):
        """Host K/V state -> the executor's pinned device layout (the
        identity off-mesh: the executor lazy-pins on first run)."""
        if self._mesh is None:
            return value
        return self._exe.place_state(name, value)

    def clone(self):
        """A worker sharing this one's weights and compiled-program
        identity (same Program objects -> same jit cache keys) with a
        PRIVATE cache scope + executor — concurrent decode streams
        can't cross-talk."""
        return DecodePredictor(self._base, _clone_of=self)

    # -- execution ---------------------------------------------------------
    def _pad_prompts(self, prompts, slot_ids):
        pb, T = self.prefill_batch, self.max_len
        if not prompts or len(prompts) > pb:
            raise ValueError('prefill takes 1..%d prompts, got %d'
                             % (pb, len(prompts)))
        if len(prompts) != len(slot_ids):
            raise ValueError('%d prompts for %d slots'
                             % (len(prompts), len(slot_ids)))
        tokens = np.zeros((pb, T, 1), np.int64)
        pos = np.zeros((pb,), np.int32)
        slots = np.zeros((pb,), np.int32)
        for i, (p, s) in enumerate(zip(prompts, slot_ids)):
            p = np.asarray(p).reshape(-1)
            if not 1 <= p.size <= T:
                raise ValueError(
                    'prompt length %d outside [1, %d] (max_len)'
                    % (p.size, T))
            if not 0 <= int(s) < self.slots:
                raise ValueError('slot %r outside [0, %d)'
                                 % (s, self.slots))
            tokens[i, :p.size, 0] = p
            pos[i] = p.size - 1
            slots[i] = int(s)
        # a short batch re-writes the LAST real (prompt, slot) pair into
        # the same slot: identical values, so the duplicate scatter is
        # deterministic and no idle slot is touched
        for i in range(len(prompts), pb):
            tokens[i] = tokens[len(prompts) - 1]
            pos[i] = pos[len(prompts) - 1]
            slots[i] = slots[len(prompts) - 1]
        return tokens, pos, slots

    def prefill(self, prompts, slot_ids, return_logits=False):
        """Write the prompts' K/V into their slots and return the first
        greedy token per prompt: ids [len(prompts)] int64 (and, with
        return_logits, last-position logits [len(prompts), vocab])."""
        tokens, pos, slots = self._pad_prompts(prompts, slot_ids)
        logits, ids = self._exe.run(
            self._pair.prefill_program,
            feed={'prefill_tokens': tokens, 'prefill_pos': pos,
                  'prefill_slots': slots},
            fetch_list=self._pair.prefill_fetches,
            scope=self._scope, return_numpy=False)
        n = len(prompts)
        out_ids = np.asarray(ids)[:n]
        if return_logits:
            return out_ids, np.asarray(logits)[:n]
        return out_ids

    def decode_step(self, tokens, positions, return_logits=False):
        """One step for the WHOLE pool: tokens [slots] (last generated
        token per slot), positions [slots] (its absolute position; the
        ring write lands at position % max_len). Returns next greedy
        ids [slots] int64 (and logits [slots, vocab] if asked). Idle
        slots may carry any values — their rows are garbage by
        contract and rewritten at admission."""
        tokens = np.asarray(tokens, np.int64).reshape(self.slots, 1, 1)
        positions = np.asarray(positions, np.int32).reshape(self.slots)
        logits, ids = self._exe.run(
            self._pair.decode_program,
            feed={'decode_tokens': tokens,
                  'decode_step_idx': positions},
            fetch_list=self._pair.decode_fetches,
            scope=self._scope, return_numpy=False)
        if return_logits:
            return np.asarray(ids), np.asarray(logits)
        # ids only: the [slots, vocab] logits stay on device — at
        # production vocab sizes the per-step host transfer would
        # otherwise dominate the decode step itself
        return np.asarray(ids)

    def generate(self, prompt, max_new_tokens, eos_id=None, slot=0):
        """Solo greedy generation on one slot (the benchmark / parity
        path; real traffic goes through ServingEngine)."""
        ids = self.prefill([prompt], [slot])
        tok = int(ids[0])
        out = [tok]
        pos = len(np.asarray(prompt).reshape(-1))
        toks = np.zeros((self.slots,), np.int64)
        poss = np.zeros((self.slots,), np.int32)
        while len(out) < max_new_tokens and tok != eos_id:
            toks[slot] = tok
            poss[slot] = pos
            tok = int(self.decode_step(toks, poss)[slot])
            out.append(tok)
            pos += 1
        return out
