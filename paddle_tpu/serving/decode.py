"""DecodePredictor: cached prefill/decode execution over a slot pool.

Scope layout is the whole trick:

    base Predictor Scope (weights, device-resident, shared)
        └── this DecodePredictor's child Scope (K/V ring caches)

Weights are pinned to device ONCE in the parent scope at construction;
every clone() gets a fresh child scope (private cache state, zeroed)
over the same parent, so N serving workers share one copy of the
weights in HBM — the reference PaddlePredictor::Clone contract extended
to runtime state. Both programs are static-shape, so each compiles
exactly once through the executor's whole-block jit cache and the cache
buffers ride the executor's donation path (in-place update on device).
"""
from __future__ import annotations

import numpy as np

from ..executor import Executor, Scope
from ..flags import get_flag

__all__ = ['DecodePredictor']


class DecodePredictor(object):
    def __init__(self, predictor, slots=None, prefill_batch=None,
                 _clone_of=None):
        """predictor: a (loaded) Predictor/AnalysisPredictor whose
        program is a decoder-only LM; prefer
        AnalysisPredictor.prepare_decoding() over calling this
        directly. slots / prefill_batch default to FLAGS_serving_slots
        / FLAGS_serving_prefill_batch."""
        self._base = predictor
        if _clone_of is not None:
            self._pair = _clone_of._pair
            self._weight_scope = _clone_of._weight_scope
        else:
            from ..transpiler.decode_transpiler import DecodeTranspiler
            slots = int(slots or get_flag('serving_slots'))
            prefill_batch = int(prefill_batch
                                or get_flag('serving_prefill_batch'))
            self._pair = DecodeTranspiler().transpile(
                predictor._program, slots=slots,
                prefill_batch=prefill_batch)
            self._weight_scope = predictor._scope
        self._exe = Executor(predictor._place)
        if _clone_of is None:
            self._pin_weights()
        self._scope = Scope(parent=self._weight_scope)
        self.reset()

    # -- introspection -----------------------------------------------------
    @property
    def slots(self):
        return self._pair.slots

    @property
    def prefill_batch(self):
        return self._pair.prefill_batch

    @property
    def max_len(self):
        return self._pair.spec.max_len

    @property
    def vocab(self):
        return self._pair.spec.vocab

    def jit_cache_stats(self):
        return self._exe.jit_cache_stats()

    # -- lifecycle ---------------------------------------------------------
    def _pin_weights(self):
        """Pin every referenced parameter to device in the PARENT scope
        before any child scope exists — otherwise the executor's lazy
        pin would write per-worker device copies into each child,
        duplicating the model in HBM once per clone."""
        import jax
        block = self._pair.decode_program.global_block()
        for name in self._pair.spec.param_names():
            val = self._weight_scope.find_var(name)
            if val is None:
                raise RuntimeError(
                    'decode transpile references param %r that is not '
                    'in the predictor scope — was the model loaded with '
                    'load_params=True?' % name)
            if isinstance(val, np.ndarray) and \
                    val.dtype not in (np.int64, np.uint64, np.float64):
                var = block.vars.get(name)
                if var is not None and var.persistable:
                    self._weight_scope.set_var(
                        name, jax.device_put(val, self._exe.device))

    def load_sharded(self, ckpt_dir, mesh=None):
        """Replace the weights from a sharded checkpoint root
        (checkpoint/sharded.py two-generation layout): each referenced
        param is assembled from the shard files of the last committed,
        digest-verified generation and resharded onto `mesh` (default:
        pinned whole to this predictor's device) — serving can roll to
        a checkpoint saved on ANY training topology. Cache vars are
        runtime state, never checkpointed, never touched here. Raises
        if no generation is loadable or a referenced param is absent."""
        import jax
        from ..checkpoint import restore as restore_mod
        ckpt = restore_mod.load_checkpoint(ckpt_dir)
        if ckpt is None:
            raise RuntimeError(
                'no committed checkpoint generation under %r' % ckpt_dir)
        cache_names = set(self._pair.cache_names)
        for name in self._pair.spec.param_names():
            if name in cache_names:
                continue
            if name not in ckpt:
                raise RuntimeError(
                    'sharded checkpoint %s (generation %d) is missing '
                    'param %r' % (ckpt.dirname, ckpt.generation, name))
            if mesh is not None:
                val = ckpt.as_jax(name, mesh)
            else:
                val = jax.device_put(ckpt.read(name), self._exe.device)
            self._weight_scope.set_var(name, val)

    def param_names(self):
        """The refreshable weight names: every transpile-referenced
        param minus the runtime cache vars (which are per-worker state,
        never shipped by a parameter server)."""
        cache_names = set(self._pair.cache_names)
        return [n for n in self._pair.spec.param_names()
                if n not in cache_names]

    def param_digests(self):
        """{name: crc32 of the param's wire payload} over the served
        weights — the same digest a pserver stamps into its manifest,
        so a fleet deploy can prove a replica converged to a published
        version without shipping the bytes again."""
        from ..distributed import wire
        from ..integrity import crc32
        out = {}
        for name in self.param_names():
            val = np.asarray(self._weight_scope.find_var(name))
            out[name] = crc32(wire._payload_of(val)[1])
        return out

    def stage_weights(self, params):
        """Stage a {name: host array} weight update for install: names
        are validated against the decode programs' param set, shapes
        against the currently pinned values, and every array is
        device_put OFF the decode path — the expensive half of a
        refresh. Returns an opaque staged dict for install_weights.
        Raises (installing nothing) on an unknown name or a shape
        mismatch."""
        import jax
        known = set(self.param_names())
        staged = {}
        for name, val in params.items():
            if name not in known:
                raise KeyError(
                    'refresh carries unknown param %r (this predictor '
                    'serves %d params)' % (name, len(known)))
            arr = np.ascontiguousarray(val)
            cur = self._weight_scope.find_var(name)
            cur_shape = getattr(cur, 'shape', None)
            if cur_shape is not None and tuple(cur_shape) != arr.shape:
                raise ValueError(
                    'refresh shape mismatch for %r: got %r, serving %r'
                    % (name, arr.shape, tuple(cur_shape)))
            staged[name] = jax.device_put(arr, self._exe.device)
        return staged

    def install_weights(self, staged):
        """Swap staged device arrays into the PARENT weight scope — a
        few dict-pointer writes, cheap enough to run under the serving
        engine's step-boundary swap gate. Every clone sees the new
        weights on its next step (shared parent scope); in-flight steps
        already read the old arrays."""
        for name, val in staged.items():
            self._weight_scope.set_var(name, val)

    def reset(self):
        """Zero every ring cache (all slots forget everything)."""
        shape = self._pair.spec.cache_shape(self.slots)
        for name in self._pair.cache_names:
            self._scope.set_var(name, np.zeros(shape, np.float32))

    def clone(self):
        """A worker sharing this one's weights and compiled-program
        identity (same Program objects -> same jit cache keys) with a
        PRIVATE cache scope + executor — concurrent decode streams
        can't cross-talk."""
        return DecodePredictor(self._base, _clone_of=self)

    # -- execution ---------------------------------------------------------
    def _pad_prompts(self, prompts, slot_ids):
        pb, T = self.prefill_batch, self.max_len
        if not prompts or len(prompts) > pb:
            raise ValueError('prefill takes 1..%d prompts, got %d'
                             % (pb, len(prompts)))
        if len(prompts) != len(slot_ids):
            raise ValueError('%d prompts for %d slots'
                             % (len(prompts), len(slot_ids)))
        tokens = np.zeros((pb, T, 1), np.int64)
        pos = np.zeros((pb,), np.int32)
        slots = np.zeros((pb,), np.int32)
        for i, (p, s) in enumerate(zip(prompts, slot_ids)):
            p = np.asarray(p).reshape(-1)
            if not 1 <= p.size <= T:
                raise ValueError(
                    'prompt length %d outside [1, %d] (max_len)'
                    % (p.size, T))
            if not 0 <= int(s) < self.slots:
                raise ValueError('slot %r outside [0, %d)'
                                 % (s, self.slots))
            tokens[i, :p.size, 0] = p
            pos[i] = p.size - 1
            slots[i] = int(s)
        # a short batch re-writes the LAST real (prompt, slot) pair into
        # the same slot: identical values, so the duplicate scatter is
        # deterministic and no idle slot is touched
        for i in range(len(prompts), pb):
            tokens[i] = tokens[len(prompts) - 1]
            pos[i] = pos[len(prompts) - 1]
            slots[i] = slots[len(prompts) - 1]
        return tokens, pos, slots

    def prefill(self, prompts, slot_ids, return_logits=False):
        """Write the prompts' K/V into their slots and return the first
        greedy token per prompt: ids [len(prompts)] int64 (and, with
        return_logits, last-position logits [len(prompts), vocab])."""
        tokens, pos, slots = self._pad_prompts(prompts, slot_ids)
        logits, ids = self._exe.run(
            self._pair.prefill_program,
            feed={'prefill_tokens': tokens, 'prefill_pos': pos,
                  'prefill_slots': slots},
            fetch_list=self._pair.prefill_fetches,
            scope=self._scope, return_numpy=False)
        n = len(prompts)
        out_ids = np.asarray(ids)[:n]
        if return_logits:
            return out_ids, np.asarray(logits)[:n]
        return out_ids

    def decode_step(self, tokens, positions, return_logits=False):
        """One step for the WHOLE pool: tokens [slots] (last generated
        token per slot), positions [slots] (its absolute position; the
        ring write lands at position % max_len). Returns next greedy
        ids [slots] int64 (and logits [slots, vocab] if asked). Idle
        slots may carry any values — their rows are garbage by
        contract and rewritten at admission."""
        tokens = np.asarray(tokens, np.int64).reshape(self.slots, 1, 1)
        positions = np.asarray(positions, np.int32).reshape(self.slots)
        logits, ids = self._exe.run(
            self._pair.decode_program,
            feed={'decode_tokens': tokens,
                  'decode_step_idx': positions},
            fetch_list=self._pair.decode_fetches,
            scope=self._scope, return_numpy=False)
        if return_logits:
            return np.asarray(ids), np.asarray(logits)
        # ids only: the [slots, vocab] logits stay on device — at
        # production vocab sizes the per-step host transfer would
        # otherwise dominate the decode step itself
        return np.asarray(ids)

    def generate(self, prompt, max_new_tokens, eos_id=None, slot=0):
        """Solo greedy generation on one slot (the benchmark / parity
        path; real traffic goes through ServingEngine)."""
        ids = self.prefill([prompt], [slot])
        tok = int(ids[0])
        out = [tok]
        pos = len(np.asarray(prompt).reshape(-1))
        toks = np.zeros((self.slots,), np.int64)
        poss = np.zeros((self.slots,), np.int32)
        while len(out) < max_new_tokens and tok != eos_id:
            toks[slot] = tok
            poss[slot] = pos
            tok = int(self.decode_step(toks, poss)[slot])
            out.append(tok)
            pos += 1
        return out
