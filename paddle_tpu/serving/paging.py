"""Host-side paged KV-cache bookkeeping: PagePool / PageTable /
PrefixCache.

The device side (ops/attention_ops.py kv_page_* ops, the paged program
pair from models/transformer.py) is pure address arithmetic over feed
values; everything stateful lives HERE, on the host, in plain Python:

  PagePool     free list + per-page refcounts over the physical pool.
               Physical page 0 is the reserved null page (never
               allocated, the redirect target for dead writes). An
               empty free list first asks the eviction callback (the
               PrefixCache LRU) to give a page back, then raises the
               typed, retryable CacheExhaustedError — the paged answer
               to COVERAGE divergence 8's silent ring slide.
               save_pages/restore_pages move page contents device<->
               host for the preempt-first capacity engine
               (serving/preempt.py): float32 copies onto freshly
               allocated pages, so a swapped-out stream resumes
               bit-exact.
  PageTable    one stream's logical -> physical mapping. Pages adopted
               from the prefix cache are marked SHARED; the first
               append into a shared page forks it (copy-on-write): a
               fresh page is allocated, a (src, dst) copy instruction
               is returned for the device program, and the shared ref
               is dropped. Because the device copy reads all sources
               before writing any destination, a page freed and
               reallocated within the same step still copies its
               pre-step contents.
  PrefixCache  content-hash chain over FULL pages (h_k = sha1(h_{k-1}
               || tokens of page k) -> physical page) plus
               partial-tail entries keyed by (chain hash, tail tokens)
               — RadixAttention-style sharing restricted to page
               granularity. The cache holds its own +1 ref on every
               registered page so shared prefixes survive stream
               churn; entries are evicted leaf-first by LRU when the
               pool runs dry.

Sharing is capped at prompt[:-1]: the last prompt token is always
recomputed, because its logits produce the stream's first output
token. Everything here is deterministic — no clocks, no randomness —
so greedy decode over shared pages stays bit-exact with the dense and
full-recompute paths.
"""
from __future__ import annotations

import collections
import hashlib

import numpy as np

__all__ = ['CacheExhaustedError', 'PagePool', 'PageTable', 'PrefixCache',
           'chain_keys']

NULL_PAGE = 0


class CacheExhaustedError(RuntimeError):
    """The page pool is empty (after prefix-cache eviction): the stream
    cannot grow. Retryable — a shed, not a model error: the serving
    engine requeues the victim and the fleet router retries it on a
    less-loaded replica (replica.py already marks RuntimeError
    subclasses retryable on the wire)."""

    retryable = True

    def __init__(self, msg, slots=()):
        super(CacheExhaustedError, self).__init__(msg)
        self.slots = tuple(slots)


class PagePool(object):
    """Refcounted free-list allocator over `num_pages` physical pages.

    Page 0 is pinned as the null page and never handed out. `evict` is
    an optional zero-arg callable returning True if it released at
    least one page (the PrefixCache's LRU drop) — alloc() keeps asking
    it until a page frees or it gives up."""

    def __init__(self, num_pages, page_tokens, evict=None):
        num_pages = int(num_pages)
        if num_pages < 2:
            raise ValueError('page pool needs >= 2 pages (one is the '
                             'reserved null page), got %d' % num_pages)
        self.num_pages = num_pages
        self.page_tokens = int(page_tokens)
        self._free = collections.deque(range(1, num_pages))
        self._ref = [0] * num_pages
        self._ref[NULL_PAGE] = 1            # pinned forever
        self._evict = evict

    def set_evict(self, evict):
        self._evict = evict

    # -- accounting --------------------------------------------------------
    @property
    def pages_free(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.num_pages - 1 - len(self._free)

    def refcount(self, page):
        return self._ref[page]

    def check(self):
        """Invariant sweep (the property test's oracle): the free list
        and the ref>0 set partition pages 1..N-1 exactly."""
        free = set(self._free)
        assert len(free) == len(self._free), 'free list holds duplicates'
        assert NULL_PAGE not in free, 'null page leaked into free list'
        assert self._ref[NULL_PAGE] >= 1, 'null page pin lost'
        for p in range(1, self.num_pages):
            assert self._ref[p] >= 0, 'negative refcount on page %d' % p
            assert (self._ref[p] == 0) == (p in free), \
                'page %d: ref %d but free=%s' % (p, self._ref[p], p in free)

    # -- alloc / ref -------------------------------------------------------
    def alloc(self):
        while not self._free:
            if self._evict is None or not self._evict():
                raise CacheExhaustedError(
                    'KV page pool exhausted: %d pages all referenced '
                    '(and no prefix-cache entry left to evict)'
                    % (self.num_pages - 1))
        page = self._free.popleft()
        self._ref[page] = 1
        return page

    def alloc_many(self, n):
        """All-or-nothing batch alloc: returns n pages or raises with
        none taken (so a failed admission never strands pages)."""
        out = []
        try:
            for _ in range(int(n)):
                out.append(self.alloc())
        except CacheExhaustedError:
            for p in out:
                self.unref(p)
            raise
        return out

    def share(self, page):
        if page == NULL_PAGE or self._ref[page] <= 0:
            raise ValueError('cannot share dead page %d' % page)
        self._ref[page] += 1
        return page

    def unref(self, page):
        if page == NULL_PAGE:
            raise ValueError('cannot unref the null page')
        if self._ref[page] <= 0:
            raise ValueError('double free of page %d' % page)
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)

    # -- host swap (preempt-first capacity, serving/preempt.py) ------------
    def save_pages(self, pools, page_ids):
        """Device -> host: gather the `page_ids` rows of every pool
        array into float32 host copies (one np.ndarray per pool, shape
        [len(page_ids), page_tokens, ...]). A pure read — refcounts and
        the free list are untouched; the caller releases the stream's
        refs AFTER the copy so a failed gather never strands a page.
        Float32 bytes copy exactly, so a later restore_pages is
        bit-identical."""
        idx = [int(p) for p in page_ids]
        for p in idx:
            if p == NULL_PAGE or not 0 < p < self.num_pages \
                    or self._ref[p] <= 0:
                raise ValueError('cannot save dead/null page %d' % p)
        idx = np.asarray(idx, np.int32)
        return [np.asarray(pool[idx]) for pool in pools]

    def restore_pages(self, pools, saved):
        """Host -> device: allocate len(saved[0]) FRESH pages
        (all-or-nothing — raises the retryable CacheExhaustedError
        with nothing taken when the pool cannot fit, so a resuming
        stream just stays queued) and write each saved row back at the
        new physical ids. Returns (page_ids, pools); device-resident
        pools are functionally updated (`.at[ids].set`), so the caller
        must reinstall the returned arrays in its scope."""
        n = len(saved[0]) if saved else 0
        ids = self.alloc_many(n)
        idx = np.asarray(ids, np.int32)
        out = []
        for pool, host in zip(pools, saved):
            if hasattr(pool, 'at'):            # jax array: functional
                pool = pool.at[idx].set(host)
            else:                              # numpy: in-place
                pool[idx] = host
            out.append(pool)
        return ids, out


class PageTable(object):
    """One stream's page index: logical position j lives at
    pages[j // page_tokens] offset j % page_tokens. `shared` marks
    table indices whose page is referenced elsewhere (prefix cache or
    another stream) and therefore read-only for this stream."""

    def __init__(self, pool, width):
        self.pool = pool
        self.width = int(width)             # table entries (P)
        self.pages = []                     # physical page ids
        self.length = 0                     # tokens written so far
        self.shared = set()                 # read-only table indices

    @property
    def capacity(self):
        return self.width * self.pool.page_tokens

    def adopt_shared(self, pages, tokens):
        """Seed a fresh table with prefix-cache pages (the cache's own
        refs are untouched; this stream takes one more each)."""
        assert not self.pages and not self.length
        if tokens > len(pages) * self.pool.page_tokens:
            raise ValueError('shared prefix %d tokens > %d pages'
                             % (tokens, len(pages)))
        for p in pages:
            self.pool.share(p)
            self.shared.add(len(self.pages))
            self.pages.append(p)
        self.length = int(tokens)

    def mark_shared(self, index):
        self.shared.add(int(index))

    def ensure(self, tokens):
        """Grow the table so positions [0, tokens) are addressable.
        All-or-nothing; raises CacheExhaustedError past `width` pages
        or an empty pool. Idempotent for already-covered extents."""
        tokens = int(tokens)
        need = -(-tokens // self.pool.page_tokens)      # ceil
        if need > self.width:
            raise CacheExhaustedError(
                'stream needs %d pages, table width is %d (%d-token '
                'window)' % (need, self.width, self.capacity))
        if need > len(self.pages):
            self.pages.extend(self.pool.alloc_many(need - len(self.pages)))

    def cow_for_append(self, position):
        """Make the page holding `position` writable. Returns a
        (src, dst) physical copy pair for the device program when the
        page was shared and had to fork, else None. This stream's ref
        on src is deliberately NOT dropped here: the caller unrefs it
        only AFTER the device copy actually ran, so a step that fails
        after this fork can roll back (restore src, unref dst) without
        ever touching a freed page."""
        idx = int(position) // self.pool.page_tokens
        if idx >= len(self.pages) or idx not in self.shared:
            return None
        dst = self.pool.alloc()
        src = self.pages[idx]
        self.pages[idx] = dst
        self.shared.discard(idx)
        return (src, dst)

    def row(self, out):
        """Fill `out` (a length-width int32 view) with the physical
        page ids, null-padded."""
        out[:] = NULL_PAGE
        out[:len(self.pages)] = self.pages
        return out

    def release(self):
        for p in self.pages:
            self.pool.unref(p)
        self.pages = []
        self.shared = set()
        self.length = 0


def _digest(prev, tokens):
    h = hashlib.sha1(prev)
    h.update(b','.join(b'%d' % int(t) for t in tokens))
    return h.digest()


def chain_keys(tokens, page_tokens, limit=None):
    """Hex hash-chain keys over the FULL pages of tokens[:limit] — the
    content address every disagg page ship and fleet prefix-directory
    entry is keyed by. A pure function of the tokens and the page size,
    so a receiver can recompute the chain and refuse a shipment whose
    keys do not match its own hash of the prompt."""
    pt = int(page_tokens)
    toks = [int(t) for t in tokens]
    limit = len(toks) if limit is None else min(int(limit), len(toks))
    out, chain = [], b''
    for k in range(limit // pt):
        chain = _digest(chain, toks[k * pt:(k + 1) * pt])
        out.append(chain.hex())
    return out


class _Node(object):
    __slots__ = ('page', 'parent', 'children', 'tails', 'stamp')

    def __init__(self, page, parent):
        self.page = page
        self.parent = parent     # chain digest of the previous node
        self.children = 0
        self.tails = 0
        self.stamp = 0


class _Tail(object):
    __slots__ = ('page', 'tokens', 'chain', 'stamp')

    def __init__(self, page, tokens, chain):
        self.page = page
        self.tokens = tokens
        self.chain = chain
        self.stamp = 0


class PrefixCache(object):
    """Content-hash page index for shared prefixes.

    Full pages form a hash CHAIN (a radix tree collapsed to page
    granularity): node k is keyed by sha1 over all tokens of pages
    0..k and maps to the physical page holding page k's K/V. A prompt
    matches greedily along the chain; an optional partial TAIL entry
    (chain digest + the tail's exact tokens) shares the last,
    partially filled page — the matcher picks the longest registered
    tail that prefixes the prompt remainder. The cache owns one ref
    per registered page; evict_one() drops the least-recently-used
    LEAF (no children, no tails) so interior chain pages are never
    orphaned while still reachable."""

    def __init__(self, pool):
        self.pool = pool
        self._nodes = {}          # chain digest -> _Node
        self._tails = {}          # chain digest -> {tokens: _Tail}
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        # delta logs for the fleet prefix directory (drained through
        # SRV_HEALTH): hex chain keys of full-page nodes registered /
        # evicted since the last drain_events(). Bounded by cache
        # churn between probes — tails are never logged (the directory
        # tracks full pages only).
        self._announced = []
        self._evicted = []

    def _touch(self, entry):
        self._clock += 1
        entry.stamp = self._clock

    # -- lookup ------------------------------------------------------------
    def match(self, prompt, limit=None):
        """Longest shared prefix of `prompt` (at most `limit` tokens;
        callers pass len(prompt) - 1 so the last token is always
        computed). Returns (pages, tokens): the physical pages to adopt
        (the last may be partial) and how many tokens they carry. The
        caller must adopt_shared() them promptly — match() itself takes
        no refs."""
        pt = self.pool.page_tokens
        limit = len(prompt) if limit is None else min(limit, len(prompt))
        full = limit // pt
        pages, chain, k = [], b'', 0
        while k < full:
            nxt = _digest(chain, prompt[k * pt:(k + 1) * pt])
            node = self._nodes.get(nxt)
            if node is None:
                break
            self._touch(node)
            pages.append(node.page)
            chain = nxt
            k += 1
        tokens = k * pt
        if k == full:             # a tail only connects at chain end
            rest = tuple(int(t) for t in prompt[tokens:limit])
            best = None
            for tail_tokens, tail in self._tails.get(chain, {}).items():
                if rest[:len(tail_tokens)] == tail_tokens and \
                        (best is None or len(tail_tokens) > len(best.tokens)):
                    best = tail
            if best is not None:
                self._touch(best)
                pages.append(best.page)
                tokens += len(best.tokens)
        if tokens:
            self.hits += 1
            self.tokens_reused += tokens
        elif limit > 0:
            # a shareable prompt found nothing — the miss half of the
            # fleet_prefix_hit_rate metric (a 1-token prompt, limit 0,
            # can never share and counts as neither)
            self.misses += 1
        return pages, tokens

    def chain(self, prompt, limit=None):
        """Walk the FULL-page hash chain registered for prompt[:limit]
        (no hit/LRU accounting — a pure read for the disagg shipper
        and directory). Returns (digests, pages): the longest resident
        leading run. Because eviction is leaf-first, the resident part
        of a chain is always a prefix of it."""
        pt = self.pool.page_tokens
        toks = [int(t) for t in prompt]
        limit = len(toks) if limit is None else min(int(limit), len(toks))
        digests, pages, chain = [], [], b''
        for k in range(limit // pt):
            nxt = _digest(chain, toks[k * pt:(k + 1) * pt])
            node = self._nodes.get(nxt)
            if node is None:
                break
            digests.append(nxt)
            pages.append(node.page)
            chain = nxt
        return digests, pages

    def extend_chain(self, parent, digests, pages):
        """Graft externally prefilled full pages onto the chain at
        `parent` (b'' = the root): digests[i] hangs off digests[i-1].
        Each page arrives with the caller's fresh-alloc ref, which
        BECOMES the cache's ref (no extra share). A digest already
        present — a racing install — keeps the resident page and the
        duplicate ref is returned to the pool. The disagg install path
        (serving/disagg.py): shipped bytes were computed by the same
        deterministic prefill on the sender, so the content address
        guarantees byte-identical pages."""
        chain = parent
        for d, p in zip(digests, pages):
            node = self._nodes.get(d)
            if node is not None:
                self.pool.unref(p)
                self._touch(node)
                chain = d
                continue
            node = _Node(p, chain)
            self._nodes[d] = node
            par = self._nodes.get(chain)
            if par is not None:
                par.children += 1
            self._touch(node)
            self._announced.append(d.hex())
            chain = d

    # -- registration ------------------------------------------------------
    def register(self, prompt, table):
        """Index a freshly prefilled prompt's pages for future sharing.
        Takes one cache ref per newly registered page and returns the
        TABLE indices that are now shared (the caller marks them so the
        stream's own appends fork instead of scribbling on cached
        pages)."""
        pt = self.pool.page_tokens
        full = len(prompt) // pt
        chain = b''
        newly_shared = []
        for k in range(min(full, len(table.pages))):
            nxt = _digest(chain, prompt[k * pt:(k + 1) * pt])
            node = self._nodes.get(nxt)
            if node is None:
                node = _Node(self.pool.share(table.pages[k]), chain)
                self._nodes[nxt] = node
                parent = self._nodes.get(chain)
                if parent is not None:
                    parent.children += 1
                newly_shared.append(k)
                self._announced.append(nxt.hex())
            elif node.page == table.pages[k]:
                newly_shared.append(k)       # already cache-shared
            self._touch(node)
            chain = nxt
        rest = tuple(int(t) for t in prompt[full * pt:])
        if rest and full < len(table.pages):
            tails = self._tails.setdefault(chain, {})
            if rest not in tails:
                tail = _Tail(self.pool.share(table.pages[full]),
                             rest, chain)
                tails[rest] = tail
                node = self._nodes.get(chain)
                if node is not None:
                    node.tails += 1
                newly_shared.append(full)
            elif tails[rest].page == table.pages[full]:
                newly_shared.append(full)
            self._touch(tails[rest])
        for idx in newly_shared:
            table.mark_shared(idx)
        return newly_shared

    # -- eviction ----------------------------------------------------------
    def _leaves(self):
        for digest, node in self._nodes.items():
            if not node.children and not node.tails:
                yield node.stamp, ('node', digest, node)
        for chain, tails in self._tails.items():
            for tokens, tail in tails.items():
                yield tail.stamp, ('tail', (chain, tokens), tail)

    def evict_one(self):
        """Drop the LRU leaf entry and unref its page; True if a page
        ref was released (it only FREES the page if no live stream
        still shares it — alloc() loops until one actually frees)."""
        best = min(self._leaves(), default=None, key=lambda e: e[0])
        if best is None:
            return False
        _, (kind, key, entry) = best
        if kind == 'node':
            del self._nodes[key]
            parent = self._nodes.get(entry.parent)
            if parent is not None:
                parent.children -= 1
            self._evicted.append(key.hex())
        else:
            chain, tokens = key
            del self._tails[chain][tokens]
            if not self._tails[chain]:
                del self._tails[chain]
            node = self._nodes.get(chain)
            if node is not None:
                node.tails -= 1
        self.pool.unref(entry.page)
        return True

    def drain_events(self):
        """Take (and clear) the registered/evicted delta since the last
        drain — the replica's SRV_HEALTH reply carries these so the
        router's prefix directory follows replica truth instead of
        guessing from dispatch history."""
        new, gone = self._announced, self._evicted
        self._announced, self._evicted = [], []
        return {'new': new, 'evicted': gone}

    @property
    def resident_pages(self):
        """Pages the cache itself holds a ref on (nodes + tails)."""
        return len(self)

    def __len__(self):
        return len(self._nodes) + sum(len(t) for t in self._tails.values())
