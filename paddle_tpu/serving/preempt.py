"""Preempt-first capacity: SLO-tiered preemption policy + host swap.

The vLLM-style answer to page-pool exhaustion (ROADMAP item 3): when
HBM pages run dry mid-decode or mid-prefill, the engine no longer
sheds the victim stream — it PREEMPTS the lowest-tier longest-idle
stream instead, so overload costs latency for low-tier work, never
availability for anyone. Two resume paths, both bit-exact vs an
unpreempted run:

  swap       PagePool.save_pages copies the victim's pages to host RAM
             (float32 bytes copy exactly); on resume, restore_pages
             writes them back onto freshly allocated pages and the
             stream continues from its exact position. Host memory is
             bounded by FLAGS_serving_swap_host_mb (HostSwapBudget) —
             past the budget the preemption degrades to re-prefill.
  reprefill  pages are simply dropped; greedy determinism means the
             stream is fully described by (prompt + tokens so far), so
             re-admission re-prefills that sequence and the final
             chunk's output token IS the next stream token — the same
             contract PR 11's fleet failover already proves bit-exact
             (and the PR 12 prefix cache makes nearly free).

This module holds the policy pieces the engine composes: victim
selection, the host-RAM budget, and the serving.* preemption
telemetry. The mechanics live where the state lives —
PagePool.save_pages/restore_pages in paging.py,
save_stream/restore_stream on the paged predictors, and the
tier-queue scheduling in engine.py.

Telemetry: serving.preemptions / serving.swapped_pages /
serving.swap_bytes counters, serving.resume_latency histogram
(preempt -> back in a slot, seconds), serving.preempted_streams gauge
(currently swapped/dropped streams waiting to resume).
"""
from __future__ import annotations

import threading

from ..flags import get_flag
from ..obs import telemetry

__all__ = ['HostSwapBudget', 'pick_victim', 'preempt_policy']

preemptions = telemetry.counter('serving.preemptions')
swapped_pages = telemetry.counter('serving.swapped_pages')
swap_bytes = telemetry.counter('serving.swap_bytes')
resume_latency = telemetry.histogram('serving.resume_latency')
preempted_streams = telemetry.gauge('serving.preempted_streams')


def preempt_policy():
    """The validated FLAGS_serving_preempt_policy value."""
    policy = str(get_flag('serving_preempt_policy') or 'swap').lower()
    if policy not in ('swap', 'reprefill', 'off'):
        raise ValueError("FLAGS_serving_preempt_policy must be 'swap', "
                         "'reprefill' or 'off', got %r" % policy)
    return policy


def pick_victim(lanes, below=None):
    """The slot to preempt: lowest tier first, longest idle (oldest
    last-token activity) within a tier. Only READY lanes qualify — a
    mid-prefill lane has its own requeue path and nothing worth
    swapping. `below` restricts candidates to tiers strictly under it
    (a prefilling stream only preempts strictly lower-tier work, so
    equal-tier streams never thrash each other). Returns None when no
    lane qualifies."""
    cands = [(lane.req.priority, lane.last_active, slot)
             for slot, lane in lanes.items()
             if lane.ready and (below is None or lane.req.priority < below)]
    if not cands:
        return None
    return min(cands)[2]


class HostSwapBudget(object):
    """FLAGS_serving_swap_host_mb accounting, shared by every worker of
    one engine (host RAM is a process resource, unlike the per-worker
    page pools). reserve() is all-or-nothing: a swap that does not fit
    degrades to the re-prefill path instead of growing host memory
    unboundedly."""

    def __init__(self, limit_mb=None):
        limit_mb = (get_flag('serving_swap_host_mb')
                    if limit_mb is None else limit_mb)
        self.limit_bytes = int(float(limit_mb) * (1 << 20))
        self._used = 0
        self._mu = threading.Lock()

    @property
    def used_bytes(self):
        return self._used

    def reserve(self, nbytes):
        """Take `nbytes` of budget; False (nothing taken) when it does
        not fit."""
        nbytes = int(nbytes)
        with self._mu:
            if self._used + nbytes > self.limit_bytes:
                return False
            self._used += nbytes
            return True

    def release(self, nbytes):
        with self._mu:
            self._used = max(0, self._used - int(nbytes))
