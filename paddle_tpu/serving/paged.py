"""PagedDecodePredictor: page-table cached decoding over a shared pool.

The DecodePredictor contract (prefill / decode_step / generate /
clone, weights pinned once in the parent Scope) re-based onto the
paged cache: per-layer [num_pages, page_tokens, H, dk] pools live in
this predictor's child Scope, a host-side PagePool/PrefixCache
(serving/paging.py) decides which physical page every logical position
maps to, and both compiled programs take the page index as a FEED —
admission, copy-on-write and prefix sharing never recompile anything.

Streams replace the dense path's whole-row prefill:

    open_stream(slot, prompt)   match the prefix cache, adopt shared
                                pages read-only (zero recompute),
                                allocate nothing yet
    prefill_step(slot)          run ONE prefill_chunk-token chunk;
                                returns the first greedy token once the
                                prompt is complete (None before that)
    decode_step(tokens, pos)    one compiled step over ALL slots; pages
                                are allocated on demand per live stream
    release(slot)               drop the stream's page refs
    save_stream(slot)           copy the stream's pages to host RAM
                                (preempt-first capacity); paired with
    restore_stream(slot, snap)  write them back onto fresh pages and
                                resume bit-exact

Exhaustion is typed: when the pool runs dry (after prefix-cache LRU
eviction) prefill_step/decode_step raise CacheExhaustedError — the
dense ring's silent slide past max_len (COVERAGE divergence 8) cannot
happen here. decode_step is transactional: on exhaustion every page
allocated for THAT call is rolled back, so retrying the same feed
after a release is deterministic and bit-exact.

Telemetry: serving.kv_pages_in_use / serving.kv_pages_free gauges,
serving.prefix_hits / serving.prefix_tokens_reused counters,
serving.prefill_chunks histogram (chunks per admitted prompt).
"""
from __future__ import annotations

import numpy as np

from ..executor import Scope
from ..flags import get_flag
from ..obs import telemetry
from .decode import DecodePredictor
from .paging import (CacheExhaustedError, PagePool, PageTable, PrefixCache,
                     chain_keys)

__all__ = ['PagedDecodePredictor']

_pages_in_use = telemetry.gauge('serving.kv_pages_in_use')
_pages_free = telemetry.gauge('serving.kv_pages_free')
_prefix_hits = telemetry.counter('serving.prefix_hits')
_prefix_tokens = telemetry.counter('serving.prefix_tokens_reused')
_prefill_chunks = telemetry.histogram('serving.prefill_chunks')


class _PendingPrefill(object):
    __slots__ = ('prompt', 'chunks')

    def __init__(self, prompt):
        self.prompt = prompt
        self.chunks = 0


class PagedDecodePredictor(DecodePredictor):
    """Drop-in replacement for DecodePredictor with a paged cache.
    prefer AnalysisPredictor.prepare_decoding(paged=True) over calling
    this directly."""

    paged = True

    def __init__(self, predictor, slots=None, page_tokens=None,
                 kv_pages=None, prefill_chunk=None, _clone_of=None,
                 pair=None, mesh=None):
        """With `pair` (an already-transpiled PagedDecodePair) the
        transpile is skipped — the speculative path builds its target
        and draft pairs in one transpile_spec and hands them here.
        mesh follows the DecodePredictor contract (None = read
        FLAGS_serve_mesh_shape; '' = single-chip): the page pool shards
        its heads axis over tp and every program runs as ONE SPMD
        program over the mesh (serving/mesh.py)."""
        self._base = predictor
        if _clone_of is not None:
            self._pair = _clone_of._pair
            self._weight_scope = _clone_of._weight_scope
            self._mesh = _clone_of._mesh
            self._mesh_shape = _clone_of._mesh_shape
        else:
            from .mesh import serving_mesh
            if pair is not None:
                self._pair = pair
            else:
                from ..transpiler.decode_transpiler import DecodeTranspiler
                slots = int(slots or get_flag('serving_slots'))
                self._pair = DecodeTranspiler().transpile(
                    predictor._program, slots=slots, paged=True,
                    page_tokens=page_tokens, kv_pages=kv_pages,
                    prefill_chunk=prefill_chunk)
            self._weight_scope = predictor._scope
            self._mesh, self._mesh_shape = serving_mesh(mesh)
            self._pair.spec.mesh = self._mesh_shape
        self._exe = self._make_executor(predictor._place)
        if _clone_of is None:
            self._pin_weights()
        self._scope = Scope(parent=self._weight_scope)
        self.reset()

    # -- introspection -----------------------------------------------------
    @property
    def page_tokens(self):
        return self._pair.page_tokens

    @property
    def num_pages(self):
        return self._pair.num_pages

    @property
    def pages_per_slot(self):
        return self._pair.pages_per_slot

    @property
    def prefill_chunk(self):
        return self._pair.prefill_chunk

    @property
    def window(self):
        """Max tokens (prompt + generated) one stream can hold."""
        return self.pages_per_slot * self.page_tokens

    def slot_tokens(self):
        """{slot: tokens held} for every open stream — the per-slot
        cache pressure LMServer.stats() exposes to the fleet router."""
        return {slot: t.length for slot, t in self._tables.items()}

    def pool_stats(self):
        return {'page_tokens': self.page_tokens,
                'num_pages': self.num_pages,
                'pages_in_use': self._pool.pages_in_use,
                'pages_free': self._pool.pages_free,
                'prefix_entries': len(self._prefix),
                'prefix_hits': self._prefix.hits,
                'prefix_misses': self._prefix.misses,
                'prefix_pages': self._prefix.resident_pages,
                'prefix_tokens_reused': self._prefix.tokens_reused}

    def _update_gauges(self):
        _pages_in_use.set(self._pool.pages_in_use)
        _pages_free.set(self._pool.pages_free)

    # -- lifecycle ---------------------------------------------------------
    def reset(self):
        """Zero the page pools and forget every stream and cached
        prefix (fresh allocator state). On a mesh the zeroed pools land
        under the heads-sharded pin up front (steady-state layout from
        step one)."""
        shape = self._pair.pool_shape
        for name in self._pair.cache_names:
            self._scope.set_var(name, self._place_cache(
                name, np.zeros(shape, np.float32)))
        self._pool = PagePool(self.num_pages, self.page_tokens)
        self._prefix = PrefixCache(self._pool)
        self._pool.set_evict(self._prefix.evict_one)
        self._tables = {}             # slot -> PageTable
        self._pending = {}            # slot -> _PendingPrefill
        self._update_gauges()

    def clone(self):
        return PagedDecodePredictor(self._base, _clone_of=self)

    # -- streams -----------------------------------------------------------
    def open_stream(self, slot, prompt):
        """Begin a stream on `slot`: match the prefix cache and adopt
        any shared pages (read-only, zero recompute). Allocates no new
        pages, so admission itself can never exhaust the pool. Returns
        {'shared_tokens', 'chunks'} — the suffix prefill plan."""
        slot = int(slot)
        if not 0 <= slot < self.slots:
            raise ValueError('slot %r outside [0, %d)' % (slot, self.slots))
        if slot in self._tables:
            raise RuntimeError('slot %d already holds a stream — '
                               'release() it first' % slot)
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not 1 <= len(prompt) <= self.max_len:
            raise ValueError('prompt length %d outside [1, %d] (max_len)'
                             % (len(prompt), self.max_len))
        table = PageTable(self._pool, self.pages_per_slot)
        pages, shared = self._prefix.match(prompt, limit=len(prompt) - 1)
        if shared:
            table.adopt_shared(pages, shared)
            _prefix_hits.inc()
            _prefix_tokens.inc(shared)
        self._tables[slot] = table
        self._pending[slot] = _PendingPrefill(prompt)
        self._update_gauges()
        chunk = self.prefill_chunk
        return {'slot': slot, 'prompt_tokens': len(prompt),
                'shared_tokens': shared,
                'chunks': -(-(len(prompt) - shared) // chunk)}

    def release(self, slot):
        """Drop a stream's page refs (cache-registered prefix pages
        stay resident for future hits)."""
        slot = int(slot)
        table = self._tables.pop(slot, None)
        self._pending.pop(slot, None)
        if table is not None:
            table.release()
            self._update_gauges()

    # -- preempt / resume (serving/preempt.py) -----------------------------
    def save_stream(self, slot):
        """Snapshot one open, fully prefilled stream's page contents
        device -> host (preempt-first capacity, serving/preempt.py).
        Returns {'length', 'pages', 'data', 'nbytes'}; the stream
        itself is untouched — the caller release()s the slot only after
        the copy succeeded, so a failed gather never loses pages."""
        slot = int(slot)
        if slot in self._pending:
            raise RuntimeError('slot %d is still prefilling — requeue '
                               'it, there is nothing worth swapping'
                               % slot)
        table = self._tables[slot]
        pools = [self._scope.find_var(name)
                 for name in self._pair.cache_names]
        data = self._pool.save_pages(pools, table.pages)
        return {'length': table.length, 'pages': len(table.pages),
                'data': data,
                'nbytes': int(sum(d.nbytes for d in data))}

    def restore_stream(self, slot, snapshot, prompt=None):
        """Re-seat a save_stream() snapshot on `slot`: allocate fresh
        pages (all-or-nothing — CacheExhaustedError with nothing taken
        when the pool is still too tight, so the resuming stream just
        stays queued), write the host copies back, and rebuild the page
        table at the saved length. Every restored page is private (the
        stream owns the fresh copies), so later appends never fork.
        `prompt` (the committed token sequence) is unused here; the
        speculative override re-prefills its draft from it."""
        slot = int(slot)
        if slot in self._tables:
            raise RuntimeError('slot %d already holds a stream — '
                               'release() it first' % slot)
        names = self._pair.cache_names
        pools = [self._scope.find_var(name) for name in names]
        ids, pools = self._pool.restore_pages(pools, snapshot['data'])
        for name, pool in zip(names, pools):
            # _place_cache: on a mesh the .at[].set result re-pins to the
            # heads-sharded layout so the donated pool never flips
            # sharding (which would recompile the decode step)
            self._scope.set_var(name, self._place_cache(name, pool))
        table = PageTable(self._pool, self.pages_per_slot)
        table.pages = list(ids)
        table.length = int(snapshot['length'])
        self._tables[slot] = table
        self._update_gauges()

    # -- disaggregated page shipping (serving/disagg.py) -------------------
    def export_prefix(self, prompt):
        """Gather the full-page hash chain this cache holds for
        `prompt` (capped at prompt[:-1], the sharing limit) into host
        float32 copies — the prefill tier's half of a page ship.
        Returns None when nothing is resident, else {'keys' (hex, in
        chain order), 'tokens', 'data' (one [n, page_tokens, ...] array
        per layer pool), 'nbytes'}. A pure read: refcounts, tables and
        LRU stamps are untouched."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        digests, pages = self._prefix.chain(prompt,
                                            limit=len(prompt) - 1)
        if not digests:
            return None
        pools = [self._scope.find_var(name)
                 for name in self._pair.cache_names]
        data = self._pool.save_pages(pools, pages)
        return {'keys': [d.hex() for d in digests],
                'tokens': len(digests) * self.page_tokens,
                'data': data,
                'nbytes': int(sum(d.nbytes for d in data))}

    def resident_keys(self, prompt):
        """Hex keys of the leading full-page chain run this cache holds
        for `prompt` — the 'have' list a page fetch sends so the sender
        skips pages already here. Advisory (no quiesce, no LRU touch):
        install_prefix re-checks residency under the swap gate, so a
        racing eviction only costs wire bytes, never correctness."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        digests, _ = self._prefix.chain(prompt, limit=len(prompt) - 1)
        return [d.hex() for d in digests]

    def install_prefix(self, prompt, keys, data, skip=0):
        """Install a shipped page run into the local pool + prefix
        cache (the decode tier's half). `keys` is the FULL leading run
        of the prompt's hash chain the sender holds; `data` carries
        rows for keys[skip:] only (the sender omitted pages the
        receiver reported having). The chain is recomputed here, so a
        shipment with foreign pages, corrupt keys, or a different
        page_tokens is refused with ValueError and the caller
        re-prefills locally — as is a shipment whose skipped prefix is
        no longer resident (evicted between report and install: the
        graft would dangle). Rows already resident (a racing install)
        are deduped without allocation. Returns (installed, deduped)
        page counts; raises the retryable CacheExhaustedError with
        nothing taken when the pool cannot fit the fresh rows."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        keys = list(keys)
        skip = int(skip)
        n = len(keys)
        if n == 0:
            return 0, 0
        expected = chain_keys(prompt, self.page_tokens,
                              limit=len(prompt) - 1)
        if keys != expected[:n]:
            raise ValueError(
                'shipped keys are not a leading run of the prompt '
                'hash chain (%d keys, page_tokens=%d)'
                % (n, self.page_tokens))
        resident, _ = self._prefix.chain(prompt, limit=len(prompt) - 1)
        have = min(len(resident), n)
        if have >= n:
            return 0, n
        if have < skip:
            raise ValueError(
                'shipment skipped %d pages but only %d are still '
                'resident — the graft parent was evicted' % (skip, have))
        names = self._pair.cache_names
        pools = [self._scope.find_var(name) for name in names]
        ids, pools = self._pool.restore_pages(
            pools, [rows[have - skip:n - skip] for rows in data])
        for name, pool in zip(names, pools):
            self._scope.set_var(name, self._place_cache(name, pool))
        parent = resident[have - 1] if have else b''
        self._prefix.extend_chain(
            parent, [bytes.fromhex(k) for k in keys[have:n]], ids)
        self._update_gauges()
        return n - have, have

    def prefix_report(self):
        """Drain the prefix cache's registered/evicted delta (the
        SRV_HEALTH payload feeding the fleet prefix directory)."""
        return self._prefix.drain_events()

    @staticmethod
    def _rollback(cows, grows):
        """Undo page mutations from a failed (never-run) step: COW
        sources were NOT unref'd yet, so restoring them is pure
        bookkeeping and the device state is untouched."""
        for table, before in reversed(grows):
            while len(table.pages) > before:
                table.pool.unref(table.pages.pop())
        for table, idx, (src, dst) in reversed(cows):
            table.pages[idx] = src
            table.shared.add(idx)
            table.pool.unref(dst)

    # -- execution ---------------------------------------------------------
    def prefill_step(self, slot, return_logits=False):
        """Advance one stream's prefill by ONE chunk. Returns None
        while more chunks remain; on the final chunk, registers the
        prompt with the prefix cache and returns the first greedy
        token (with return_logits: (token, logits [vocab])). Raises
        CacheExhaustedError — with this call's allocations rolled
        back — when the pool cannot cover the chunk."""
        slot = int(slot)
        st = self._pending[slot]
        table = self._tables[slot]
        prompt, start = st.prompt, table.length
        C, P, pt = self.prefill_chunk, self.pages_per_slot, self.page_tokens
        n = min(C, len(prompt) - start)
        cows, grows = [], []
        before = len(table.pages)
        try:
            pair = table.cow_for_append(start)
            if pair is not None:
                cows.append((table, start // pt, pair))
            table.ensure(start + n)
        except CacheExhaustedError as e:
            self._rollback(cows, grows)
            raise CacheExhaustedError(str(e), slots=[slot])
        if len(table.pages) > before:
            grows.append((table, before))
        tokens = np.zeros((1, C, 1), np.int64)
        tokens[0, :n, 0] = prompt[start:start + n]
        positions = (start + np.arange(C, dtype=np.int32))
        table_feed = np.zeros((1, P), np.int32)
        table.row(table_feed[0])
        cow_src = np.zeros((1,), np.int32)
        cow_dst = np.zeros((1,), np.int32)
        if cows:
            cow_src[0], cow_dst[0] = cows[0][2]
        logits, ids = self._exe.run(
            self._pair.prefill_program,
            feed={'prefill_tokens': tokens,
                  'prefill_positions': positions,
                  'prefill_len': np.array([n], np.int32),
                  'prefill_last': np.array([n - 1], np.int32),
                  'prefill_page_table': table_feed,
                  'prefill_cow_src': cow_src,
                  'prefill_cow_dst': cow_dst},
            fetch_list=self._pair.prefill_fetches,
            scope=self._scope, return_numpy=False)
        for table_, _idx, (src, _dst) in cows:
            table_.pool.unref(src)
        table.length = start + n
        st.chunks += 1
        self._update_gauges()
        if table.length < len(prompt):
            return None
        self._prefix.register(prompt, table)
        del self._pending[slot]
        _prefill_chunks.observe(st.chunks)
        tok = int(np.asarray(ids)[0])
        if return_logits:
            return tok, np.asarray(logits)[0]
        return tok

    def decode_step(self, tokens, positions, return_logits=False):
        """One step for the WHOLE pool — same ABI as the dense path:
        tokens [slots], positions [slots] (each stream's next append
        position, which must be its current length). Only open,
        fully-prefilled streams take part; every other lane is fed the
        null-page table row, so its mandatory write is dead weight
        exactly like the dense ring's idle-slot append. New pages are
        allocated on demand; if ANY stream cannot grow, the step runs
        nothing, this call's allocations are rolled back, and
        CacheExhaustedError(slots=[...]) names the victims — the
        caller releases or evicts them and retries the same feed."""
        S, P, pt = self.slots, self.pages_per_slot, self.page_tokens
        tokens = np.asarray(tokens, np.int64).reshape(S, 1, 1)
        positions = np.asarray(positions, np.int32).reshape(S)
        table_feed = np.zeros((S, P), np.int32)
        pos_feed = np.zeros((S,), np.int32)
        cow_src = np.zeros((S,), np.int32)
        cow_dst = np.zeros((S,), np.int32)
        cows, grows, failed, live = [], [], [], []
        for slot in sorted(self._tables):
            if slot in self._pending:
                continue              # mid-prefill: stays on null pages
            table = self._tables[slot]
            pos = int(positions[slot])
            before = len(table.pages)
            try:
                pair = table.cow_for_append(pos)
                if pair is not None:
                    cows.append((table, pos // pt, pair))
                table.ensure(pos + 1)
            except CacheExhaustedError:
                failed.append(slot)
                continue
            if len(table.pages) > before:
                grows.append((table, before))
            table.row(table_feed[slot])
            pos_feed[slot] = pos
            if pair is not None:
                cow_src[slot], cow_dst[slot] = pair
            live.append(slot)
        if failed:
            self._rollback(cows, grows)
            self._update_gauges()
            raise CacheExhaustedError(
                'KV page pool exhausted for slot(s) %s'
                % ','.join(map(str, failed)), slots=failed)
        logits, ids = self._exe.run(
            self._pair.decode_program,
            feed={'decode_tokens': tokens,
                  'decode_step_idx': pos_feed,
                  'decode_page_table': table_feed,
                  'decode_cow_src': cow_src,
                  'decode_cow_dst': cow_dst},
            fetch_list=self._pair.decode_fetches,
            scope=self._scope, return_numpy=False)
        for table, _idx, (src, _dst) in cows:
            table.pool.unref(src)
        for slot in live:
            table = self._tables[slot]
            table.length = max(table.length, int(positions[slot]) + 1)
        self._update_gauges()
        if return_logits:
            return np.asarray(ids), np.asarray(logits)
        return np.asarray(ids)

    def prefill(self, prompts, slot_ids, return_logits=False):
        """Dense-ABI prefill (the parity / generate() path): each
        prompt is streamed chunk by chunk to completion; a slot that
        already holds a stream is released first (the dense path's
        overwrite-on-admission semantics). Returns first greedy ids
        [len(prompts)] (+ last-position logits with return_logits)."""
        if not prompts or len(prompts) != len(slot_ids):
            raise ValueError('%d prompts for %d slots'
                             % (len(prompts), len(slot_ids)))
        out_ids = np.zeros((len(prompts),), np.int64)
        out_logits = []
        for i, (prompt, slot) in enumerate(zip(prompts, slot_ids)):
            slot = int(slot)
            if slot in self._tables:
                self.release(slot)
            self.open_stream(slot, prompt)
            result = None
            while result is None:
                result = self.prefill_step(slot,
                                           return_logits=return_logits)
            if return_logits:
                out_ids[i], logits = result
                out_logits.append(logits)
            else:
                out_ids[i] = result
        if return_logits:
            return out_ids, np.stack(out_logits)
        return out_ids
