"""KV-cache incremental decoding + continuous-batching serving.

The "millions of users" half of the north star (ROADMAP item 1),
layered on the inference Predictor ABI:

- decode.py   DecodePredictor: a loaded LM transpiled into a prefill +
              decode program pair (transpiler/decode_transpiler.py)
              with per-layer [slots, T, H, dk] K/V ring caches living
              in a child Scope — weights shared with the base
              Predictor (and every clone) through the parent Scope,
              cache state private per worker.
- paging.py   Host-side paged-cache bookkeeping: PagePool (refcounted
              free-list allocator over [num_pages, page_tokens, H, dk]
              pools, typed retryable CacheExhaustedError when dry),
              PageTable (per-stream logical -> physical map with
              copy-on-write forks), PrefixCache (content-hash chain
              over full pages + partial tails — shared system prompts
              map their prefix pages read-only, zero recompute).
- paged.py    PagedDecodePredictor: the DecodePredictor contract over
              the page pool — chunked prefill (one
              FLAGS_serving_prefill_chunk slice per engine iteration),
              page index as a decode feed (no recompile per
              admission), transactional on-demand page allocation.
- speculative.py  SpeculativeDecodePredictor: draft/verify speculative
              decoding over the paged cache — a layer-truncated
              self-draft (or explicit draft LM) proposes FLAGS_spec_k
              tokens per stream, one batched verify pass scores all
              k+1 positions for every slot, and greedy acceptance
              (longest matching prefix + free bonus token) keeps the
              emitted stream token-for-token identical to plain greedy
              decode. Mid-verify pool exhaustion rolls the whole
              speculation back and retries as a plain decode step.
- engine.py   ServingEngine: continuous batching over a fixed slot
              pool — requests are admitted into the running batch
              between decode steps, finished/cancelled slots are
              evicted and masked, worker threads share weights via
              clone(). serving.* telemetry flows into paddle_tpu/obs/.
- preempt.py  Preempt-first capacity policy: SLO tiers
              (submit(priority=)), victim selection (lowest tier,
              longest idle), and a host-RAM swap budget
              (FLAGS_serving_swap_host_mb) — on pool exhaustion the
              engine swaps a low-tier stream's pages to host memory
              (or drops and re-prefills when the budget is dry) and
              resumes it bit-exactly once pressure clears.
- api.py      LMServer: the user-facing blocking generate() + async
              submit/poll surface (reference
              inference/api/paddle_inference_api.h PaddlePredictor
              serving contract, re-shaped for token streams).
- replica.py  ReplicaServer: one LMServer exposed on the wire (SRV_*
              message types) so a fleet router can address it.
- disagg.py   Disaggregated prefill/decode: KV pages as first-class
              wire objects (SRV_PAGES / SRV_PAGE_FETCH) — a prefill
              tier computes pages once per unique prefix and ships
              them content-addressed to decode replicas; every ship
              failure falls back to bit-exact local re-prefill.
- fleet.py    FleetRouter: health-checked dispatch over N replicas
              with session affinity, transparent mid-stream failover
              (greedy re-prefill from the accumulated prefix),
              SLO-rule admission control (typed OverloadError), and
              zero-drop rolling weight deploys; FleetAutoscaler drives
              replica count from the same signals.

Decode cost per token is O(1) against the cache instead of O(T) prefix
recompute, and greedy decode is bit-exact against the full-recompute
path (tests/test_serving.py); the same determinism makes fleet
failover bit-exact (tests/test_fleet.py).
"""
from .decode import DecodePredictor
from .paging import (CacheExhaustedError, PagePool, PageTable,
                     PrefixCache, chain_keys)
from .paged import PagedDecodePredictor
from .speculative import DraftModel, SpeculativeDecodePredictor
from .engine import ServingEngine, Request, DeadlineExceededError
from .preempt import HostSwapBudget
from .api import LMServer
from .replica import ReplicaServer
from .disagg import ShipError
from .fleet import (FleetRouter, FleetAutoscaler, FleetRequest,
                    OverloadError, FleetDeployError)

__all__ = ['DecodePredictor', 'PagedDecodePredictor',
           'DraftModel', 'SpeculativeDecodePredictor',
           'CacheExhaustedError', 'PagePool', 'PageTable', 'PrefixCache',
           'chain_keys', 'ShipError',
           'ServingEngine', 'Request', 'DeadlineExceededError',
           'HostSwapBudget', 'LMServer',
           'ReplicaServer', 'FleetRouter', 'FleetAutoscaler',
           'FleetRequest', 'OverloadError', 'FleetDeployError']
