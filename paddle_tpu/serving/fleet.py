"""FleetRouter: health-checked serving fleet with admission control,
transparent mid-stream failover, and zero-drop rolling weight deploys.

The "millions of users" topology (ROADMAP item 1): one router in front
of N ReplicaServer processes (tools/serve_replica.py, each wrapping an
LMServer), speaking the SRV_* wire types. Four responsibilities:

dispatch    queue-depth/occupancy-aware: a held request goes to the
            least-loaded healthy, non-draining replica (router-side
            in-flight count + the replica's own serving.queue_depth
            from the SRV_HEALTH probe, normalized by capacity), with
            session affinity — a multi-turn session sticks to its
            replica while that replica stays eligible. The hold queue
            is tiered by priority (submit(priority=), higher = more
            important): dispatch always serves the highest non-empty
            tier first, and a replica's count of swapped-out preempted
            streams (SRV_HEALTH) raises its load score — a replica
            busy preempting is already out of cache headroom.

failover    greedy decode is deterministic, so a stream is fully
            described by (original prompt + tokens so far, remaining
            budget). When a replica dies (failed poll/submit, or
            fleet_probe_fails consecutive failed probes), every live
            stream it held is re-submitted to a healthy replica with
            its accumulated prefix as the prompt — the continuation is
            bit-exact with the unkilled run (tests/test_fleet.py), and
            the client's FleetRequest never notices beyond latency.

admission   obs/slo.py rules evaluated every control tick against the
            router's OWN fleet.* snapshot (local accounting, so the
            trigger works whether or not telemetry export is enabled).
            A rule breached fleet_shed_consecutive ticks flips the
            router into shedding: submit() raises a typed
            OverloadError (counted in fleet.shed) instead of letting
            queue depth grow until the TTFT SLO breaks. The hold-queue
            bound (fleet_max_hold) is a hard backstop. BOTH rejections
            apply only to the lowest tier (priority <= 0): a paying
            tier is always admitted — under pressure the replicas
            preempt lowest-tier streams to make room rather than the
            router turning important work away at the door.

gray        the fail-SLOW half of the failure model (Huang et al.
            "Gray Failure"; Dean & Barroso "The Tail at Scale"): a
            replica that answers SRV_HEALTH while its streams hang. A
            progress watchdog (FLAGS_fleet_progress_timeout_secs)
            gray-marks a replica whose streams — or whose in-flight
            RPC — made no progress within the horizon, fails its
            streams over through the same bit-exact re-prefill path,
            and interrupts the wedged connection so the pump never
            waits out the full RPC timeout. Gray replicas keep
            answering probes on a DEDICATED short-timeout probe
            connection (FLAGS_fleet_probe_timeout) in half-open
            probation and rejoin after FLAGS_fleet_gray_probes clean
            probes (a circuit breaker over a probe-latency EWMA +
            progress strikes). Hedged dispatch
            (FLAGS_fleet_hedge_ms) covers the slow-prefill tail: a
            stream with no first token past the horizon is duplicated
            to a second replica, first token wins, the loser is
            SRV_CANCELled — greedy determinism makes both copies
            identical, so hedging can never change output. Optional
            end-to-end deadlines (submit(deadline_ms=)) ride the
            SRV_SUBMIT meta with the ELAPSED time deducted at every
            failover/hedge re-dispatch; expiry is a typed,
            non-retryable DeadlineExceededError.

deploys     rolling_deploy(): one replica at a time — stop dispatching
            to it (+ SRV_DRAIN fence), wait for its in-flight streams,
            SRV_REFRESH (the PR-9 ParamSubscriber pull/verify/install
            path, orchestrator-driven on paused subscribers),
            health-check the installed version, rejoin. A param
            version bump drops zero streams. enable_rolling_deploys()
            watches the pservers' published version and rolls
            automatically.

FleetAutoscaler drives replica count from the same snapshot: sustained
up-rule breach -> scale_up() (spawn a replica — Supervisor.add_role —
and router.add_replica), sustained idle -> drain + remove + scale_down.

Telemetry (exported when FLAGS_obs_dir is set; the router ALSO keeps
local counts for stats() and the admission snapshot):
  fleet.requests.{submitted,completed,failed,cancelled} / fleet.shed /
  fleet.cache_sheds / fleet.failovers / fleet.replica_deaths /
  fleet.dispatches / fleet.deploys / fleet.tokens_generated /
  fleet.hedges / fleet.hedge_wins / fleet.gray_marks /
  fleet.deadline_expired                   counters;
  fleet.queue_depth / fleet.active_streams / fleet.replicas_healthy /
  fleet.replicas_total / fleet.shedding /
  fleet.pages_shipped / fleet.ship_bytes / fleet.prefix_hit_rate
                                           gauges;
  fleet.ttft / fleet.dispatch_wait / fleet.probe_latency  histograms;
  fleet.deploy / fleet.drain               spans.
"""
from __future__ import annotations

import collections
import itertools
import os
import socket
import threading
import time

import numpy as np

from ..distributed import wire
from ..flags import get_flag
from ..obs import telemetry
from ..obs import trace as _trace
from .engine import QUEUED, RUNNING, DONE, CANCELLED, FAILED

__all__ = ['FleetRouter', 'FleetAutoscaler', 'FleetRequest',
           'OverloadError', 'FleetDeployError']

_submitted = telemetry.counter('fleet.requests.submitted')
_completed = telemetry.counter('fleet.requests.completed')
_failed = telemetry.counter('fleet.requests.failed')
_cancelled = telemetry.counter('fleet.requests.cancelled')
_shed = telemetry.counter('fleet.shed')
_cache_sheds = telemetry.counter('fleet.cache_sheds')
_failovers = telemetry.counter('fleet.failovers')
_deaths = telemetry.counter('fleet.replica_deaths')
_dispatches = telemetry.counter('fleet.dispatches')
_deploys = telemetry.counter('fleet.deploys')
_tokens_out = telemetry.counter('fleet.tokens_generated')
_queue_depth = telemetry.gauge('fleet.queue_depth')
_active_streams = telemetry.gauge('fleet.active_streams')
_replicas_healthy = telemetry.gauge('fleet.replicas_healthy')
_replicas_total = telemetry.gauge('fleet.replicas_total')
_shedding_g = telemetry.gauge('fleet.shedding')
_ttft = telemetry.histogram('fleet.ttft')
_dispatch_wait = telemetry.histogram('fleet.dispatch_wait')
_hedges = telemetry.counter('fleet.hedges')
_hedge_wins = telemetry.counter('fleet.hedge_wins')
_gray_marks = telemetry.counter('fleet.gray_marks')
_deadline_expired = telemetry.counter('fleet.deadline_expired')
_probe_latency = telemetry.histogram('fleet.probe_latency')
# disaggregated prefill/decode (serving/disagg.py): fleet-wide totals
# aggregated from SRV_HEALTH each control tick — gauges, because the
# replicas own the counters and the router only mirrors their sum
_pages_shipped_g = telemetry.gauge('fleet.pages_shipped')
_ship_bytes_g = telemetry.gauge('fleet.ship_bytes')
_prefix_hit_rate_g = telemetry.gauge('fleet.prefix_hit_rate')


class OverloadError(RuntimeError):
    """Typed admission-control rejection: the fleet is shedding load
    (an obs/slo.py admission rule breached for a sustained window) or
    the hold queue hit its hard bound. Back off and retry — accepted
    streams are being protected, not dropped."""


class FleetDeployError(RuntimeError):
    """A rolling deploy step could not complete inside its deadline
    (drain stuck, refresh failing, or the post-refresh health check
    disagreeing about the installed version). The replica is
    un-drained and keeps serving its OLD verified weights."""


class _ReplicaError(RuntimeError):
    """REPLY_ERR from a replica; retryable mirrors the wire field."""

    def __init__(self, msg, retryable=False):
        super(_ReplicaError, self).__init__(msg)
        self.retryable = retryable


class _LocalHist(object):
    """telemetry.Histogram's bucket layout, always-on: the admission
    rules must see fleet.ttft whether or not export is enabled, so the
    router keeps its own accounting and feeds SLORule.evaluate
    snapshot-shaped dicts."""

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float('inf')
        self.max = float('-inf')
        self.buckets = [0] * (len(telemetry._BOUNDS) + 1)

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        i = 0
        for bound in telemetry._BOUNDS:
            if v <= bound:
                break
            i += 1
        self.buckets[i] += 1

    def snapshot(self):
        return {'count': self.count, 'sum': self.sum, 'min': self.min,
                'max': self.max, 'buckets': list(self.buckets)}


class FleetRequest(object):
    """One fleet-level generation stream. `tokens` accumulates ACROSS
    failover segments; wait()/result() match engine.Request."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens, eos_id, session,
                 priority=0, deadline_ms=None):
        self.id = next(FleetRequest._ids)
        self.prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.session = session
        self.priority = int(priority)
        self.state = QUEUED
        self.tokens = []
        self.error = None
        self.replica = None           # endpoint currently serving it
        self.segment = 0              # bumps on every failover
        self.cache_sheds = 0          # CacheExhausted retry budget used
        self.base = 0                 # len(tokens) at segment dispatch
        self.rid = None
        self.submitted_at = time.perf_counter()
        # end-to-end budget: absolute perf_counter expiry, None = no
        # deadline. Every re-dispatch (failover, hedge) forwards only
        # the REMAINING milliseconds — elapsed time is never refunded.
        self.deadline_at = (None if deadline_ms is None
                            else self.submitted_at
                            + float(deadline_ms) / 1000.0)
        # progress clock for the gray-failure watchdog: stamped at
        # dispatch and on every token growth
        self.last_progress_at = None
        self.hedge_ep = None          # endpoint holding the duplicate
        self.hedge_rid = None
        self._ck_cache = None         # (page_tokens, chain keys) memo
        #                               for the prefix-affinity score
        self.dispatched_at = None
        self.first_token_at = None
        self.done_at = None
        self._done = threading.Event()

    def _finish(self, state, error=None):
        self.state = state
        self.error = error
        self.done_at = time.perf_counter()
        self._done.set()

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    def result(self, timeout=None):
        if not self.wait(timeout):
            raise TimeoutError('fleet request %d still %s after %rs'
                               % (self.id, self.state, timeout))
        if self.state == FAILED:
            raise RuntimeError('fleet request %d failed: %s'
                               % (self.id, self.error))
        return list(self.tokens)


class _ReplicaClient(object):
    """One blocking wire connection to a replica (reconnect on demand,
    serialized calls, seq-echo desync check). NO retry layer: a failed
    call IS the router's death signal for that replica."""

    def __init__(self, endpoint, timeout=10.0):
        self.endpoint = endpoint
        self._timeout = float(timeout)
        self._sock = None
        self._mu = threading.Lock()
        self._seq = itertools.count()
        # perf_counter at the start of the in-flight call, None when
        # idle — the gray-failure watchdog reads this (racily, without
        # the lock: a stale glimpse only delays detection one tick) to
        # catch a replica that accepted a request and then went silent
        self.inflight_since = None

    def call(self, msg_type, meta=None, value=None, timeout=None):
        with self._mu:
            self.inflight_since = time.perf_counter()
            try:
                return self._call_locked(msg_type, meta, value, timeout)
            finally:
                self.inflight_since = None

    def _call_locked(self, msg_type, meta, value, timeout):
        seq = next(self._seq)
        m = dict(meta or {})
        m['seq'] = seq
        try:
            if self._sock is None:
                host, port = self.endpoint.rsplit(':', 1)
                # the dial honors the caller's budget: a short-timeout
                # probe must not spend the full connect allowance on a
                # SYN blackhole (FLAGS_fleet_connect_timeout caps the
                # dial fleet-wide; the per-call timeout caps it tighter)
                dial = min(float(timeout or self._timeout),
                           float(get_flag('fleet_connect_timeout')))
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=dial)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
            self._sock.settimeout(timeout or self._timeout)
            wire.write_msg(self._sock, msg_type, m, value)
            rt, rmeta, _rv = wire.read_msg(self._sock)
        except (ConnectionError, OSError):
            self._reset_locked()
            raise
        if rmeta.get('seq') != seq:
            self._reset_locked()
            raise ConnectionError(
                'replica %s reply seq %r != %d — desynced'
                % (self.endpoint, rmeta.get('seq'), seq))
        if rt == wire.REPLY_ERR:
            raise _ReplicaError(
                '%s: %s' % (self.endpoint, rmeta.get('error')),
                retryable=bool(rmeta.get('retryable')))
        return rmeta

    def _reset_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def interrupt(self):
        """Unblock a stalled in-flight call WITHOUT taking the call
        lock — the stalled caller HOLDS it, so close() here would
        deadlock the watchdog behind the very stall it is breaking.
        shutdown() makes the blocked read raise immediately; the
        call's own error path then closes and resets the socket."""
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self):
        with self._mu:
            self._reset_locked()


class _Replica(object):
    __slots__ = ('endpoint', 'client', 'probe', 'order', 'healthy',
                 'draining', 'fails', 'active', 'hedges', 'capacity',
                 'queue_depth', 'max_len', 'param_version', 'hold_until',
                 'gray', 'strikes', 'clean_probes', 'probe_ewma',
                 'cache_tokens', 'cache_capacity',
                 'effective_tokens_per_step', 'spec_accept_rate',
                 'preemptions', 'preempted_streams', 'role',
                 'mesh_shape', 'mesh_devices',
                 'page_tokens', 'prefix_hits', 'prefix_misses',
                 'prefix_entries', 'prefix_pages', 'pages_shipped',
                 'ship_bytes', 'pages_installed', 'pages_deduped',
                 'local_reprefills')

    def __init__(self, endpoint, order, timeout, role='serve'):
        self.endpoint = endpoint
        self.client = _ReplicaClient(endpoint, timeout=timeout)
        # health probes ride a DEDICATED connection: a gray replica
        # stalls its data connection while this one keeps answering —
        # exactly the split that lets the router keep measuring a
        # replica it no longer trusts with streams
        self.probe = _ReplicaClient(endpoint, timeout=timeout)
        self.order = order
        self.healthy = False          # flips on the first good probe
        self.draining = False
        self.fails = 0
        self.active = {}              # req.id -> FleetRequest
        self.hedges = {}              # req.id -> FleetRequest (duplicates
        #                               hedged ONTO this replica)
        self.gray = False             # gray-marked: probe-only probation
        self.strikes = 0              # consecutive slow-probe strikes
        self.clean_probes = 0         # clean probes while gray
        self.probe_ewma = None        # probe-latency EWMA (secs)
        self.capacity = 1
        self.queue_depth = 0
        self.max_len = None
        self.param_version = None
        self.hold_until = 0.0         # brief dispatch backoff (full)
        self.cache_tokens = 0         # tokens held in the KV cache
        self.cache_capacity = None    # total cache tokens (paged)
        # speculative replicas: mean tokens emitted per decode step
        # (>= 1.0 once speculation engages; 1.0 == plain decode) and
        # the measured draft accept rate, both from SRV_HEALTH
        self.effective_tokens_per_step = 1.0
        self.spec_accept_rate = None
        # preempt-first replicas: lifetime preemptions plus streams
        # currently swapped out awaiting resume (both from SRV_HEALTH)
        self.preemptions = 0
        self.preempted_streams = 0
        # disaggregated serving: 'prefill' replicas answer
        # SRV_PAGE_FETCH and never take decode streams; 'serve' (the
        # default) is the decode/colocated tier. The prefix/ship
        # numbers mirror the replica's SRV_HEALTH truth.
        self.role = role
        # mesh-sharded replicas: axis spec + chip count their SPMD
        # decode programs span (SRV_HEALTH; '' / 1 = single-chip)
        self.mesh_shape = ''
        self.mesh_devices = 1
        self.page_tokens = None
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_entries = 0
        self.prefix_pages = 0
        self.pages_shipped = 0
        self.ship_bytes = 0
        self.pages_installed = 0
        self.pages_deduped = 0
        self.local_reprefills = 0


class FleetAutoscaler(object):
    """Replica-count policy over the router's admission snapshot.

    scale_up() -> endpoint of a freshly launched replica (the caller
    owns process lifecycle — Supervisor.add_role in production); the
    router add_replica()s it. scale_down(endpoint) is called AFTER the
    router drained and removed the replica. Sustained up-rule breach
    (default: fleet.queue_depth > 0) scales out; a fully idle fleet
    (no held or active streams) for `sustain` ticks scales in, down to
    min_replicas. cooldown_secs separates consecutive actions."""

    def __init__(self, scale_up=None, scale_down=None, min_replicas=1,
                 max_replicas=8, up_rules=None, sustain=3,
                 cooldown_secs=5.0):
        from ..obs import slo as _slo
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.sustain = int(sustain)
        self.cooldown_secs = float(cooldown_secs)
        if up_rules is None:
            up_rules = [{'name': 'fleet_backlog',
                         'metric': 'fleet.queue_depth',
                         'kind': 'gauge_max', 'threshold': 0}]
        self.up_rules = _slo.parse_rules(up_rules)
        self._up_streak = 0
        self._idle_streak = 0
        self._cool_until = 0.0
        self.events = []              # [(monotonic, action, endpoint)]

    def tick(self, router, snap, prev, dt):
        gauges = snap.get('gauges', {})
        breach = False
        for rule in self.up_rules:
            out = rule.evaluate(snap, prev=prev, dt=dt)
            if out is not None and out[1]:
                breach = True
        idle = (not gauges.get('fleet.queue_depth')
                and not gauges.get('fleet.active_streams'))
        if breach:
            self._up_streak += 1
            self._idle_streak = 0
        elif idle:
            self._idle_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._idle_streak = 0
        now = time.monotonic()
        if now < self._cool_until:
            return
        n = len(router.replicas())
        if (self._up_streak >= self.sustain and n < self.max_replicas
                and self.scale_up is not None):
            ep = self.scale_up()
            if ep:
                router.add_replica(ep)
                self.events.append((now, 'scale_up', ep))
                _trace.event('fleet.scale_up', endpoint=ep, replicas=n + 1)
            self._up_streak = 0
            self._cool_until = now + self.cooldown_secs
        elif self._idle_streak >= self.sustain and n > self.min_replicas:
            victim = router.idle_replica()
            if victim is None:
                return
            try:
                router.remove_replica(victim, drain=True, timeout=10.0)
            except FleetDeployError:
                return                # streams arrived mid-drain: keep it
            if self.scale_down is not None:
                self.scale_down(victim)
            self.events.append((now, 'scale_down', victim))
            _trace.event('fleet.scale_down', endpoint=victim,
                         replicas=n - 1)
            self._idle_streak = 0
            self._cool_until = now + self.cooldown_secs


class FleetRouter(object):
    def __init__(self, replicas, pservers=None, poll_secs=None,
                 probe_secs=None, max_hold=None, admission_rules=None,
                 shed_consecutive=None, probe_fail_threshold=None,
                 call_timeout=10.0, subscriber_id=900,
                 prefill_replicas=None):
        """replicas: ReplicaServer endpoints ('host:port'). pservers:
        the parameter-server fleet (only needed for published-version
        watching / enable_rolling_deploys). admission_rules: obs/slo.py
        rule list (objects, dicts, JSON, or @path — parse_rules) over
        the fleet.* snapshot; default is a fleet.queue_depth gauge_max
        rule at max_hold/2. prefill_replicas: endpoints of the PREFILL
        TIER (serving/disagg.py) — probed for health like any replica
        but never dispatched decode streams; defaults from
        FLAGS_fleet_prefill_endpoints ('' = colocated, no tier)."""
        from ..obs import slo as _slo
        self._poll_secs = float(poll_secs if poll_secs is not None
                                else get_flag('fleet_poll_secs'))
        self._probe_secs = float(probe_secs if probe_secs is not None
                                 else get_flag('fleet_probe_secs'))
        self._max_hold = int(max_hold or get_flag('fleet_max_hold'))
        self._shed_consecutive = int(
            shed_consecutive if shed_consecutive is not None
            else get_flag('fleet_shed_consecutive'))
        self._probe_fail_threshold = int(
            probe_fail_threshold if probe_fail_threshold is not None
            else get_flag('fleet_probe_fails'))
        self._call_timeout = float(call_timeout)
        self._probe_timeout = min(
            float(get_flag('fleet_probe_timeout')), self._call_timeout)
        self._progress_timeout = float(
            get_flag('fleet_progress_timeout_secs'))
        self._hedge_ms = float(get_flag('fleet_hedge_ms'))
        self._gray_probes = max(1, int(get_flag('fleet_gray_probes')))
        if admission_rules is None:
            admission_rules = get_flag('fleet_admission_rules') or [
                {'name': 'fleet_queue_depth',
                 'metric': 'fleet.queue_depth', 'kind': 'gauge_max',
                 'threshold': max(1, self._max_hold // 2)}]
        self._admission_rules = _slo.parse_rules(admission_rules)
        self._pservers = list(pservers or [])
        self._subscriber_id = int(subscriber_id)
        self._mu = threading.Condition()
        self._hold = {}               # priority tier -> deque
        self._reps = {}
        self._order = itertools.count()
        self._sessions = {}           # session -> endpoint
        self._nonce = os.urandom(4).hex()
        self._ttft_local = _LocalHist()
        self._submitted_n = 0
        self._completed_n = 0
        self._failed_n = 0
        self._cancelled_n = 0
        self._shed_n = 0
        self._cache_sheds_n = 0
        self._failovers_n = 0
        self._deploys_n = 0
        self._tokens_n = 0
        self._dispatches_n = 0
        self._hedges_n = 0
        self._hedge_wins_n = 0
        self._gray_marks_n = 0
        self._deadline_expired_n = 0
        self._cancelq = []            # [(endpoint, rid)] — loser rids
        #                               the pump SRV_CANCELs best-effort
        self._pollers = {}            # endpoint -> poller thread
        self._shedding = False
        self._breach_streak = 0
        self._breach_rule = None
        self._prev_snap = None
        self._prev_snap_t = None
        self._deployed_version = 0
        self._autoscaler = None
        self._stop_evt = threading.Event()
        self._threads = []
        # disaggregated serving: the fleet-wide prefix directory — hex
        # chain key -> set of endpoints whose PrefixCache holds that
        # page (reconciled from SRV_HEALTH new/evicted deltas,
        # invalidated wholesale on death/gray-mark) — plus the
        # prefix-affinity weight feeding _pick_locked
        self._prefix_dir = {}
        self._prefix_affinity = float(get_flag('fleet_prefix_affinity'))
        for ep in replicas:
            self.add_replica(ep)
        if prefill_replicas is None:
            raw = str(get_flag('fleet_prefill_endpoints') or '')
            prefill_replicas = [e.strip() for e in raw.split(',')
                                if e.strip()]
        for ep in prefill_replicas:
            self.add_replica(ep, role='prefill')

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._threads:
            return self
        self._stop_evt.clear()
        self._threads = [
            threading.Thread(target=self._pump_loop,
                             name='fleet-pump', daemon=True),
            threading.Thread(target=self._control_loop,
                             name='fleet-control', daemon=True)]
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop_evt.set()
        with self._mu:
            reps = list(self._reps.values())
        for rep in reps:
            # unblock any poller/pump call wedged on a stalled replica
            # so the joins below do not wait out a full RPC timeout
            rep.client.interrupt()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        for t in self._pollers.values():
            t.join(timeout=5.0)
        self._pollers.clear()
        with self._mu:
            victims = [r for q in self._hold.values() for r in q]
            self._hold.clear()
            for rep in self._reps.values():
                victims.extend(rep.active.values())
                rep.active.clear()
                rep.hedges.clear()
        for req in victims:
            if req.state in (QUEUED, RUNNING):
                req._finish(CANCELLED)
                self._cancelled_n += 1
                _cancelled.inc()
        for rep in self._reps.values():
            rep.client.close()
            rep.probe.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- fleet membership --------------------------------------------------
    def add_replica(self, endpoint, role='serve'):
        with self._mu:
            if endpoint in self._reps:
                return
            self._reps[endpoint] = _Replica(endpoint,
                                            next(self._order),
                                            self._call_timeout,
                                            role=role)
        _replicas_total.set(len(self._reps))

    def remove_replica(self, endpoint, drain=True, timeout=30.0):
        """Scale-in: stop dispatching to the replica, optionally wait
        for its in-flight streams, drop it. The process itself belongs
        to the caller (Supervisor.remove_role)."""
        with self._mu:
            rep = self._reps.get(endpoint)
            if rep is None:
                return
            rep.draining = True
        if drain:
            deadline = time.monotonic() + timeout
            while True:
                with self._mu:
                    n = len(rep.active)
                if not n:
                    break
                if time.monotonic() >= deadline:
                    with self._mu:
                        rep.draining = False
                    raise FleetDeployError(
                        'replica %s still has %d in-flight streams '
                        'after %.1fs drain' % (endpoint, n, timeout))
                time.sleep(0.01)
        with self._mu:
            # no drain (or a dead replica): surviving streams fail over
            for req in list(rep.active.values()):
                rep.active.pop(req.id, None)
                self._requeue_locked(req)
            for req in list(rep.hedges.values()):
                self._drop_hedge_locked(req, cancel=False)
            self._reps.pop(endpoint, None)
            self._dir_forget_locked(endpoint)
            for s, ep in list(self._sessions.items()):
                if ep == endpoint:
                    del self._sessions[s]
        rep.client.close()
        rep.probe.close()
        _replicas_total.set(len(self._reps))

    def replicas(self):
        with self._mu:
            return list(self._reps)

    def idle_replica(self):
        """A healthy replica with no in-flight streams (scale-in
        victim), preferring the newest; None when all are busy."""
        with self._mu:
            idle = [r for r in self._reps.values()
                    if r.healthy and not r.active and not r.draining
                    and r.role != 'prefill']
            if not idle:
                return None
            return max(idle, key=lambda r: r.order).endpoint

    def wait_healthy(self, n=None, timeout=60.0):
        """Block until `n` replicas (default: all) answered a probe."""
        if n is None:
            n = len(self._reps)
        deadline = time.monotonic() + timeout
        while True:
            with self._mu:
                healthy = sum(1 for r in self._reps.values()
                              if r.healthy)
            if healthy >= n:
                return healthy
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    'only %d/%d replicas healthy after %.1fs'
                    % (healthy, n, timeout))
            time.sleep(0.02)

    def attach_autoscaler(self, autoscaler):
        self._autoscaler = autoscaler
        return autoscaler

    # -- hold queue (tiered by priority) -----------------------------------
    def _hold_len_locked(self):
        return sum(len(q) for q in self._hold.values())

    def _hold_push_locked(self, req, front=False):
        q = self._hold.get(req.priority)
        if q is None:
            q = self._hold[req.priority] = collections.deque()
        (q.appendleft if front else q.append)(req)
        _queue_depth.set(self._hold_len_locked())

    def _hold_front_locked(self):
        """The highest non-empty tier's deque, or None."""
        for prio in sorted(self._hold, reverse=True):
            if self._hold[prio]:
                return self._hold[prio]
        return None

    # -- client surface ----------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               session=None, priority=0, deadline_ms=None):
        """Admit a stream into the fleet. priority is the SLO tier
        (higher = more important, 0 = the default lowest). Raises
        OverloadError while shedding (or when the hold queue is at its
        hard bound) — but only for the lowest tier (priority <= 0):
        higher tiers are always admitted, and the replicas preempt
        lowest-tier streams to make room for them. deadline_ms is the
        optional end-to-end budget (None = no deadline): expiry fails
        the stream with a typed, non-retryable DeadlineExceededError —
        at dispatch (before wasting a prefill) or replica-side at
        dequeue / per decode step — and the REMAINING budget, elapsed
        deducted, rides every failover or hedge re-dispatch."""
        req = FleetRequest(prompt, max_new_tokens, eos_id, session,
                           priority=priority, deadline_ms=deadline_ms)
        if not req.prompt:
            raise ValueError('empty prompt')
        with self._mu:
            if req.priority <= 0:
                if self._shedding:
                    self._shed_n += 1
                    _shed.inc()
                    raise OverloadError(
                        'fleet is shedding: admission rule %r breached '
                        '%d consecutive checks' % (self._breach_rule,
                                                   self._breach_streak))
                if self._hold_len_locked() >= self._max_hold:
                    self._shed_n += 1
                    _shed.inc()
                    raise OverloadError('fleet hold queue full (%d)'
                                        % self._max_hold)
            self._hold_push_locked(req)
            self._submitted_n += 1
            _submitted.inc()
            self._mu.notify_all()
        return req

    def generate(self, prompt, max_new_tokens=16, eos_id=None,
                 session=None, priority=0, timeout=None):
        return self.submit(prompt, max_new_tokens, eos_id=eos_id,
                           session=session,
                           priority=priority).result(timeout)

    def cancel(self, req):
        with self._mu:
            if req.state == QUEUED and req.replica is None:
                try:
                    self._hold.get(req.priority,
                                   collections.deque()).remove(req)
                except ValueError:
                    pass
                else:
                    req._finish(CANCELLED)
                    self._cancelled_n += 1
                    _cancelled.inc()
                    return req
            rep = self._reps.get(req.replica)
            rid = req.rid
        if rep is not None and rid is not None:
            try:
                rep.client.call(wire.SRV_CANCEL, {'rid': rid})
            except (ConnectionError, OSError, _ReplicaError):
                pass                  # the pump will finalize either way
        return req

    def stats(self):
        with self._mu:
            reps = {ep: {'healthy': r.healthy, 'draining': r.draining,
                         'gray': r.gray,
                         'active': len(r.active),
                         'capacity': r.capacity,
                         'queue_depth': r.queue_depth,
                         'param_version': r.param_version,
                         'effective_tokens_per_step':
                             r.effective_tokens_per_step,
                         'spec_accept_rate': r.spec_accept_rate,
                         'preemptions': r.preemptions,
                         'preempted_streams': r.preempted_streams,
                         'role': r.role,
                         'mesh_shape': r.mesh_shape,
                         'mesh_devices': r.mesh_devices,
                         'prefix_entries': r.prefix_entries,
                         'prefix_hits': r.prefix_hits,
                         'prefix_misses': r.prefix_misses,
                         'pages_shipped': r.pages_shipped,
                         'local_reprefills': r.local_reprefills}
                    for ep, r in self._reps.items()}
            hits = sum(r.prefix_hits for r in self._reps.values()
                       if r.role != 'prefill')
            misses = sum(r.prefix_misses for r in self._reps.values()
                         if r.role != 'prefill')
            return {'replicas': reps,
                    'prefill_replicas': sum(
                        1 for r in self._reps.values()
                        if r.role == 'prefill'),
                    'pages_shipped': sum(r.pages_shipped
                                         for r in self._reps.values()),
                    'ship_bytes': sum(r.ship_bytes
                                      for r in self._reps.values()),
                    'pages_installed': sum(
                        r.pages_installed for r in self._reps.values()),
                    'pages_deduped': sum(
                        r.pages_deduped for r in self._reps.values()),
                    'local_reprefills': sum(
                        r.local_reprefills
                        for r in self._reps.values()),
                    'prefix_hits': hits,
                    'prefix_misses': misses,
                    'prefix_hit_rate': (hits / (hits + misses)
                                        if hits + misses else 0.0),
                    'prefix_dir_entries': len(self._prefix_dir),
                    'queue_depth': self._hold_len_locked(),
                    'active': sum(len(r.active)
                                  for r in self._reps.values()),
                    'submitted': self._submitted_n,
                    'completed': self._completed_n,
                    'failed': self._failed_n,
                    'cancelled': self._cancelled_n,
                    'shed': self._shed_n,
                    'cache_sheds': self._cache_sheds_n,
                    'preemptions': sum(r.preemptions
                                       for r in self._reps.values()),
                    'failovers': self._failovers_n,
                    'deploys': self._deploys_n,
                    'dispatches': self._dispatches_n,
                    'tokens': self._tokens_n,
                    'hedges': self._hedges_n,
                    'hedge_wins': self._hedge_wins_n,
                    'gray_marks': self._gray_marks_n,
                    'deadline_expired': self._deadline_expired_n,
                    'shedding': self._shedding}

    def admission_snapshot(self):
        """The snapshot-dict the admission rules (and any external SLO
        check) evaluate — fleet.* series from the router's local
        accounting, telemetry-shaped."""
        with self._mu:
            return self._snapshot_locked()

    def _snapshot_locked(self):
        active = sum(len(r.active) for r in self._reps.values())
        healthy = sum(1 for r in self._reps.values() if r.healthy)
        return {
            'counters': {'fleet.requests.submitted': self._submitted_n,
                         'fleet.requests.completed': self._completed_n,
                         'fleet.shed': self._shed_n,
                         'fleet.failovers': self._failovers_n,
                         'fleet.tokens_generated': self._tokens_n},
            'gauges': {'fleet.queue_depth':
                           float(self._hold_len_locked()),
                       'fleet.active_streams': float(active),
                       'fleet.replicas_healthy': float(healthy)},
            'hists': {'fleet.ttft': self._ttft_local.snapshot()}}

    # -- pump: dispatch + stream progress ----------------------------------
    def _pump_loop(self):
        while not self._stop_evt.is_set():
            try:
                self._ensure_pollers()
                self._dispatch_held()
                self._drain_cancelq()
            except Exception as e:    # noqa: BLE001 — router survives
                _trace.event('fleet.pump_error', error=repr(e))
            self._stop_evt.wait(self._poll_secs)

    def _ensure_pollers(self):
        """One poll thread PER replica (started lazily here, pump
        thread only): a gray replica stalls its own poll for the full
        RPC timeout, and with a shared poll loop that stall would
        freeze progress for every healthy replica too — the exact
        amplification gray failures are famous for."""
        with self._mu:
            reps = list(self._reps.values())
        for rep in reps:
            t = self._pollers.get(rep.endpoint)
            if t is not None and t.is_alive():
                continue
            t = threading.Thread(target=self._poller_loop, args=(rep,),
                                 name='fleet-poll-%s' % rep.endpoint,
                                 daemon=True)
            self._pollers[rep.endpoint] = t
            t.start()

    def _poller_loop(self, rep):
        while not self._stop_evt.is_set():
            if self._reps.get(rep.endpoint) is not rep:
                return                # replica removed (or replaced)
            try:
                self._poll_one(rep)
            except Exception as e:    # noqa: BLE001 — router survives
                _trace.event('fleet.pump_error', error=repr(e))
            self._stop_evt.wait(self._poll_secs)

    def _drain_cancelq(self):
        """Best-effort SRV_CANCEL of hedge-loser rids, off the poller
        threads so a slow loser cannot block progress accounting."""
        while True:
            with self._mu:
                if not self._cancelq:
                    return
                ep, rid = self._cancelq.pop(0)
                rep = self._reps.get(ep)
            if rep is None:
                continue
            try:
                rep.client.call(wire.SRV_CANCEL, {'rid': rid})
            except (ConnectionError, OSError, _ReplicaError):
                pass                  # loser dies with its replica

    def _dispatch_held(self):
        while not self._stop_evt.is_set():
            with self._mu:
                q = self._hold_front_locked()
                if q is None:
                    return
                req = q[0]
                if req.state == CANCELLED:
                    q.popleft()
                    req._finish(CANCELLED)
                    self._cancelled_n += 1
                    _cancelled.inc()
                    continue
                if req.deadline_at is not None and \
                        time.perf_counter() > req.deadline_at:
                    # spent budget: fail BEFORE wasting a prefill
                    q.popleft()
                    _queue_depth.set(self._hold_len_locked())
                    self._deadline_expired_n += 1
                    _deadline_expired.inc()
                    self._finalize_locked(
                        req, FAILED,
                        'DeadlineExceededError: expired before dispatch')
                    continue
                remaining = req.max_new_tokens - len(req.tokens)
                if remaining <= 0:    # failover landed exactly at budget
                    q.popleft()
                    self._finalize_locked(req, DONE)
                    continue
                rep = self._pick_locked(req)
                if rep is None:
                    return            # no eligible replica right now
                q.popleft()
                _queue_depth.set(self._hold_len_locked())
                req.replica = rep.endpoint
                req.base = len(req.tokens)
                req.rid = '%s/%d/%d' % (self._nonce, req.id,
                                        req.segment)
                rep.active[req.id] = req
                # the progress clock starts NOW, covering the submit
                # RPC itself: a replica that accepts the connection and
                # never replies is as gray as one that stops decoding
                req.last_progress_at = time.perf_counter()
                if req.session is not None:
                    self._sessions[req.session] = rep.endpoint
                prompt = req.prompt + req.tokens
                rid, mnt, eos = req.rid, remaining, req.eos_id
                prio = req.priority
                meta = {'rid': rid, 'mnt': mnt, 'eos': eos,
                        'prio': prio}
                if req.deadline_at is not None:
                    # forward only the REMAINING budget — elapsed time
                    # (queueing, earlier segments) is never refunded
                    meta['deadline_ms'] = max(
                        1.0, (req.deadline_at - req.last_progress_at)
                        * 1000.0)
                # disaggregated dispatch: name a prefill peer so the
                # decode replica pulls the prompt's pages instead of
                # prefilling (serving/disagg.py). No healthy prefill
                # tier -> key absent -> today's colocated path.
                pf = self._pick_prefill_locked(req)
                if pf is not None:
                    meta['prefill_from'] = pf.endpoint
                if rep.max_len is not None and len(prompt) > rep.max_len:
                    # a failover prefix past the context window cannot
                    # be re-prefilled bit-exactly (ring slide)
                    rep.active.pop(req.id, None)
                    self._finalize_locked(
                        req, FAILED,
                        'failover prefix %d exceeds replica max_len %d'
                        % (len(prompt), rep.max_len))
                    continue
            try:
                rep.client.call(wire.SRV_SUBMIT, meta,
                                value=np.asarray(prompt, np.int64))
            except _ReplicaError as e:
                with self._mu:
                    if rep.active.get(req.id) is not req:
                        # superseded while the submit was in flight (a
                        # hedge won, or the watchdog failed it over):
                        # this reply belongs to a dead dispatch
                        continue
                    rep.active.pop(req.id, None)
                    req.replica = None
                    if e.retryable:   # full / draining: try elsewhere
                        rep.hold_until = time.monotonic() + 0.05
                        self._hold_push_locked(req, front=True)
                    else:
                        if 'DeadlineExceeded' in str(e):
                            self._deadline_expired_n += 1
                            _deadline_expired.inc()
                        self._finalize_locked(req, FAILED, str(e))
            except (ConnectionError, OSError):
                self._on_replica_down(rep)
            else:
                with self._mu:
                    req.state = RUNNING
                    if req.dispatched_at is None:
                        req.dispatched_at = time.perf_counter()
                        _dispatch_wait.observe(req.dispatched_at
                                               - req.submitted_at)
                    self._dispatches_n += 1
                _dispatches.inc()

    def _pick_locked(self, req, exclude=None):
        now = time.monotonic()
        elig = [r for r in self._reps.values()
                if r.healthy and not r.draining and not r.gray
                and r.role != 'prefill'
                and r.endpoint != exclude
                and now >= r.hold_until
                and len(r.active) < max(1, r.capacity)]
        if not elig:
            return None
        if req.session is not None:
            ep = self._sessions.get(req.session)
            for r in elig:
                if r.endpoint == ep:
                    return r
        return min(elig, key=lambda r: (
            ((len(r.active) + r.queue_depth) / max(1, r.capacity)
             # cache-pressure term (paged replicas report token
             # occupancy): two replicas with equal lane counts tie-break
             # toward the one holding fewer KV tokens, so long streams
             # spread out instead of stacking onto one page pool
             + (r.cache_tokens / r.cache_capacity
                if r.cache_capacity else 0.0)
             # preemption-pressure term: every stream a replica has
             # swapped out is a stream its cache could NOT hold — count
             # it like an active lane so new work flows to replicas
             # that are not already evicting
             + r.preempted_streams / max(1, r.capacity))
            # speculative replicas retire a lane's tokens in fewer
            # steps: divide the load score by the measured tokens per
            # step so a high-accept-rate replica absorbs more streams
            # (neutral 1.0 for plain replicas keeps the old ordering)
            / max(1.0, r.effective_tokens_per_step)
            # prefix-affinity term (FLAGS_fleet_prefix_affinity): the
            # directory says this replica already holds a leading run
            # of the request's page chain — landing there turns the
            # prefill into a PrefixCache hit (or a near-free dedup
            # ship). Subtractive, so a stale directory entry only
            # nudges the ordering and dispatch still falls back to any
            # healthy replica.
            - self._prefix_affinity * self._affinity_locked(req, r),
            r.order))

    def _affinity_locked(self, req, rep):
        """Fraction [0, 1] of the request's full-page hash chain the
        directory believes `rep` holds as a LEADING run (only leading
        pages are adoptable — the chain breaks at the first miss)."""
        if self._prefix_affinity <= 0 or not self._prefix_dir:
            return 0.0
        pt = rep.page_tokens
        if not pt:
            return 0.0
        cache = req._ck_cache
        if cache is None or cache[0] != pt:
            from .paging import chain_keys
            prompt = req.prompt + req.tokens
            req._ck_cache = cache = (
                pt, chain_keys(prompt, pt, limit=len(prompt) - 1))
        keys = cache[1]
        if not keys:
            return 0.0
        matched = 0
        for k in keys:
            if rep.endpoint not in self._prefix_dir.get(k, ()):
                break
            matched += 1
        return matched / len(keys)

    def _pick_prefill_locked(self, req):
        """The prefill-tier replica a dispatch names in
        meta['prefill_from'] — prefix-affine first (the peer that
        already computed this chain ships it from cache), then
        least-loaded. None when no prefill tier is configured or none
        of it is currently trustworthy (the decode replica then
        prefills locally: today's colocated path)."""
        now = time.monotonic()
        elig = [r for r in self._reps.values()
                if r.role == 'prefill' and r.healthy and not r.draining
                and not r.gray and now >= r.hold_until]
        if not elig:
            return None
        return min(elig, key=lambda r: (
            -self._affinity_locked(req, r),
            (len(r.active) + r.queue_depth) / max(1, r.capacity),
            r.order))

    # -- fleet prefix directory (serving/disagg.py) ------------------------
    def _dir_apply_locked(self, rep, health):
        """Fold one replica's SRV_HEALTH prefix/disagg fields into the
        router's view: mirror the counters, then reconcile the
        directory from the replica's own registered/evicted key deltas
        — replica truth, not dispatch bookkeeping."""
        rep.page_tokens = health.get('page_tokens') or rep.page_tokens
        rep.prefix_hits = int(health.get('prefix_hits', 0) or 0)
        rep.prefix_misses = int(health.get('prefix_misses', 0) or 0)
        rep.prefix_entries = int(health.get('prefix_entries', 0) or 0)
        rep.prefix_pages = int(health.get('prefix_pages', 0) or 0)
        rep.pages_shipped = int(health.get('pages_shipped', 0) or 0)
        rep.ship_bytes = int(health.get('ship_bytes', 0) or 0)
        rep.pages_installed = int(health.get('pages_installed', 0) or 0)
        rep.pages_deduped = int(health.get('pages_deduped', 0) or 0)
        rep.local_reprefills = int(health.get('local_reprefills', 0)
                                   or 0)
        ep = rep.endpoint
        for k in health.get('prefix_new') or ():
            self._prefix_dir.setdefault(str(k), set()).add(ep)
        for k in health.get('prefix_evicted') or ():
            eps = self._prefix_dir.get(str(k))
            if eps is not None:
                eps.discard(ep)
                if not eps:
                    del self._prefix_dir[str(k)]

    def _dir_forget_locked(self, endpoint):
        """Drop every directory entry naming `endpoint` (replica death,
        gray-mark, removal): its pages may be gone, and a stale entry
        must only ever cost a dedup round trip, never a dispatch."""
        for k in list(self._prefix_dir):
            eps = self._prefix_dir[k]
            eps.discard(endpoint)
            if not eps:
                del self._prefix_dir[k]

    def _poll_one(self, rep):
        with self._mu:
            pairs = {r.rid: (r, False) for r in rep.active.values()}
            for r in rep.hedges.values():
                pairs[r.hedge_rid] = (r, True)
        if not pairs:
            return
        try:
            reply = rep.client.call(wire.SRV_POLL,
                                    {'rids': list(pairs)})
        except (ConnectionError, OSError):
            self._on_replica_down(rep)
            return
        except _ReplicaError:
            return
        streams = reply.get('streams', {})
        for rid, (req, hedged) in pairs.items():
            st = streams.get(rid)
            if st is not None:
                self._apply_poll(rep, req, st, hedged=hedged)

    def _apply_poll(self, rep, req, st, hedged=False):
        state = st.get('state')
        toks = [int(t) for t in st.get('tokens', ())]
        with self._mu:
            if req.state not in (QUEUED, RUNNING):
                (rep.hedges if hedged else rep.active).pop(req.id, None)
                return
            if hedged:
                if rep.hedges.get(req.id) is not req:
                    return            # hedge already resolved away
                if state == 'UNKNOWN' or state in (CANCELLED, FAILED):
                    # the duplicate died (replica restart, cache
                    # pressure, its own deadline): drop it quietly —
                    # the primary stream is untouched
                    self._drop_hedge_locked(req, cancel=False)
                    return
                if not toks:
                    return            # duplicate has nothing yet
                # first token came from the DUPLICATE: the hedge wins.
                # Promote it to primary — queue a cancel for the slow
                # copy, rebind the stream — then fall through to plain
                # token accounting. Greedy determinism makes both
                # copies emit identical tokens, so whichever side wins
                # the stream is the same.
                prim = self._reps.get(req.replica)
                if prim is not None and prim.active.get(req.id) is req:
                    prim.active.pop(req.id, None)
                    self._cancelq.append((req.replica, req.rid))
                rep.hedges.pop(req.id, None)
                req.replica = rep.endpoint
                req.rid = req.hedge_rid
                req.hedge_ep = req.hedge_rid = None
                rep.active[req.id] = req
                if req.session is not None:
                    self._sessions[req.session] = rep.endpoint
                self._hedge_wins_n += 1
                _hedge_wins.inc()
            elif rep.active.get(req.id) is not req:
                return                # already failed over elsewhere
            if state == 'UNKNOWN':
                # replica restarted underneath its streams: same
                # failover as a dead connection, per stream
                rep.active.pop(req.id, None)
                self._requeue_locked(req)
                return
            old = len(req.tokens)
            if toks:
                req.tokens[req.base:] = toks
            new = len(req.tokens)
            if new > old:
                req.last_progress_at = time.perf_counter()
                if req.hedge_ep is not None:
                    # the PRIMARY produced the first token: its
                    # duplicate loses and is cancelled
                    self._drop_hedge_locked(req)
                self._tokens_n += new - old
                _tokens_out.inc(new - old)
                if req.first_token_at is None:
                    req.first_token_at = time.perf_counter()
                    ttft = req.first_token_at - req.submitted_at
                    self._ttft_local.observe(ttft)
                    _ttft.observe(ttft)
            shed_budget = int(get_flag('fleet_cache_shed_budget'))
            if state == FAILED and req.cache_sheds < shed_budget and \
                    'CacheExhausted' in (st.get('error') or ''):
                # typed retryable shed (COVERAGE divergence 8): the
                # replica's page pool was dry, not the stream's fault —
                # requeue onto a (hopefully cooler) replica with a brief
                # hold on this one; FLAGS_fleet_cache_shed_budget bounds
                # the livelock when the whole fleet is saturated
                rep.active.pop(req.id, None)
                rep.hold_until = time.monotonic() + 0.05
                req.cache_sheds += 1
                self._cache_sheds_n += 1
                _cache_sheds.inc()
                self._requeue_locked(req)
                return
            if state in (DONE, CANCELLED, FAILED):
                rep.active.pop(req.id, None)
                self._drop_hedge_locked(req)
                if state == FAILED and \
                        'DeadlineExceeded' in (st.get('error') or ''):
                    self._deadline_expired_n += 1
                    _deadline_expired.inc()
                self._finalize_locked(req, state, st.get('error'))

    def _finalize_locked(self, req, state, error=None):
        req._finish(state, error)
        if state == DONE:
            self._completed_n += 1
            _completed.inc()
        elif state == CANCELLED:
            self._cancelled_n += 1
            _cancelled.inc()
        else:
            self._failed_n += 1
            _failed.inc()

    def _drop_hedge_locked(self, req, cancel=True):
        """Forget a stream's pending duplicate (under _mu). cancel=True
        queues the loser's rid for a best-effort SRV_CANCEL by the
        pump — never inline, so a slow loser cannot block the caller."""
        ep, rid = req.hedge_ep, req.hedge_rid
        req.hedge_ep = req.hedge_rid = None
        if ep is None:
            return
        hrep = self._reps.get(ep)
        if hrep is not None:
            hrep.hedges.pop(req.id, None)
        if cancel and rid is not None:
            self._cancelq.append((ep, rid))

    def _requeue_locked(self, req):
        if req.state not in (QUEUED, RUNNING):
            return
        self._drop_hedge_locked(req)
        req.segment += 1
        req.replica = None
        req.state = QUEUED
        # front of the request's OWN tier: a failover victim already
        # waited its turn once — but it must not cut ahead of a higher
        # tier, nor be buried behind its own tier's backlog
        self._hold_push_locked(req, front=True)
        self._failovers_n += 1
        _failovers.inc()

    def _on_replica_down(self, rep):
        with self._mu:
            if rep is not self._reps.get(rep.endpoint):
                return                # already removed
            was_live = rep.healthy or bool(rep.active)
            rep.healthy = False
            rep.fails = max(rep.fails, self._probe_fail_threshold)
            victims = list(rep.active.values())
            rep.active.clear()
            # duplicates hedged ONTO the dead replica die with it; their
            # primaries are untouched
            for req in list(rep.hedges.values()):
                self._drop_hedge_locked(req, cancel=False)
            for s, ep in list(self._sessions.items()):
                if ep == rep.endpoint:
                    del self._sessions[s]
            for req in victims:
                self._requeue_locked(req)
            self._dir_forget_locked(rep.endpoint)
        rep.client.close()
        if was_live:
            self._deaths_inc(rep, len(victims))

    def _deaths_inc(self, rep, n_streams):
        _deaths.inc()
        _trace.event('fleet.replica_down', endpoint=rep.endpoint,
                     failover_streams=n_streams)

    # -- control: probes, admission, autoscale, auto-deploy ----------------
    def _control_loop(self):
        while not self._stop_evt.is_set():
            try:
                self._control_once()
            except Exception as e:    # noqa: BLE001 — router survives
                _trace.event('fleet.control_error', error=repr(e))
            self._stop_evt.wait(self._probe_secs)

    def _control_once(self):
        for rep in list(self._reps.values()):
            t0 = time.perf_counter()
            try:
                # the dedicated probe connection with its OWN short
                # timeout (FLAGS_fleet_probe_timeout): liveness checks
                # must stay cheap and honest while the data connection
                # is wedged behind a gray stall
                h = rep.probe.call(wire.SRV_HEALTH, {},
                                   timeout=self._probe_timeout)
            except (ConnectionError, OSError, _ReplicaError):
                with self._mu:
                    rep.fails += 1
                    rep.clean_probes = 0
                    dead = (rep.fails >= self._probe_fail_threshold
                            and (rep.healthy or rep.active))
                if dead:
                    self._on_replica_down(rep)
                continue
            lat = time.perf_counter() - t0
            _probe_latency.observe(lat)
            with self._mu:
                self._probe_ok_locked(rep, lat)
                rep.fails = 0
                rep.queue_depth = int(h.get('queue_depth', 0))
                rep.capacity = int(h.get('capacity') or rep.capacity)
                rep.max_len = h.get('max_len', rep.max_len)
                rep.param_version = h.get('param_version')
                rep.cache_tokens = int(h.get('cache_tokens', 0))
                rep.cache_capacity = (h.get('cache_capacity')
                                      or rep.cache_capacity)
                eff = h.get('effective_tokens_per_step')
                # a replica that has not decoded yet reports 0.0 — keep
                # the neutral weight until speculation actually engages
                rep.effective_tokens_per_step = (float(eff)
                                                 if eff else 1.0)
                rep.spec_accept_rate = h.get('spec_accept_rate')
                rep.preemptions = int(h.get('preemptions', 0) or 0)
                rep.preempted_streams = int(
                    h.get('preempted_streams', 0) or 0)
                rep.mesh_shape = h.get('mesh_shape', '') or ''
                rep.mesh_devices = int(h.get('mesh_devices', 1) or 1)
                self._dir_apply_locked(rep, h)
                rep.healthy = True
        with self._mu:
            shipped = sum(r.pages_shipped for r in self._reps.values())
            sbytes = sum(r.ship_bytes for r in self._reps.values())
            # hit rate over the DECODE tier only: the prefill tier's
            # cache exists to feed ships, and counting its warm hits
            # would flatter the number the bench gates on
            hits = sum(r.prefix_hits for r in self._reps.values()
                       if r.role != 'prefill')
            misses = sum(r.prefix_misses for r in self._reps.values()
                         if r.role != 'prefill')
        _pages_shipped_g.set(shipped)
        _ship_bytes_g.set(sbytes)
        _prefix_hit_rate_g.set(hits / (hits + misses)
                               if hits + misses else 0.0)
        self._watchdog_tick()
        self._hedge_tick()
        now = time.monotonic()
        snap = self.admission_snapshot()
        dt = (now - self._prev_snap_t) if self._prev_snap_t else None
        self._evaluate_admission(snap, dt)
        if self._autoscaler is not None:
            self._autoscaler.tick(self, snap, self._prev_snap, dt)
        self._prev_snap, self._prev_snap_t = snap, now
        gauges = snap['gauges']
        _active_streams.set(gauges['fleet.active_streams'])
        _replicas_healthy.set(gauges['fleet.replicas_healthy'])
        _replicas_total.set(len(self._reps))

    # -- gray-failure machinery --------------------------------------------
    def _probe_ok_locked(self, rep, lat):
        """Probe-latency circuit breaker + half-open probation. A probe
        that answered but took far longer than the replica's own EWMA
        (and a floor of half the probe timeout — cold-start latency
        must not poison the baseline) is a STRIKE; three consecutive
        strikes gray-mark without waiting for a stream to starve. A
        gray replica rejoins after FLAGS_fleet_gray_probes consecutive
        clean probes. The strike path rides the watchdog arm
        (FLAGS_fleet_progress_timeout_secs > 0): an unarmed router
        must never gray-mark — a host-wide compile or GC pause slows
        probes 4x without the replica being at fault. The EWMA warms
        either way so arming starts from a real baseline."""
        if rep.probe_ewma is None:
            rep.probe_ewma = lat
        slow = lat > max(4.0 * rep.probe_ewma,
                         0.5 * self._probe_timeout)
        rep.probe_ewma += 0.2 * (lat - rep.probe_ewma)
        if self._progress_timeout <= 0:
            return
        if rep.gray:
            if slow:
                rep.clean_probes = 0
            else:
                rep.clean_probes += 1
                if rep.clean_probes >= self._gray_probes:
                    rep.gray = False
                    rep.strikes = 0
                    rep.clean_probes = 0
                    _trace.event('fleet.gray_rejoin',
                                 endpoint=rep.endpoint)
            return
        if slow:
            rep.strikes += 1
            if rep.strikes >= 3:
                self._gray_mark_locked(
                    rep, 'probe latency %.3fs vs ewma %.3fs (3 strikes)'
                    % (lat, rep.probe_ewma))
        else:
            rep.strikes = 0

    def _gray_mark_locked(self, rep, reason):
        """Stop trusting a live-but-stalled replica: fail its streams
        over (the same bit-exact re-prefill path a death takes), drop
        duplicates hedged onto it, and demote it to probe-only
        probation. Its data connection is interrupted by the CALLER
        (outside _mu) so a wedged pump/poller call surfaces now
        instead of after the full RPC timeout."""
        fresh = not rep.gray
        rep.gray = True
        rep.strikes = 0
        rep.clean_probes = 0
        victims = list(rep.active.values())
        rep.active.clear()
        for req in list(rep.hedges.values()):
            self._drop_hedge_locked(req, cancel=False)
        for s, ep in list(self._sessions.items()):
            if ep == rep.endpoint:
                del self._sessions[s]
        for req in victims:
            self._requeue_locked(req)
        self._dir_forget_locked(rep.endpoint)
        if fresh:
            self._gray_marks_n += 1
            _gray_marks.inc()
            _trace.event('fleet.gray_mark', endpoint=rep.endpoint,
                         reason=reason, failover_streams=len(victims))

    def _watchdog_tick(self):
        """The progress watchdog — the anti-gray-failure check health
        probes cannot make: a replica is only as healthy as its
        streams. No token growth (and no reply to an in-flight RPC)
        within FLAGS_fleet_progress_timeout_secs gray-marks the
        replica even though SRV_HEALTH still answers."""
        horizon = self._progress_timeout
        if horizon <= 0:
            return                    # watchdog disabled (default)
        now = time.perf_counter()
        for rep in list(self._reps.values()):
            stuck = None
            with self._mu:
                if self._reps.get(rep.endpoint) is not rep or rep.gray:
                    continue
                inflight = rep.client.inflight_since
                if inflight is not None and now - inflight > horizon:
                    stuck = 'rpc in flight %.2fs' % (now - inflight)
                else:
                    for r in rep.active.values():
                        lp = r.last_progress_at
                        if lp is not None and now - lp > horizon:
                            stuck = ('stream %d no progress %.2fs'
                                     % (r.id, now - lp))
                            break
                if stuck:
                    self._gray_mark_locked(rep, stuck)
            if stuck:
                rep.client.interrupt()

    def _hedge_tick(self):
        """Hedged dispatch for the slow-prefill tail: a RUNNING stream
        with no first token FLAGS_fleet_hedge_ms after dispatch is
        duplicated to a second replica; whichever copy produces a
        token first becomes the stream, the loser is SRV_CANCELled.
        Greedy determinism makes both copies identical, so hedging
        never changes output — it only moves the tail."""
        hedge_ms = self._hedge_ms
        if hedge_ms <= 0:
            return                    # hedging disabled (default)
        now = time.perf_counter()
        jobs = []
        with self._mu:
            for rep in list(self._reps.values()):
                for req in list(rep.active.values()):
                    # anything registered in rep.active is dispatched
                    # (or dispatchING — a stream whose SRV_SUBMIT is
                    # itself wedged on a gray replica is still QUEUED
                    # and needs the hedge MOST)
                    if req.state not in (QUEUED, RUNNING) \
                            or req.hedge_ep is not None:
                        continue
                    if len(req.tokens) > req.base:
                        continue      # first token already landed
                    lp = req.last_progress_at
                    if lp is None or (now - lp) * 1000.0 < hedge_ms:
                        continue
                    second = self._pick_locked(req,
                                               exclude=rep.endpoint)
                    if second is None:
                        continue
                    rid = '%s/%d/%dh' % (self._nonce, req.id,
                                         req.segment)
                    req.hedge_ep = second.endpoint
                    req.hedge_rid = rid
                    second.hedges[req.id] = req
                    meta = {'rid': rid,
                            'mnt': req.max_new_tokens - len(req.tokens),
                            'eos': req.eos_id, 'prio': req.priority}
                    if req.deadline_at is not None:
                        meta['deadline_ms'] = max(
                            1.0, (req.deadline_at - now) * 1000.0)
                    prompt = np.asarray(req.prompt + req.tokens,
                                        np.int64)
                    jobs.append((req, second, meta, prompt))
                    self._hedges_n += 1
                    _hedges.inc()
        for req, second, meta, prompt in jobs:
            try:
                second.client.call(wire.SRV_SUBMIT, meta, value=prompt)
            except _ReplicaError:
                with self._mu:
                    self._drop_hedge_locked(req, cancel=False)
            except (ConnectionError, OSError):
                with self._mu:
                    self._drop_hedge_locked(req, cancel=False)
                self._on_replica_down(second)

    def _evaluate_admission(self, snap, dt):
        breached = None
        for rule in self._admission_rules:
            out = rule.evaluate(snap, prev=self._prev_snap, dt=dt)
            if out is not None and out[1]:
                breached = rule.name
        with self._mu:
            if breached is not None:
                self._breach_streak += 1
                self._breach_rule = breached
            else:
                self._breach_streak = 0
            shed = self._breach_streak >= self._shed_consecutive
            flipped = shed != self._shedding
            self._shedding = shed
        if flipped:
            _shedding_g.set(1.0 if shed else 0.0)
            _trace.event('fleet.shed_on' if shed else 'fleet.shed_off',
                         rule=self._breach_rule or '',
                         streak=self._breach_streak)

    # -- rolling deploys ---------------------------------------------------
    def rolling_deploy(self, min_version=None, timeout=None):
        """One replica at a time: drain -> refresh -> health-check ->
        rejoin. Returns {endpoint: installed version} (None for a
        replica that was down and skipped). Raises FleetDeployError
        when a step misses its per-replica deadline — the replica is
        un-drained and keeps serving its old verified weights; already-
        deployed replicas keep the new ones (versions are
        forward-compatible by the PR-9 contract)."""
        timeout = float(timeout if timeout is not None
                        else get_flag('fleet_deploy_timeout'))
        results = {}
        with _trace.span('fleet.deploy', kind='fleet',
                         replicas=len(self._reps),
                         min_version=min_version or 0):
            for ep in self.replicas():
                with self._mu:
                    rep = self._reps.get(ep)
                    if rep is None or not rep.healthy:
                        results[ep] = None
                        continue
                    rep.draining = True
                try:
                    results[ep] = self._deploy_one(rep, min_version,
                                                   timeout)
                finally:
                    with self._mu:
                        rep.draining = False
                    try:
                        rep.client.call(wire.SRV_DRAIN, {'on': False})
                    except (ConnectionError, OSError, _ReplicaError):
                        pass
        self._deploys_n += 1
        _deploys.inc()
        return results

    def _deploy_one(self, rep, min_version, timeout):
        deadline = time.monotonic() + timeout
        try:
            rep.client.call(wire.SRV_DRAIN, {'on': True})
        except (ConnectionError, OSError):
            self._on_replica_down(rep)
            return None
        # drain ordering: lowest-tier streams fail over to another
        # replica right away (their prefix re-prefills elsewhere,
        # bit-exact), so the wait below covers only the higher-tier
        # streams finishing in place — the most important streams are
        # the last ones a deploy disturbs
        with self._mu:
            for req in list(rep.active.values()):
                if req.priority <= 0:
                    rep.active.pop(req.id, None)
                    self._requeue_locked(req)
        with _trace.span('fleet.drain', kind='fleet',
                         endpoint=rep.endpoint):
            while True:
                with self._mu:
                    n = len(rep.active)
                if not n:
                    break
                if time.monotonic() >= deadline:
                    raise FleetDeployError(
                        'deploy drain of %s timed out with %d streams '
                        'in flight' % (rep.endpoint, n))
                time.sleep(0.01)
        version = None
        while True:
            try:
                r = rep.client.call(wire.SRV_REFRESH, {},
                                    timeout=max(5.0, timeout))
                version = int(r['param_version'])
                if min_version is None or version >= int(min_version):
                    break
            except _ReplicaError as e:
                if not e.retryable:
                    raise FleetDeployError(
                        'refresh of %s failed: %s' % (rep.endpoint, e))
            except (ConnectionError, OSError):
                self._on_replica_down(rep)
                return None
            if time.monotonic() >= deadline:
                raise FleetDeployError(
                    'refresh of %s did not reach version %r in %.1fs '
                    '(installed %r)' % (rep.endpoint, min_version,
                                        timeout, version))
            time.sleep(0.05)
        # the rejoin health check: the replica must REPORT the version
        # it claims it installed before it takes traffic again
        try:
            h = rep.client.call(wire.SRV_HEALTH, {})
        except (ConnectionError, OSError):
            self._on_replica_down(rep)
            return None
        if h.get('param_version') != version:
            raise FleetDeployError(
                'replica %s installed version %d but reports %r'
                % (rep.endpoint, version, h.get('param_version')))
        with self._mu:
            rep.param_version = version
        return version

    def published_version(self):
        """max published param version across the pserver fleet (None
        when unreachable / no pservers configured)."""
        from ..distributed import rpc
        out = []
        for ep in self._pservers:
            try:
                r = rpc.get_serving_client(
                    ep, self._subscriber_id).get_version()
                out.append(int(r.get('version', 0)))
            except (ConnectionError, OSError, RuntimeError):
                continue
        return max(out) if out else None

    def enable_rolling_deploys(self, poll_secs=1.0):
        """Watch the pservers' published version; on a bump, run a
        rolling deploy targeting it. Requires pservers=[...]."""
        if not self._pservers:
            raise ValueError('enable_rolling_deploys needs pservers')
        t = threading.Thread(target=self._deploy_watch,
                             args=(float(poll_secs),),
                             name='fleet-deploy-watch', daemon=True)
        self._threads.append(t)
        t.start()
        return t

    def _deploy_watch(self, poll_secs):
        while not self._stop_evt.is_set():
            try:
                v = self.published_version()
                if v is not None and v > self._deployed_version:
                    self.rolling_deploy(min_version=v)
                    self._deployed_version = v
            except FleetDeployError as e:
                _trace.event('fleet.deploy_error', error=str(e))
            except Exception as e:    # noqa: BLE001 — router survives
                _trace.event('fleet.control_error', error=repr(e))
            self._stop_evt.wait(poll_secs)
