"""Initializers: emit init ops into the startup program
(reference python/paddle/fluid/initializer.py).

Each initializer appends one op (fill_constant / *_random) to the startup
block for the parameter; the startup program is then executed once, jitted as
a whole -- so all random init happens on-device from a single threaded PRNG
key rather than the reference's per-op seed attrs.
"""
from __future__ import annotations

import numpy as np

__all__ = ['Constant', 'Uniform', 'Normal', 'TruncatedNormal', 'Xavier',
           'MSRA', 'Bilinear', 'NumpyArrayInitializer', 'Initializer',
           'force_init_on_cpu', 'init_on_cpu',
           'ConstantInitializer', 'UniformInitializer',
           'NormalInitializer', 'XavierInitializer',
           'BilinearInitializer', 'MSRAInitializer']


import contextlib

_force_init_on_cpu_ = False


def force_init_on_cpu():
    return _force_init_on_cpu_


@contextlib.contextmanager
def init_on_cpu():
    global _force_init_on_cpu_
    prev = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = prev


class Initializer(object):
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _compute_fans(var):
        shape = var.shape
        if len(shape) < 2:
            fan_in = fan_out = int(shape[0]) if shape else 1
        else:
            receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
            fan_in = int(shape[1]) * receptive
            fan_out = int(shape[0]) * receptive
        return fan_in, fan_out


class Constant(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type='fill_constant', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'value': float(self.value)})


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type='uniform_random', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'min': self.low, 'max': self.high, 'seed': self.seed})


class Normal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='gaussian_random', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self.loc, 'std': self.scale, 'seed': self.seed})


class TruncatedNormal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='truncated_gaussian_random', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self.loc, 'std': self.scale, 'seed': self.seed})


class Xavier(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.fan_out = fan_out
        self.seed = seed

    def __call__(self, var, block):
        f_in, f_out = self._compute_fans(var)
        fan_in = f_in if self.fan_in is None else self.fan_in
        fan_out = f_out if self.fan_out is None else self.fan_out
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
            return Uniform(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return Normal(0.0, std, self.seed)(var, block)


class MSRA(Initializer):
    """He/Kaiming init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        f_in, _ = self._compute_fans(var)
        fan_in = f_in if self.fan_in is None else self.fan_in
        if self.uniform:
            limit = float(np.sqrt(6.0 / fan_in))
            return Uniform(-limit, limit, self.seed)(var, block)
        std = float(np.sqrt(2.0 / fan_in))
        return Normal(0.0, std, self.seed)(var, block)


class Bilinear(Initializer):
    """Bilinear upsample kernel init for conv_transpose (reference
    initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError('Bilinear init needs a 4-D filter var')
        weight = np.zeros(shape, dtype='float32')
        kh, kw = shape[2], shape[3]
        f = np.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape[2:])):
            x = i % kw
            y = (i // kw) % kh
            v = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[..., y, x] = v
        return block.append_op(
            type='assign_value', outputs={'Out': var},
            attrs={'shape': list(shape), 'dtype': var.dtype,
                   'values': weight.tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type='assign_value', outputs={'Out': var},
            attrs={'shape': list(self.value.shape), 'dtype': var.dtype,
                   'values': self.value.tolist()})


# long-form aliases the reference exports beside the short names
# (reference initializer.py __all__)
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
XavierInitializer = Xavier
BilinearInitializer = Bilinear
MSRAInitializer = MSRA
