"""Device-side performance observatory: compile/JIT telemetry, live
MFU, and HBM watermarks.

The cluster half of obs/ (PR 4) watches the wire; this module watches
the device. The Executor's JIT pipeline reports into it at two points:

- compile time (`record_compile` / `jit_cache_miss` / `jit_cache_hit`)
  — every lazily-compiled device segment stamps an
  `xla.compile_latency` observation and bumps the
  `xla.jit_cache.{hit,miss}` counters, and its analytical cost
  (FLOPs / bytes accessed, from jax's compiled cost analysis) is
  accumulated onto the owning PreparedProgram so step attribution
  below has a work model to divide by.
- step time (`step_begin` / `step_end`) — wall latency of each
  `Executor.run` call lands in the `perf.step_latency` histogram, and
  combined with the compile-time FLOP count yields
  `perf.achieved_tflops` and `perf.mfu` gauges. Timing follows the
  PERF.md discipline: a `return_numpy=True` fetch has already
  synchronized through the host transfer, otherwise we
  `block_until_ready` the fetched arrays first (disable with
  `FLAGS_perf_sync_steps=0` on the remoted transport, where
  block_until_ready is documented-unreliable and throughput should be
  measured over an async window instead).

Every hook is a no-op while telemetry is disabled — same
one-global-bool fast path as the rest of the registry — so the
executor hot loop pays nothing by default. The one deliberate
exception: capturing a segment's cost analysis requires a second
lower+compile of the already-jitted function (an explicit
lower().compile() does not warm jax's call cache), which doubles a
once-per-program cost. That is why it is gated on telemetry being
enabled rather than free-running.

MFU needs a peak-FLOPs denominator: on TPU it is looked up from the
device kind (same table as bench.py); elsewhere — and in CPU tests —
set `FLAGS_perf_peak_tflops` to pin it explicitly.

HBM gauges (`hbm.bytes_in_use`, `hbm.peak_bytes`, `hbm.bytes_limit`,
`hbm.scope_bytes`, `hbm.watermark_bytes`) are refreshed on every
step_end from memory.hbm_snapshot(); on backends without PJRT memory
stats (CPU) bytes_in_use falls back to the scope footprint so the
series stay live in tests. `hbm.watermark_bytes` is a process-local
high-water mark that survives allocator-level peak resets.
"""
from __future__ import annotations

import time

from . import telemetry, trace
from .. import flags

__all__ = ['enabled', 'step_begin', 'step_end', 'jit_cache_hit',
           'jit_cache_miss', 'record_compile', 'segment_cost',
           'device_peak_flops', 'update_hbm', 'compile_span']

# --- instruments (registered at import; zero until enabled) ---------
_compile_latency = telemetry.histogram('xla.compile_latency')
_jit_hits = telemetry.counter('xla.jit_cache.hit')
_jit_misses = telemetry.counter('xla.jit_cache.miss')
_step_latency = telemetry.histogram('perf.step_latency')
_steps = telemetry.counter('perf.steps')
_mfu = telemetry.gauge('perf.mfu')
_achieved_tflops = telemetry.gauge('perf.achieved_tflops')
_hbm_in_use = telemetry.gauge('hbm.bytes_in_use')
_hbm_peak = telemetry.gauge('hbm.peak_bytes')
_hbm_limit = telemetry.gauge('hbm.bytes_limit')
_hbm_scope = telemetry.gauge('hbm.scope_bytes')
_hbm_watermark = telemetry.gauge('hbm.watermark_bytes')

_watermark = 0          # process-local high-water of bytes_in_use
_slo_started = False    # lazy FLAGS_slo_rules watchdog, armed once

# Dense peak bf16 FLOP/s by device kind prefix (same table bench.py
# uses for its MFU math; longest-prefix match on device.device_kind).
_PEAK_BF16 = {
    'TPU v4': 275e12,
    'TPU v5 lite': 197e12,
    'TPU v5': 459e12,
    'TPU v6 lite': 918e12,
}


def enabled():
    return telemetry._enabled


def device_peak_flops(device=None):
    """Peak dense bf16 FLOP/s for MFU attribution: the
    FLAGS_perf_peak_tflops override if set (TFLOP/s; the only way to
    get a nonzero MFU on CPU), else the device-kind table, else 0.0
    (MFU gauge stays unset)."""
    override = float(flags.get_flag('perf_peak_tflops', 0.0))
    if override > 0.0:
        return override * 1e12
    if device is None:
        return 0.0
    kind = getattr(device, 'device_kind', '') or ''
    best, best_len = 0.0, -1
    for prefix, peak in _PEAK_BF16.items():
        if kind.startswith(prefix) and len(prefix) > best_len:
            best, best_len = peak, len(prefix)
    return best


# --- compile-time hooks ---------------------------------------------

def jit_cache_hit():
    _jit_hits.inc()


def jit_cache_miss():
    _jit_misses.inc()


def compile_span(fingerprint, segment, n_ops):
    """Trace span wrapping a device segment's first (compiling) call.
    The program fingerprint tag lets a timeline reader join the span
    to the jit_cache series and to rerun-vs-rerun comparisons."""
    return trace.span('xla.compile', fingerprint=fingerprint,
                      segment=segment, n_ops=n_ops)


def record_compile(latency_s, flops=0.0, bytes_accessed=0.0):
    _compile_latency.observe(latency_s)


def segment_cost(jitted, arg_struct):
    """Analytical (flops, bytes_accessed) for a jitted segment via the
    XLA cost model. Requires a fresh lower+compile (jax's jit call
    cache is not warmed by an explicit .lower().compile(), so this is
    a duplicated compile — acceptable once per segment when telemetry
    is on). Returns (0.0, 0.0) on any backend that can't answer."""
    try:
        cost = jitted.lower(*arg_struct).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get('flops', 0.0) or 0.0)
        nbytes = float(cost.get('bytes accessed', 0.0) or 0.0)
        return (max(flops, 0.0), max(nbytes, 0.0))
    except Exception:
        return (0.0, 0.0)


def pallas_extra_flops():
    """Drain trace-time extra-work notes from the Pallas kernels.

    XLA's cost model cannot see inside a Pallas custom call, so the
    flash segment is priced by the analytical 2-matmul attention model.
    Arms that execute MORE than that model (the twopass forward's
    second QK sweep) note the surplus at trace time; the executor
    drains it here right after the compiling call and folds it into
    the segment's cost_flops so live MFU divides by work that actually
    ran. Granularity is once-per-trace: a second program hitting the
    same inner-jit cache contributes nothing new (and needs nothing
    new — cost_flops is per-prepared-program, priced at its own
    compile). Draining is destructive; callers that only want to
    discard stale notes call this and ignore the return."""
    try:
        from paddle_tpu.pallas import flash_attention as _fa
        return float(_fa.take_extra_flops())
    except Exception:
        return 0.0


# --- step-time hooks ------------------------------------------------

def step_begin():
    """Start-of-run timestamp, or None when telemetry is off (the
    executor passes the None straight back to step_end's guard)."""
    if not telemetry._enabled:
        return None
    return time.perf_counter()


def step_end(t0, prepared=None, device=None, scope=None, sync=None):
    """Close out one Executor.run: observe step latency, derive
    achieved TFLOP/s + MFU from the prepared program's compile-time
    cost, refresh the hbm.* gauges, and (once) arm the FLAGS_slo_rules
    watchdog.

    `sync` is the fetched result list when the caller did NOT request
    numpy (so the timer must block on device completion first); None
    means the host fetch already synchronized."""
    if t0 is None or not telemetry._enabled:
        return
    if sync is not None and flags.get_flag('perf_sync_steps', True):
        try:
            import jax
            jax.block_until_ready(
                [r for r in sync if r is not None
                 and hasattr(r, 'block_until_ready')])
        except Exception:
            pass
    dt = time.perf_counter() - t0
    _step_latency.observe(dt)
    _steps.inc()
    flops = float(getattr(prepared, 'cost_flops', 0.0) or 0.0)
    if dt > 0.0 and flops > 0.0:
        achieved = flops / dt
        _achieved_tflops.set(achieved / 1e12)
        peak = device_peak_flops(device)
        if peak > 0.0:
            _mfu.set(achieved / peak)
    update_hbm(device=device, scope=scope)
    _maybe_start_slo()


def update_hbm(device=None, scope=None):
    """Export memory.hbm_snapshot() as gauges + the process-local
    watermark. Callable standalone (bench_suite stamps it between
    steps of hand-rolled loops)."""
    global _watermark
    if not telemetry._enabled:
        return
    from .. import memory
    try:
        snap = memory.hbm_snapshot(device=device, scope=scope)
    except Exception:
        return
    _hbm_in_use.set(snap['bytes_in_use'])
    _hbm_peak.set(snap['peak_bytes'])
    _hbm_limit.set(snap['bytes_limit'])
    _hbm_scope.set(snap['scope_bytes'])
    if snap['bytes_in_use'] > _watermark:
        _watermark = snap['bytes_in_use']
    if snap['peak_bytes'] > _watermark:
        _watermark = snap['peak_bytes']
    _hbm_watermark.set(_watermark)


def _maybe_start_slo():
    """First instrumented step arms the declarative SLO watchdog when
    FLAGS_slo_rules is set — training runs get breach events without
    touching the serving engine's explicit start()/stop() wiring."""
    global _slo_started
    if _slo_started:
        return
    _slo_started = True
    if not flags.get_flag('slo_rules', ''):
        return
    from . import slo
    slo.maybe_start_global()


def _reset_for_tests():
    """Zero the module-local state telemetry.reset() can't see."""
    global _watermark, _slo_started
    _watermark = 0
    _slo_started = False
