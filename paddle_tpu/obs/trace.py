"""Cross-process trace spans + the per-process JSONL event log.

Every traced unit of work becomes one JSON record in
`<obs_dir>/events-<role>-<pid>.jsonl`; three record shapes share the
file so one merge produces one timeline (obs/report.py):

  span   {'type':'span','kind':'client'|'server'|'host', 'name',
          'sid','psid', 't0','t1' (unix epoch seconds), 'tid','pid',
          'role', ...attrs}
  fault  {'type':'fault', 't', 'action', ...}      (trainer FaultEvents,
                                                    supervisor restarts)
  mark   {'type':'mark', 't', 'name', ...}         (one-shot milestones)

Propagation: the RPC clients stamp `meta['trace'] = {'sid': ...}` on
each outbound request — an OPTIONAL key in the schemaless JSON meta
dict, so there is no wire-version bump and an untraced (or older) peer
simply ignores it. The server wraps its handler dispatch in a span
carrying the SAME sid, which is how report.py links a client span to
its server handling (flow events) and estimates per-role clock offsets
from request/reply midpoints.

Parent ids come from a thread-local span stack: a client span opened
inside a RecordEvent scope (or any other span) records that scope's
sid as `psid`.
"""
from __future__ import annotations

import binascii
import contextlib
import json
import os
import threading
import time

__all__ = ['span', 'server_span', 'host_span', 'record_span', 'event',
           'wire_trace', 'current_sid', 'new_id', 'enabled', 'enable',
           'disable']

_lock = threading.Lock()
_enabled = False
_file = None
_role = ''
_tls = threading.local()


def new_id():
    return binascii.hexlify(os.urandom(8)).decode()


def enabled():
    return _enabled


def current_sid():
    stack = getattr(_tls, 'stack', None)
    return stack[-1] if stack else None


def _push(sid):
    stack = getattr(_tls, 'stack', None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(sid)


def _pop():
    stack = getattr(_tls, 'stack', None)
    if stack:
        stack.pop()


def _emit(rec):
    rec['role'] = _role
    rec['pid'] = os.getpid()
    line = json.dumps(rec) + '\n'
    with _lock:
        f = _file
        if f is None:
            return
        f.write(line)
        f.flush()


class _Span(object):
    __slots__ = ('sid', 'psid', 'name', 'kind')

    def __init__(self, sid, psid, name, kind):
        self.sid = sid
        self.psid = psid
        self.name = name
        self.kind = kind


@contextlib.contextmanager
def span(name, kind='host', sid=None, **attrs):
    """Timed scope -> one span record; yields the _Span (None when
    tracing is off, so callers can guard their own extra work)."""
    if not _enabled:
        yield None
        return
    sp = _Span(sid or new_id(), current_sid(), name, kind)
    _push(sp.sid)
    t0 = time.time()
    try:
        yield sp
    finally:
        t1 = time.time()
        _pop()
        rec = {'type': 'span', 'kind': kind, 'name': name,
               'sid': sp.sid, 'psid': sp.psid, 't0': t0, 't1': t1,
               'tid': threading.get_ident() & 0xffff}
        rec.update(attrs)
        _emit(rec)


def wire_trace(sp):
    """The meta-dict trace field for an outbound request carrying this
    client span's id — None (field omitted, untraced) when tracing is
    off."""
    if sp is None:
        return None
    return {'sid': sp.sid}


@contextlib.contextmanager
def server_span(name, trace_meta, **attrs):
    """Server-side handler scope. Only records when BOTH this process
    traces and the request carried a trace field: the span re-uses the
    client's sid, which is the whole cross-process correlation."""
    if not _enabled or not isinstance(trace_meta, dict) \
            or 'sid' not in trace_meta:
        yield None
        return
    with span(name, kind='server', sid=str(trace_meta['sid']),
              **attrs) as sp:
        yield sp


def host_span(name, t0, t1, **attrs):
    """Record an already-timed host scope (profiler.RecordEvent routes
    through here so executor segments share the cluster timeline)."""
    if not _enabled:
        return
    rec = {'type': 'span', 'kind': 'host', 'name': name,
           'sid': new_id(), 'psid': current_sid(), 't0': t0, 't1': t1,
           'tid': threading.get_ident() & 0xffff}
    rec.update(attrs)
    _emit(rec)


def record_span(name, kind, sid, t0, t1, **attrs):
    """Record a span whose start and end were observed on DIFFERENT
    threads (the pipelined RPC client: t0 when the submit thread writes
    the request, t1 when the reader thread matches the reply) — a
    contextmanager cannot straddle that split. `sid` rides the wire meta
    exactly like span()'s, so server correlation is unchanged."""
    if not _enabled:
        return
    rec = {'type': 'span', 'kind': kind, 'name': name,
           'sid': sid, 'psid': None, 't0': t0, 't1': t1,
           'tid': threading.get_ident() & 0xffff}
    rec.update(attrs)
    _emit(rec)


def event(etype, **fields):
    """Instant record ('fault', 'mark', ...)."""
    if not _enabled:
        return
    rec = {'type': etype, 't': time.time()}
    rec.update(fields)
    _emit(rec)


def _default_role():
    from ..flags import get_flag
    return get_flag('obs_role', '') or ('pid%d' % os.getpid())


def enable(obs_dir, role=None):
    """Open (or retarget) the event log. Idempotent."""
    global _enabled, _file, _role
    disable()
    os.makedirs(obs_dir, exist_ok=True)
    role = role or _default_role()
    path = os.path.join(obs_dir,
                        'events-%s-%d.jsonl' % (role, os.getpid()))
    with _lock:
        _file = open(path, 'a')
        _role = role
    _enabled = True


def disable():
    global _enabled, _file
    _enabled = False
    with _lock:
        f, _file = _file, None
    if f is not None:
        try:
            f.close()
        except OSError:
            pass


def _bootstrap_from_flags():
    from ..flags import get_flag
    obs_dir = get_flag('obs_dir', '')
    if obs_dir:
        enable(obs_dir)


_bootstrap_from_flags()
