"""Merge per-role obs JSONL logs into one cluster timeline + rollup.

Input layout (what a Supervisor-run cluster leaves behind):

    <obs_root>/<role>/events-<role>-<pid>.jsonl    span/fault records
    <obs_root>/<role>/metrics-<role>-<pid>.jsonl   telemetry snapshots
    <obs_root>/supervisor/metrics-*.jsonl          restart counters

A restarted role leaves one file pair PER INCARNATION (pids differ);
metrics are summed across incarnations of a role, events simply
concatenate.

Clock alignment: processes stamp records with their own `time.time()`.
For every RPC whose client and server spans share a sid, the server's
handling happens strictly inside the client's request/reply window, so
`midpoint(server span) - midpoint(client span)` estimates the server
clock's offset relative to the client (symmetric-delay assumption —
the classic NTP estimate). Per role pair we take the median over all
such spans, then walk the role graph breadth-first from a reference
role, accumulating shifts, so even roles that never talk directly
(trainer1 vs trainer0 — both only talk to pservers) land on one clock.

Timeline output is chrome://tracing JSON: one pid lane per role,
spans as 'X' duration events, client->server RPC links as 's'/'f'
flow events (same `id` = span id), faults as instant events. Device
kernels from a profiler xplane capture join as their own lanes
(device_events_to_records / write_report(xplane_dir=...)): xplane
device timestamps are unix-epoch ns — the same clock family the host
spans use — so they align without an offset estimate.
"""
from __future__ import annotations

import collections
import json
import os
import warnings

from . import telemetry

__all__ = ['collect', 'estimate_offsets', 'build_timeline', 'rollup',
           'write_report', 'format_rollup_text',
           'device_events_to_records']


def _read_jsonl(path):
    out = []
    bad = 0
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    bad += 1   # torn tail from a kill -9 mid-write
    except OSError:
        pass
    if bad:
        warnings.warn(
            'obs merge: skipped %d unparseable line(s) in %s '
            '(torn tail from an unclean shutdown?)' % (bad, path),
            stacklevel=2)
    return out


def collect(root):
    """-> (events, metric_lasts): every event record under `root`, and
    the LAST metrics snapshot of every metrics file (one per process
    incarnation — summed later by rollup())."""
    events, metric_lasts = [], []
    for dirpath, _, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith('.jsonl'):
                continue
            path = os.path.join(dirpath, fn)
            if fn.startswith('events-'):
                events.extend(_read_jsonl(path))
            elif fn.startswith('metrics-'):
                recs = _read_jsonl(path)
                if recs:
                    metric_lasts.append(recs[-1])
    return events, metric_lasts


def _span_pairs(events):
    """sid -> (client spans, server spans) for sids seen on both
    sides — the cross-process links."""
    by_sid = collections.defaultdict(lambda: ([], []))
    for e in events:
        if e.get('type') != 'span' or 'sid' not in e:
            continue
        if e.get('kind') == 'client':
            by_sid[e['sid']][0].append(e)
        elif e.get('kind') == 'server':
            by_sid[e['sid']][1].append(e)
    return {sid: cs for sid, cs in by_sid.items() if cs[0] and cs[1]}


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def estimate_offsets(events):
    """-> {role: shift_seconds}; adding a role's shift to its
    timestamps moves them onto the reference role's clock."""
    deltas = collections.defaultdict(list)
    client_counts = collections.Counter()
    roles = set()
    for e in events:
        if 'role' in e:
            roles.add(e['role'])
    for cspans, sspans in _span_pairs(events).values():
        for c in cspans:
            client_counts[c.get('role')] += 1
            for s in sspans:
                if c.get('role') == s.get('role'):
                    continue   # same process: no offset information
                mid_c = 0.5 * (c['t0'] + c['t1'])
                mid_s = 0.5 * (s['t0'] + s['t1'])
                deltas[(c['role'], s['role'])].append(mid_s - mid_c)
    # undirected role graph with signed medians
    edges = collections.defaultdict(dict)
    for (a, b), ds in deltas.items():
        d = _median(ds)
        edges[a][b] = d          # clock_b - clock_a (estimated)
        edges[b].setdefault(a, -d)
    if client_counts:
        ref = max(sorted(client_counts), key=lambda r: client_counts[r])
    else:
        ref = min(roles) if roles else None
    shifts = {r: 0.0 for r in roles}
    if ref is None:
        return shifts
    seen = {ref}
    frontier = [ref]
    while frontier:
        a = frontier.pop(0)
        for b, d in sorted(edges.get(a, {}).items()):
            if b in seen:
                continue
            # t_b + shift_b must equal t_a + shift_a for the same
            # instant; d estimates clock_b - clock_a
            shifts[b] = shifts[a] - d
            seen.add(b)
            frontier.append(b)
    return shifts


def build_timeline(events, offsets=None):
    """-> chrome://tracing dict. One pid lane per role, spans as 'X',
    client/server RPC links as flow events, faults as instants."""
    if offsets is None:
        offsets = estimate_offsets(events)
    roles = sorted({e.get('role', '?') for e in events})
    role_pid = {r: i + 1 for i, r in enumerate(roles)}

    def adj(role, t):
        return t + offsets.get(role, 0.0)

    base = None
    for e in events:
        t = e.get('t0', e.get('t'))
        if t is not None:
            at = adj(e.get('role', '?'), t)
            base = at if base is None else min(base, at)
    base = base or 0.0

    def us(role, t):
        return (adj(role, t) - base) * 1e6

    out = [{'name': 'process_name', 'ph': 'M', 'pid': pid,
            'args': {'name': role}} for role, pid in role_pid.items()]
    for e in events:
        role = e.get('role', '?')
        pid = role_pid[role]
        if e.get('type') == 'span':
            args = {k: v for k, v in e.items()
                    if k not in ('type', 'kind', 'name', 't0', 't1',
                                 'tid', 'pid')}
            out.append({'ph': 'X', 'cat': e.get('kind', 'host'),
                        'name': e['name'], 'pid': pid,
                        'tid': e.get('tid', 0),
                        'ts': us(role, e['t0']),
                        'dur': max((e['t1'] - e['t0']) * 1e6, 0.0),
                        'args': args})
        elif 't' in e:
            args = {k: v for k, v in e.items()
                    if k not in ('type', 't', 'pid')}
            out.append({'ph': 'i', 's': 'p', 'cat': e.get('type', 'mark'),
                        'name': '%s:%s' % (e.get('type', 'mark'),
                                           e.get('action',
                                                 e.get('name', ''))),
                        'pid': pid, 'tid': e.get('tid', 0),
                        'ts': us(role, e['t']), 'args': args})
    # flow events: client span midpoint -> each server span midpoint
    for sid, (cspans, sspans) in sorted(_span_pairs(events).items()):
        for c in cspans:
            crole = c.get('role', '?')
            out.append({'ph': 's', 'cat': 'rpc', 'name': 'rpc',
                        'id': sid, 'pid': role_pid[crole],
                        'tid': c.get('tid', 0),
                        'ts': us(crole, 0.5 * (c['t0'] + c['t1']))})
        for s in sspans:
            srole = s.get('role', '?')
            out.append({'ph': 'f', 'bp': 'e', 'cat': 'rpc',
                        'name': 'rpc', 'id': sid,
                        'pid': role_pid[srole], 'tid': s.get('tid', 0),
                        'ts': us(srole, 0.5 * (s['t0'] + s['t1']))})
    out.sort(key=lambda e: (e.get('ts', 0), e.get('pid', 0)))
    return {'traceEvents': out,
            'metadata': {'clock_shifts': offsets}}


def device_events_to_records(device_events, role='device',
                             clock_offset=0.0):
    """profiler.device_op_events output -> span records that merge
    straight into the host event stream. Accepts (label, start_ns,
    dur_ns) 3-tuples (one shared lane) or (label, start_ns, dur_ns,
    plane) 4-tuples (one timeline lane PER PLANE — per device chip).

    xplane device timestamps are unix-epoch nanoseconds (the same
    clock host spans stamp with time.time() — see tools/timeline.py),
    so t0 = start_ns/1e9 lands directly on the merged clock;
    `clock_offset` is there for captures known to be shifted."""
    recs = []
    for i, ev in enumerate(device_events):
        label, start_ns, dur_ns = ev[0], ev[1], ev[2]
        plane = ev[3] if len(ev) > 3 else ''
        # '/device:TPU:0' -> lane 'device:TPU:0' (already self-naming)
        lane = plane.rsplit('/', 1)[-1] if plane else role
        t0 = start_ns / 1e9 + clock_offset
        recs.append({'type': 'span', 'kind': 'device', 'name': label,
                     'sid': 'dev-%d' % i, 't0': t0,
                     't1': t0 + dur_ns / 1e9, 'tid': 0, 'role': lane,
                     'pid': 0})
    return recs


def _merge_hist(into, h):
    if h.get('count', 0) == 0:
        return
    if into.get('count', 0) == 0:
        into.update({k: h[k] for k in ('count', 'sum', 'min', 'max',
                                       'buckets')})
        into['buckets'] = list(h['buckets'])
        return
    into['count'] += h['count']
    into['sum'] += h['sum']
    into['min'] = min(into['min'], h['min'])
    into['max'] = max(into['max'], h['max'])
    bs = into['buckets']
    for i, n in enumerate(h.get('buckets', ())):
        if i < len(bs):
            bs[i] += n


def rollup(metric_lasts):
    """-> {'roles': {role: {counters, gauges, hists}}, 'totals':
    {counter: sum}}. Counters sum across incarnations AND roles;
    gauges keep the latest-ts value per role; histograms merge, then
    report p50/p95/p99 recomputed over the MERGED buckets (the raw
    bucket arrays are dropped from the output — percentiles are the
    consumable form)."""
    roles = {}
    for rec in sorted(metric_lasts, key=lambda r: r.get('ts', 0)):
        role = rec.get('role', '?')
        agg = roles.setdefault(role, {'counters': {}, 'gauges': {},
                                      'hists': {}})
        for n, v in rec.get('counters', {}).items():
            agg['counters'][n] = agg['counters'].get(n, 0) + v
        for n, v in rec.get('gauges', {}).items():
            agg['gauges'][n] = v
        for n, h in rec.get('hists', {}).items():
            _merge_hist(agg['hists'].setdefault(n, {'count': 0}), h)
    totals = {}
    for agg in roles.values():
        for n, v in agg['counters'].items():
            totals[n] = totals.get(n, 0) + v
        for h in agg['hists'].values():
            if h.get('buckets') is not None:
                for key, q in (('p50', 0.50), ('p95', 0.95),
                               ('p99', 0.99)):
                    h[key] = telemetry.hist_quantile(h, q)
                del h['buckets']
    return {'roles': roles, 'totals': totals}


def format_rollup_text(ru, nonzero_only=True):
    lines = ['cluster totals:']
    for n in sorted(ru['totals']):
        v = ru['totals'][n]
        if v or not nonzero_only:
            lines.append('  %-40s %d' % (n, v))
    for role in sorted(ru['roles']):
        agg = ru['roles'][role]
        shown = [(n, v) for n, v in sorted(agg['counters'].items())
                 if v or not nonzero_only]
        shown += [('%s (gauge)' % n, v)
                  for n, v in sorted(agg['gauges'].items())
                  if v or not nonzero_only]
        hists = [(n, h) for n, h in sorted(agg['hists'].items())
                 if h.get('count')]
        if not (shown or hists):
            continue
        lines.append('%s:' % role)
        for n, v in shown:
            lines.append('  %-40s %d' % (n, v))
        for n, h in hists:
            pcts = ''
            if h.get('p50') is not None:
                pcts = ' p50=%.6fs p95=%.6fs p99=%.6fs' % (
                    h['p50'], h.get('p95') or 0.0, h.get('p99') or 0.0)
            lines.append('  %-40s n=%d mean=%.6fs%s max=%.6fs'
                         % (n, h['count'], h['sum'] / h['count'],
                            pcts, h['max']))
    return '\n'.join(lines)


def write_report(obs_root, timeline_path=None, rollup_path=None,
                 pretty=False, xplane_dir=None, hlo_dir=None):
    """Merge everything under obs_root; optionally write the timeline
    and rollup JSON files. -> (timeline dict, rollup dict).

    With xplane_dir (a jax.profiler trace capture taken during the
    run), the device-op events join the timeline as device lanes;
    hlo_dir (compiled-HLO .txt dumps, e.g. from compiled_hlo_texts())
    maps fused-instruction names back to framework op names first."""
    events, metric_lasts = collect(obs_root)
    if xplane_dir:
        from .. import profiler
        op_map = {}
        if hlo_dir and os.path.isdir(hlo_dir):
            for fn in sorted(os.listdir(hlo_dir)):
                if fn.endswith('.txt'):
                    with open(os.path.join(hlo_dir, fn)) as f:
                        op_map.update(profiler.hlo_op_map(f.read()))
        events = events + device_events_to_records(
            profiler.device_op_events(xplane_dir, op_map,
                                      with_plane=True))
    tl = build_timeline(events)
    ru = rollup(metric_lasts)
    indent = 2 if pretty else None
    if timeline_path:
        with open(timeline_path, 'w') as f:
            json.dump(tl, f, indent=indent)
    if rollup_path:
        with open(rollup_path, 'w') as f:
            json.dump(ru, f, indent=indent)
    return tl, ru
