"""Process-wide telemetry registry: Counters, Gauges, bucketed
Histograms.

The distributed stack (wire/rpc/param_service/master/trainer/reader/
supervisor) holds module-level instrument objects created at import
time; recording on them is a no-op while observability is disabled
(`FLAGS_obs_dir` unset) — the fast path is one module-global boolean
check, no lock, no allocation — so instrumentation can live on hot
paths (every wire frame) without a measurable step-time cost.

When `FLAGS_obs_dir` is set the registry is enabled at import and an
exporter thread appends a full `snapshot()` line to
`<obs_dir>/metrics-<role>-<pid>.jsonl` every `FLAGS_obs_flush_secs`
seconds, plus a final line at interpreter exit — so a role that is
kill -9'd mid-run still leaves its last periodic snapshot on disk.
`obs/report.py` merges the per-role files (last line per file wins)
into the cluster rollup.

Naming convention: dotted series names, subsystem first —
`wire.frames_out`, `rpc.client.retries`, `ps.journal.appends`,
`trainer.step_latency`, and the sharded-checkpoint family
`ckpt.save_latency` / `ckpt.bytes_written` / `ckpt.restore_latency`
(histograms) + `ckpt.generations` (counter) from paddle_tpu/checkpoint/
(see README "Observability" for the catalog).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time

__all__ = ['Counter', 'Gauge', 'Histogram', 'counter', 'gauge',
           'histogram', 'hist_quantile', 'snapshot', 'flush',
           'enabled', 'enable', 'disable', 'reset']

_lock = threading.Lock()
_enabled = False
_counters = {}
_gauges = {}
_hists = {}
_exporter = None


class Counter(object):
    """Monotonic event count. inc() is the disabled-mode fast path the
    whole registry is designed around: one global bool read, return."""
    __slots__ = ('name', 'value')

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        if not _enabled:
            return
        with _lock:
            self.value += n


class Gauge(object):
    """Last-written level (queue depth, leaked workers)."""
    __slots__ = ('name', 'value')

    def __init__(self, name):
        self.name = name
        self.value = 0

    def set(self, v):
        if not _enabled:
            return
        with _lock:
            self.value = v


# exponential bucket bounds in seconds: 100us .. ~100s, x4 per bucket
# (step latencies and RPC round trips both land mid-range); the last
# bucket is the +Inf overflow
_BOUNDS = tuple(1e-4 * (4.0 ** i) for i in range(11))


class Histogram(object):
    """Bucketed distribution (fixed exponential bounds) + running
    count/sum/min/max — enough for a latency rollup without reservoir
    sampling."""
    __slots__ = ('name', 'count', 'sum', 'min', 'max', 'buckets')

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = float('inf')
        self.max = 0.0
        self.buckets = [0] * (len(_BOUNDS) + 1)

    def observe(self, v):
        if not _enabled:
            return
        v = float(v)
        with _lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            i = 0
            for bound in _BOUNDS:
                if v <= bound:
                    break
                i += 1
            self.buckets[i] += 1


def hist_quantile(hist, q):
    """Estimate the q-quantile (0 < q <= 1) of a histogram given in
    snapshot-dict form ({'count','min','max','buckets'}). Linear
    interpolation inside the owning exponential bucket, clamped to the
    observed min/max so a single-sample histogram reports that sample
    exactly. Returns None for an empty histogram.

    Works on live snapshots and on report.py's cross-role merges alike
    (both carry the same bucket layout)."""
    count = hist.get('count', 0)
    if not count:
        return None
    buckets = hist['buckets']
    mn = hist.get('min') or 0.0
    mx = hist.get('max', 0.0)
    rank = q * count
    cum = 0
    for i, n in enumerate(buckets):
        if n and cum + n >= rank:
            lo = _BOUNDS[i - 1] if i > 0 else 0.0
            hi = _BOUNDS[i] if i < len(_BOUNDS) else mx
            frac = (rank - cum) / n
            v = lo + frac * max(hi - lo, 0.0)
            return min(max(v, mn), mx)
        cum += n
    return mx


def _hist_dict(h):
    d = {'count': h.count, 'sum': h.sum,
         'min': (None if h.count == 0 else h.min),
         'max': h.max, 'buckets': list(h.buckets)}
    for key, q in (('p50', 0.50), ('p95', 0.95), ('p99', 0.99)):
        d[key] = hist_quantile(d, q)
    return d


def _get(table, cls, name):
    with _lock:
        inst = table.get(name)
        if inst is None:
            inst = table[name] = cls(name)
        return inst


def counter(name):
    return _get(_counters, Counter, name)


def gauge(name):
    return _get(_gauges, Gauge, name)


def histogram(name):
    return _get(_hists, Histogram, name)


def enabled():
    return _enabled


def snapshot():
    """One consistent dict of every registered series. Untouched series
    are included at zero — the rollup sums them away for free and the
    catalog stays visible in every export."""
    with _lock:
        return {
            'counters': {n: c.value for n, c in _counters.items()},
            'gauges': {n: g.value for n, g in _gauges.items()},
            'hists': {n: _hist_dict(h) for n, h in _hists.items()},
        }


def reset():
    """Zero every registered series IN PLACE (instrument objects are
    held by the instrumented modules — they must stay valid). Test
    isolation helper."""
    with _lock:
        for c in _counters.values():
            c.value = 0
        for g in _gauges.values():
            g.value = 0
        for h in _hists.values():
            h.count, h.sum, h.min, h.max = 0, 0.0, float('inf'), 0.0
            h.buckets = [0] * (len(_BOUNDS) + 1)


class _Exporter(object):
    """Daemon thread appending metric snapshots as JSONL."""

    def __init__(self, obs_dir, role, period):
        self.path = os.path.join(
            obs_dir, 'metrics-%s-%d.jsonl' % (role, os.getpid()))
        self.role = role
        self.period = max(float(period), 0.05)
        self._stop = threading.Event()
        self._wlock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(timeout=self.period):
            try:
                self.write_line()
            except OSError:
                pass   # a torn-down obs dir must not kill the process

    def write_line(self):
        rec = snapshot()
        rec['ts'] = time.time()
        rec['role'] = self.role
        rec['pid'] = os.getpid()
        line = json.dumps(rec) + '\n'
        with self._wlock:
            with open(self.path, 'a') as f:
                f.write(line)

    def stop(self, final_flush=True):
        self._stop.set()
        self._thread.join(timeout=5.0)
        if final_flush:
            try:
                self.write_line()
            except OSError:
                pass


def flush():
    """Force a metric-snapshot line now (chaos tests call this before
    asserting on a freshly merged rollup)."""
    if _exporter is not None:
        _exporter.write_line()


def _default_role():
    from ..flags import get_flag
    return get_flag('obs_role', '') or ('pid%d' % os.getpid())


def enable(obs_dir=None, role=None, period=None):
    """Turn recording on; with an obs_dir, also start the JSONL
    exporter. Idempotent; re-enabling with a different dir retargets
    the exporter (test harnesses toggle this per-case)."""
    global _enabled, _exporter
    from ..flags import get_flag
    disable(final_flush=False)
    _enabled = True
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        _exporter = _Exporter(
            obs_dir, role or _default_role(),
            period if period is not None
            else float(get_flag('obs_flush_secs', 2.0)))


def disable(final_flush=True):
    global _enabled, _exporter
    _enabled = False
    if _exporter is not None:
        _exporter.stop(final_flush=final_flush)
        _exporter = None


@atexit.register
def _atexit_flush():
    if _exporter is not None:
        try:
            _exporter.stop()
        except Exception:
            pass


def _bootstrap_from_flags():
    """Enabled-at-import when FLAGS_obs_dir is set (the Supervisor
    plants it in each role's environment) — worker processes need no
    code changes to start exporting."""
    from ..flags import get_flag
    obs_dir = get_flag('obs_dir', '')
    if obs_dir:
        enable(obs_dir)


_bootstrap_from_flags()
