"""Declarative SLO rules evaluated live against the telemetry
registry.

A rule names one metric series, a comparison kind, and a threshold:

    {"name": "mfu_floor",    "metric": "perf.mfu",
     "kind": "gauge_min",    "threshold": 0.45}
    {"name": "step_p99",     "metric": "perf.step_latency",
     "kind": "p99_max",      "threshold": 0.250, "min_count": 20}
    {"name": "ttft",         "metric": "serving.ttft",
     "kind": "p95_max",      "threshold": 1.5}
    {"name": "tokens_floor", "metric": "serving.tokens_generated",
     "kind": "rate_min",     "threshold": 100.0}

Kinds:
  gauge_min / gauge_max      — last-written gauge level vs threshold
  p50_max / p95_max / p99_max— histogram percentile (exponential-
                               bucket estimate, telemetry.hist_quantile)
  mean_max                   — histogram sum/count
  rate_min / rate_max        — counter delta per second between two
                               consecutive checks (first check only
                               primes the baseline)

`min_count` (default 1) suppresses judgement until a histogram has
that many observations / a gauge-family rule sees a nonzero snapshot
— a cold registry should not page anyone.

The watchdog re-evaluates every FLAGS_slo_check_secs from a daemon
thread (`SLOWatchdog.start()`), or on demand (`check_now()`).
A breach emits a `slo.breach` instant event into the trace stream
(rule, metric, observed value, threshold — it lands on the merged
timeline next to whatever caused it) and bumps the `slo.breaches`
counter; `slo.breaching` holds the number of currently-failing rules.

Wiring: serving.Engine.start()/stop() own a watchdog when
FLAGS_slo_rules is set; training runs arm one lazily from
obs.perf.step_end. FLAGS_slo_rules is either inline JSON (a list of
rule dicts) or `@/path/to/rules.json`.
"""
from __future__ import annotations

import json
import threading
import time

from . import telemetry, trace
from .. import flags

__all__ = ['SLORule', 'SLOWatchdog', 'parse_rules',
           'watchdog_from_flags', 'maybe_start_global', 'stop_global']

_breaches = telemetry.counter('slo.breaches')
_breaching = telemetry.gauge('slo.breaching')

_GAUGE_KINDS = ('gauge_min', 'gauge_max')
_HIST_KINDS = ('p50_max', 'p95_max', 'p99_max', 'mean_max')
_RATE_KINDS = ('rate_min', 'rate_max')
_KINDS = _GAUGE_KINDS + _HIST_KINDS + _RATE_KINDS


class SLORule(object):
    """One named threshold over one telemetry series."""

    def __init__(self, name, metric, kind, threshold, min_count=1):
        if kind not in _KINDS:
            raise ValueError('unknown SLO kind %r (one of %s)'
                             % (kind, ', '.join(_KINDS)))
        self.name = name
        self.metric = metric
        self.kind = kind
        self.threshold = float(threshold)
        self.min_count = int(min_count)

    @classmethod
    def from_dict(cls, d):
        return cls(d['name'], d['metric'], d['kind'], d['threshold'],
                   d.get('min_count', 1))

    def to_dict(self):
        return {'name': self.name, 'metric': self.metric,
                'kind': self.kind, 'threshold': self.threshold,
                'min_count': self.min_count}

    def evaluate(self, snap, prev=None, dt=None):
        """(observed_value, breached) against one registry snapshot,
        or None when the rule can't be judged yet (series absent,
        min_count unmet, no rate baseline)."""
        kind = self.kind
        if kind in _GAUGE_KINDS:
            if self.metric not in snap['gauges']:
                return None
            v = float(snap['gauges'][self.metric])
            if kind == 'gauge_min':
                return (v, v < self.threshold)
            return (v, v > self.threshold)
        if kind in _HIST_KINDS:
            h = snap['hists'].get(self.metric)
            if not h or h['count'] < self.min_count:
                return None
            if kind == 'mean_max':
                v = h['sum'] / h['count']
            else:
                q = {'p50_max': 0.50, 'p95_max': 0.95,
                     'p99_max': 0.99}[kind]
                v = telemetry.hist_quantile(h, q)
                if v is None:
                    return None
            return (v, v > self.threshold)
        # rate kinds: counter delta / wall delta between two checks
        if (prev is None or not dt or dt <= 0.0
                or self.metric not in snap['counters']
                or self.metric not in prev.get('counters', {})):
            return None
        delta = snap['counters'][self.metric] - \
            prev['counters'][self.metric]
        if delta < self.min_count:
            return None
        rate = delta / dt
        if kind == 'rate_min':
            return (rate, rate < self.threshold)
        return (rate, rate > self.threshold)


def parse_rules(spec):
    """Rule list from inline JSON, `@path`, a *.json path, or an
    already-materialized list of dicts/SLORules."""
    if not spec:
        return []
    if isinstance(spec, str):
        spec = spec.strip()
        if spec.startswith('@'):
            with open(spec[1:]) as f:
                spec = json.load(f)
        elif spec.endswith('.json') and not spec.startswith('['):
            with open(spec) as f:
                spec = json.load(f)
        else:
            spec = json.loads(spec)
    if isinstance(spec, dict):
        spec = [spec]
    out = []
    for r in spec:
        out.append(r if isinstance(r, SLORule)
                   else SLORule.from_dict(r))
    return out


class SLOWatchdog(object):
    """Periodic evaluator over a rule set. check_now() is also the
    test/serving-drain entry point — it is safe without start()."""

    def __init__(self, rules, period=None):
        self.rules = list(rules)
        self.period = float(period if period is not None
                            else flags.get_flag('slo_check_secs', 5.0))
        self._prev_snap = None
        self._prev_ts = None
        self._stop = threading.Event()
        self._thread = None
        self._lock = threading.Lock()

    def check_now(self):
        """Evaluate every rule against a fresh snapshot; emit a
        slo.breach trace event per failing rule. Returns the breach
        list: [{'rule','metric','kind','value','threshold'}, ...]."""
        with self._lock:
            snap = telemetry.snapshot()
            now = time.time()
            prev, dt = self._prev_snap, None
            if self._prev_ts is not None:
                dt = now - self._prev_ts
            self._prev_snap, self._prev_ts = snap, now
            breaches = []
            for rule in self.rules:
                res = rule.evaluate(snap, prev=prev, dt=dt)
                if res is None:
                    continue
                value, breached = res
                if not breached:
                    continue
                breach = {'rule': rule.name, 'metric': rule.metric,
                          'kind': rule.kind, 'value': value,
                          'threshold': rule.threshold}
                breaches.append(breach)
                trace.event('slo.breach', **breach)
                _breaches.inc()
            _breaching.set(len(breaches))
            return breaches

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(timeout=self.period):
            try:
                self.check_now()
            except Exception:
                pass    # the watchdog must never take the host down

    def stop(self, final_check=True):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if final_check:
            try:
                self.check_now()
            except Exception:
                pass


def watchdog_from_flags():
    """SLOWatchdog built from FLAGS_slo_rules / FLAGS_slo_check_secs,
    or None when no rules are configured (the universal default)."""
    rules = parse_rules(flags.get_flag('slo_rules', ''))
    if not rules:
        return None
    return SLOWatchdog(rules)


_global = None
_global_lock = threading.Lock()


def maybe_start_global():
    """Idempotent process-wide watchdog from flags (training path —
    obs.perf arms this on the first instrumented step)."""
    global _global
    with _global_lock:
        if _global is not None:
            return _global
        wd = watchdog_from_flags()
        if wd is None:
            return None
        _global = wd.start()
        return _global


def stop_global():
    global _global
    with _global_lock:
        wd, _global = _global, None
    if wd is not None:
        wd.stop()
