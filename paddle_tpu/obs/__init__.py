"""Cluster observability: telemetry registry, cross-process trace
spans, and the merged cluster timeline/report.

Three pillars (see README "Observability"):

- `obs.telemetry` — process-wide Counters/Gauges/Histograms with a
  near-free disabled path, `snapshot()`, and periodic JSONL export.
- `obs.trace` — span ids propagated through the wire meta dict's
  optional `trace` field; spans, FaultEvents, and RecordEvent scopes
  share one per-process JSONL event log.
- `obs.report` — merges per-role logs into one chrome://tracing
  timeline (clock offsets estimated from RPC midpoints, device-op
  lanes from profiler xplane captures) plus a metrics rollup.
  CLI: `python tools/obs_report.py --obs_dir ...`.

Plus the device-side performance observatory on top of them:

- `obs.perf` — compile/JIT telemetry (xla.compile spans,
  xla.compile_latency, xla.jit_cache.{hit,miss}), per-step
  perf.step_latency / perf.mfu / perf.achieved_tflops, and hbm.*
  gauges/watermarks. Wired into Executor/ParallelExecutor.
- `obs.slo` — declarative threshold rules over the registry
  (MFU floor, latency percentiles, serving rates) evaluated by a
  watchdog that emits slo.breach events.

Everything is off unless `FLAGS_obs_dir` is set (the Supervisor plants
a per-role subdir in each child's environment).
"""
from . import telemetry, trace, report, perf, slo

__all__ = ['telemetry', 'trace', 'report', 'perf', 'slo']
