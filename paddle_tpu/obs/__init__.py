"""Cluster observability: telemetry registry, cross-process trace
spans, and the merged cluster timeline/report.

Three pillars (see README "Observability"):

- `obs.telemetry` — process-wide Counters/Gauges/Histograms with a
  near-free disabled path, `snapshot()`, and periodic JSONL export.
- `obs.trace` — span ids propagated through the wire meta dict's
  optional `trace` field; spans, FaultEvents, and RecordEvent scopes
  share one per-process JSONL event log.
- `obs.report` — merges per-role logs into one chrome://tracing
  timeline (clock offsets estimated from RPC midpoints) plus a
  metrics rollup. CLI: `python tools/obs_report.py --obs_dir ...`.

Everything is off unless `FLAGS_obs_dir` is set (the Supervisor plants
a per-role subdir in each child's environment).
"""
from . import telemetry, trace, report

__all__ = ['telemetry', 'trace', 'report']
