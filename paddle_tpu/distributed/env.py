"""Cluster topology from environment variables — the bootstrap half of
the reference's Trainer env contract (reference python/paddle/fluid/
trainer.py:329-377 reads TRAINING_ROLE / PADDLE_PSERVER* /
PADDLE_TRAINER* and dispatches to pserver or trainer startup; SURVEY
§5.6). Entry scripts launched by tools/kube_gen_job.py (or any
scheduler exporting the same variables) call `cluster_from_env()` and
branch on `.role`:

    env = fluid.distributed.cluster_from_env()
    if env.role == 'PSERVER':
        ParameterService(...).serve(env.current_endpoint)
    else:
        t = fluid.DistributeTranspiler()
        t.transpile(env.trainer_id, pservers=env.pserver_csv,
                    trainers=env.num_trainers)

Collective (non-pserver) jobs instead pass `.trainer_id` /
`.trainer_endpoints` to `paddle_tpu.parallel.init_parallel_env`, which
reads the same PADDLE_TRAINER_* variables itself when called bare.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

__all__ = ['ClusterEnv', 'cluster_from_env']


@dataclass
class ClusterEnv:
    role: str                               # 'TRAINER' | 'PSERVER'
    trainer_id: int
    num_trainers: int
    trainer_endpoints: list = field(default_factory=list)
    pserver_endpoints: list = field(default_factory=list)
    current_endpoint: str = ''

    @property
    def pserver_csv(self):
        """Comma list in the form DistributeTranspiler.transpile takes."""
        return ','.join(self.pserver_endpoints)


def _split(csv):
    return [e.strip() for e in csv.split(',') if e.strip()]


def cluster_from_env(environ=None):
    """Parse the PADDLE_* contract out of `environ` (default
    os.environ). Unset variables degrade to a single-process TRAINER —
    the same local-mode default the reference's env bootstrap has."""
    env = os.environ if environ is None else environ
    role = env.get('TRAINING_ROLE', 'TRAINER').upper()
    tid = int(env.get('PADDLE_TRAINER_ID', 0) or 0)
    n = int(env.get('PADDLE_TRAINERS_NUM',
                    env.get('PADDLE_TRAINERS', 1)) or 1)
    tr_eps = _split(env.get('PADDLE_TRAINER_ENDPOINTS', ''))
    ps_eps = _split(env.get('PADDLE_PSERVER_ENDPOINTS', ''))
    cur = env.get('PADDLE_CURRENT_ENDPOINT', '')
    if not cur:
        eps = ps_eps if role == 'PSERVER' else tr_eps
        if eps and 0 <= tid < len(eps):
            cur = eps[tid]
    if role not in ('TRAINER', 'PSERVER'):
        raise ValueError('TRAINING_ROLE must be TRAINER or PSERVER, '
                         'got %r' % role)
    return ClusterEnv(role=role, trainer_id=tid, num_trainers=n,
                      trainer_endpoints=tr_eps,
                      pserver_endpoints=ps_eps,
                      current_endpoint=cur)
