"""Fault-tolerant data-task master: the TPU-native analog of the
reference's Go master service (go/master/service.go — SetDataset :280
builds a task queue over RecordIO chunks, GetTask :368 leases a task
with a timeout timer :341, TaskFinished :411, TaskFailed :455
re-enqueues until failureMax kills the task; etcd-backed snapshot
:207 / recover :166).

Redesign decisions:
- etcd is replaced by an atomic-rename JSON snapshot on local/shared
  disk (the same durability contract the Trainer checkpoints use:
  written-fully-or-not-at-all, recovered on restart);
- the RPC layer is the framework's own wire/TCP stack (distributed/
  wire.py) rather than Go net/rpc;
- tasks are opaque JSON payloads — recordio shard paths from
  recordio.convert_reader_to_recordio_files fit naturally, but any
  descriptor works;
- leases expire lazily (checked on every queue interaction) AND via a
  reaper thread, so a dead worker's tasks return to the queue even
  when no one else is calling.

`task_reader(client, make_samples)` adapts the lease/finish/fail cycle
into an ordinary sample generator, so the whole data stack
(batch/DataFeeder/py_reader) composes with elastic dispatch.
"""
from __future__ import annotations

import binascii
import json
import os
import socket
import threading
import time
from collections import deque

from . import wire
from .resilience import FatalRPCError, RetryableRPCError, RetryPolicy
from ..obs import telemetry as _tm
from ..obs import trace as _trace

__all__ = ['TaskMaster', 'MasterServer', 'MasterClient', 'task_reader']

# wire message types (continuing distributed/wire.py's space)
GET_TASK = 20
TASK_FINISHED = 21
TASK_FAILED = 22
SET_DATASET = 23
MASTER_STATUS = 24

_MSG_NAMES = {GET_TASK: 'GET_TASK', TASK_FINISHED: 'TASK_FINISHED',
              TASK_FAILED: 'TASK_FAILED', SET_DATASET: 'SET_DATASET',
              MASTER_STATUS: 'MASTER_STATUS'}

# MasterClient shares the rpc.client.* series with PSClient — a
# trainer's RPC health is one number regardless of which server it
# talked to; the trace span name distinguishes them
_CALLS = _tm.counter('rpc.client.calls')
_RETRIES = _tm.counter('rpc.client.retries')
_RECONNECTS = _tm.counter('rpc.client.reconnects')
_DEADLINE_TIMEOUTS = _tm.counter('rpc.client.read_deadline_timeouts')


class TaskMaster(object):
    """Task-queue state machine (thread-safe). States mirror the
    reference: todo -> pending(lease) -> done | failed(dropped)."""

    def __init__(self, timeout_secs=60.0, failure_max=3,
                 snapshot_path=None):
        self.timeout_secs = float(timeout_secs)
        self.failure_max = int(failure_max)
        self.snapshot_path = snapshot_path
        self._lock = threading.Lock()
        self._todo = []            # [task_id]
        self._pending = {}         # task_id -> (deadline, worker)
        self._done = []
        self._dead = []            # failed > failure_max
        self._payloads = {}        # task_id -> payload
        self._failures = {}        # task_id -> count
        self._lease_seq = 0        # nonce: stale finish/fail rejected
        self._next_id = 0
        self._pass_id = 0
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- queue operations --------------------------------------------------
    def set_dataset(self, payloads):
        """Start a pass over `payloads` (one task each). Appends to any
        unfinished work (reference SetDataset is idempotent per pass)."""
        with self._lock:
            for p in payloads:
                tid = self._next_id
                self._next_id += 1
                self._payloads[tid] = p
                self._failures[tid] = 0
                self._todo.append(tid)
            self._pass_id += 1
            self._snapshot()
            return self._pass_id

    def get_task(self, worker='?'):
        """Lease one task: (task_id, payload, lease_id) or
        (None, None, None) when nothing is leasable right now.
        Distinguish 'drained' (all done/dead) from 'wait' (leases
        outstanding) via all_done(). The lease_id must be echoed to
        task_finished/task_failed: a worker that stalled past its
        timeout holds a STALE lease and must not be able to complete or
        revoke the task after it was re-leased elsewhere.

        No snapshot here: recovery re-queues pending as todo anyway, so
        the persisted state is identical to the pre-lease snapshot (and
        per-lease writes would make snapshot I/O O(n^2) per pass)."""
        with self._lock:
            self._requeue_expired()
            if not self._todo:
                return None, None, None
            tid = self._todo.pop(0)
            self._lease_seq += 1
            self._pending[tid] = (time.monotonic() + self.timeout_secs,
                                  worker, self._lease_seq)
            return tid, self._payloads[tid], self._lease_seq

    def _owns(self, task_id, lease_id):
        lease = self._pending.get(task_id)
        return lease is not None and (lease_id is None
                                      or lease[2] == lease_id)

    def task_finished(self, task_id, lease_id=None):
        with self._lock:
            if self._owns(task_id, lease_id):
                del self._pending[task_id]
                self._done.append(task_id)
                self._snapshot()
                return True
            return False    # lease expired/re-leased; not the owner

    def task_failed(self, task_id, lease_id=None):
        """Re-enqueue, or drop after failure_max (reference :455)."""
        with self._lock:
            if not self._owns(task_id, lease_id):
                return False
            del self._pending[task_id]
            self._failures[task_id] += 1
            if self._failures[task_id] >= self.failure_max:
                self._dead.append(task_id)
            else:
                self._todo.append(task_id)
            self._snapshot()
            return True

    def all_done(self):
        with self._lock:
            self._requeue_expired()
            return not self._todo and not self._pending

    def status(self):
        with self._lock:
            self._requeue_expired()
            return {'todo': len(self._todo),
                    'pending': len(self._pending),
                    'done': len(self._done), 'dead': len(self._dead),
                    'pass': self._pass_id}

    def _requeue_expired(self):
        now = time.monotonic()
        expired = [t for t, (dl, _w, _l) in self._pending.items()
                   if dl < now]
        for t in expired:
            del self._pending[t]
            self._failures[t] += 1
            if self._failures[t] >= self.failure_max:
                self._dead.append(t)
            else:
                self._todo.append(t)
        if expired:
            self._snapshot()

    # -- durability --------------------------------------------------------
    def _snapshot(self):
        if not self.snapshot_path:
            return
        state = {'todo': self._todo,
                 # pending leases snapshot as todo: after a master
                 # restart their deadlines are meaningless and the
                 # reference recovers them as runnable
                 'pending_as_todo': list(self._pending),
                 'done': self._done, 'dead': self._dead,
                 'payloads': {str(k): v
                              for k, v in self._payloads.items()},
                 'failures': {str(k): v
                              for k, v in self._failures.items()},
                 'next_id': self._next_id, 'pass_id': self._pass_id}
        from .statefile import atomic_write_json
        atomic_write_json(self.snapshot_path, state)   # (service.go:346)

    def _recover(self):
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self._todo = list(state['todo']) + list(state['pending_as_todo'])
        self._done = list(state['done'])
        self._dead = list(state['dead'])
        self._payloads = {int(k): v for k, v in state['payloads'].items()}
        self._failures = {int(k): v for k, v in state['failures'].items()}
        self._next_id = state['next_id']
        self._pass_id = state['pass_id']


class MasterServer(object):
    """TCP front end over a TaskMaster (wire.py framing, JSON meta).

    Replay idempotency: every reply is cached under the request's
    (incarnation, seq) token. A MasterClient that lost a reply to a
    dropped connection replays the request on a fresh connection and
    receives the ORIGINAL reply — a replayed GET_TASK does not lease a
    second task, and a replayed TASK_FINISHED does not read as a stale
    lease (the at-most-once contract the Go master gets from net/rpc
    call sequencing)."""

    _REPLY_CACHE_MAX = 1024

    def __init__(self, endpoint, master=None, bind_retry_secs=10.0,
                 **master_kwargs):
        self.master = master or TaskMaster(**master_kwargs)
        self._replies = {}            # (cli, seq) -> reply meta
        self._reply_order = deque()
        self._reply_lock = threading.Lock()
        host, port = endpoint.rsplit(':', 1)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # a RESTARTED master re-binds its old endpoint while the dead
        # instance's connections drain — retry instead of failing the
        # recovery it exists to provide
        deadline = time.monotonic() + bind_retry_secs
        while True:
            try:
                self._lsock.bind((host, int(port)))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._stop = threading.Event()
        self._threads = []
        self._conns = []
        # reaper: expired leases return to the queue even while idle
        self._reaper = threading.Thread(target=self._reap_loop,
                                        daemon=True)

    def start(self):
        self._accept_t = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._accept_t.start()
        self._reaper.start()
        return self

    def _reap_loop(self):
        while not self._stop.wait(min(self.master.timeout_secs / 4, 5)):
            self.master.all_done()       # side effect: requeue expired

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _cached_reply(self, key):
        if key is None:
            return None
        with self._reply_lock:
            return self._replies.get(key)

    def _remember_reply(self, key, reply):
        if key is None:
            return
        with self._reply_lock:
            if key in self._replies:
                return
            self._replies[key] = reply
            self._reply_order.append(key)
            while len(self._reply_order) > self._REPLY_CACHE_MAX:
                self._replies.pop(self._reply_order.popleft(), None)

    def _serve_conn(self, conn):
        replay_hits = _tm.counter('master.reply_cache_hits')
        try:
            while not self._stop.is_set():
                msg_type, meta, _ = wire.read_msg(conn)
                seq = meta.get('seq')
                key = (meta.get('cli'), seq) if seq is not None else None
                reply = self._cached_reply(key)
                if reply is not None:   # replay: resend, don't re-apply
                    replay_hits.inc()
                    wire.write_msg(conn, wire.REPLY_OK, reply)
                    continue
                with _trace.server_span(
                        _MSG_NAMES.get(msg_type, 'MSG%d' % msg_type),
                        meta.get('trace')):
                    self._dispatch_one(conn, msg_type, meta, key)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # prune: long-lived masters serve many short-lived workers
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
            self._threads = [t for t in self._threads if t.is_alive()]

    def _dispatch_one(self, conn, msg_type, meta, key):
        if msg_type == GET_TASK:
            tid, payload, lease = self.master.get_task(
                meta.get('worker', '?'))
            reply = {'task_id': tid, 'payload': payload,
                     'lease_id': lease,
                     'drained': self.master.all_done()}
        elif msg_type == TASK_FINISHED:
            reply = {'ok': self.master.task_finished(
                meta['task_id'], meta.get('lease_id'))}
        elif msg_type == TASK_FAILED:
            reply = {'ok': self.master.task_failed(
                meta['task_id'], meta.get('lease_id'))}
        elif msg_type == SET_DATASET:
            reply = {'pass': self.master.set_dataset(
                meta['payloads'])}
        elif msg_type == MASTER_STATUS:
            reply = self.master.status()
        else:
            wire.write_msg(conn, wire.REPLY_ERR,
                           {'error': 'unknown msg %d' % msg_type,
                            'retryable': False})
            return
        self._remember_reply(key, reply)
        wire.write_msg(conn, wire.REPLY_OK, reply)

    def shutdown(self):
        self._stop.set()
        # a thread parked in accept() holds the kernel listen socket
        # open past close() — SHUT_RDWR unblocks it so the port is
        # actually released for a restarted master
        try:
            self._lsock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        if hasattr(self, '_accept_t'):
            self._accept_t.join(timeout=5.0)
        # close live connections too: their server-side sockets hold the
        # port and would block a restarted master's re-bind
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass


class MasterClient(object):
    """Worker-side client with the same reconnect/replay discipline as
    PSClient: seq-numbered requests, transparent reconnect under the
    shared RetryPolicy, and replay against the master's reply cache (a
    restarted MASTER also re-serves: connect retries cover its re-bind
    window, and TaskMaster recovery re-queues leases)."""

    def __init__(self, endpoint, worker='worker', timeout=None,
                 connect_retry_secs=60.0, retry_policy=None):
        self.worker = worker
        if timeout is None:
            # same read deadline as PSClient: a mute master surfaces as
            # a retryable timeout, never a silent hang
            from ..flags import get_flag
            timeout = float(get_flag('rpc_read_deadline', 120.0))
        self.timeout = timeout
        host, port = endpoint.rsplit(':', 1)
        self._addr = (host, int(port))
        self._retry = retry_policy or RetryPolicy.from_flags()
        self._incarnation = binascii.hexlify(os.urandom(6)).decode()
        self._seq = 0
        self._sock = None
        self._lock = threading.Lock()
        self._connect(connect_retry_secs)

    def _connect(self, retry_secs):
        deadline = time.monotonic() + retry_secs
        while True:
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self.timeout)
                return
            except (ConnectionRefusedError, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)

    def _drop_socket(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, msg_type, meta):
        meta = dict(meta)
        meta['worker'] = self.worker
        with self._lock:
            self._seq += 1
            meta['seq'] = self._seq
            meta['cli'] = self._incarnation
            _CALLS.inc()
            with _trace.span(
                    'master.%s' % _MSG_NAMES.get(msg_type, msg_type),
                    kind='client', seq=self._seq) as sp:
                tr = _trace.wire_trace(sp)
                if tr is not None:
                    meta['trace'] = tr
                return self._call_locked(msg_type, meta)

    def _call_locked(self, msg_type, meta):
        last_err = None
        first = True
        for delay in self._retry.schedule():
            if not first:
                _RETRIES.inc()
            first = False
            if delay:
                time.sleep(delay)
            try:
                if self._sock is None:
                    _RECONNECTS.inc()
                    self._connect(self._retry.reconnect_secs)
                wire.write_msg(self._sock, msg_type, meta)
                rtype, reply, _ = wire.read_msg(self._sock)
            except FatalRPCError:
                self._drop_socket()
                raise
            except (ConnectionError, OSError) as e:
                if isinstance(e, socket.timeout):
                    _DEADLINE_TIMEOUTS.inc()
                last_err = e
                self._drop_socket()
                continue
            if rtype == wire.REPLY_ERR:
                err = 'master: %s' % reply.get('error')
                if reply.get('retryable'):
                    last_err = RetryableRPCError(err)
                    continue
                raise FatalRPCError(err)
            return reply
        raise RetryableRPCError(
            'master unreachable after %d attempts (%s: %s)'
            % (self._retry.max_attempts, type(last_err).__name__,
               last_err)) from last_err

    def set_dataset(self, payloads):
        return self._call(SET_DATASET, {'payloads': list(payloads)})

    def get_task(self):
        """(task_id, payload, drained); remembers the lease id for
        the matching task_finished/task_failed call."""
        r = self._call(GET_TASK, {})
        tid = r.get('task_id')
        if tid is not None:
            self._leases = getattr(self, '_leases', {})
            self._leases[tid] = r.get('lease_id')
        return tid, r.get('payload'), r.get('drained')

    def task_finished(self, task_id):
        lease = getattr(self, '_leases', {}).pop(task_id, None)
        return self._call(TASK_FINISHED, {'task_id': task_id,
                                          'lease_id': lease})['ok']

    def task_failed(self, task_id):
        lease = getattr(self, '_leases', {}).pop(task_id, None)
        return self._call(TASK_FAILED, {'task_id': task_id,
                                        'lease_id': lease})['ok']

    def status(self):
        return self._call(MASTER_STATUS, {})

    def close(self):
        self._drop_socket()


def task_reader(client, make_samples, poll_secs=0.5):
    """Adapt the lease cycle into a sample generator (the Go client's
    live-reader integration, go/master/client.go): pulls tasks until the
    master reports the pass drained; a task whose sample stream raises
    is reported failed (-> retried elsewhere) instead of crashing the
    pass."""
    def reader():
        while True:
            tid, payload, drained = client.get_task()
            if tid is None:
                if drained:
                    return
                time.sleep(poll_secs)   # leases outstanding elsewhere
                continue
            try:
                for sample in make_samples(payload):
                    yield sample
            except Exception:           # noqa: BLE001 — retried via lease
                client.task_failed(tid)
                continue
            client.task_finished(tid)
    return reader
