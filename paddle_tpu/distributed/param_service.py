"""Parameter service: the listen_and_serv sync/async loop state machine.

Semantics transplanted from the reference pserver
(operators/listen_and_serv_op.cc — RunSyncLoop :102, RunAsyncLoop :178):

sync mode, per round:
  1. every trainer pushes its gradients (SEND_VAR) then a BATCH_BARRIER;
  2. when all live trainers' barriers arrived, gradients are merged
     (sum / num_trainers — averaging half-batch mean-loss grads
     reproduces the full-batch gradient exactly) and the optimize blocks
     run against the pserver scope;
  3. parameter pulls (GET_VAR / PREFETCH) issued after a trainer's
     barrier block until that round's update is applied, then serve the
     fresh values; FETCH_BARRIER ends the trainer's round.

async mode: each SEND_VAR immediately runs that gradient's optimize
block (no barriers, no merge — the reference's async SGD).

A COMPLETE message retires a trainer; barriers re-evaluate against the
live set so stragglers don't deadlock (reference rpc_server.cc
DecreaseClientNum), and the server shuts down once every trainer
completed.

Liveness (round-4): a trainer that dies WITHOUT sending COMPLETE used
to stall every barrier forever. Every message now refreshes the
trainer's last-seen time, and barrier evaluation retires any trainer
silent for longer than `rpc_deadline` seconds (FLAGS_rpc_deadline —
the reference's client-side deadline, operators/distributed/
rpc_client.cc FLAGS_rpc_deadline, applied server-side where this
design keeps the round state). Retired-dead trainers are recorded in
`dead_tids`; the cluster finishes with the survivors instead of
deadlocking, and the server can shut down once every trainer is
accounted for (completed or dead).

Sparse merge: SelectedRows from several trainers concatenate rows/values
(duplicate rows are legal — optimizer scatter-adds merge them), then
values scale by 1/num_trainers in sync mode.

Idempotent replay: a reconnecting PSClient replays a request whose
reply was lost (see distributed/rpc.py). Every mutating handler
(SEND_VAR / BATCH_BARRIER / CHECKPOINT) consults a bounded per-trainer
dedup window keyed on the request's (incarnation, seq) token: an
already-applied mutation is acknowledged WITHOUT re-applying, so a
retried gradient or barrier never double-counts in a sync round
(`FLAGS_rpc_dedup_window` bounds the memory). Read-only handlers
(GET_VAR / PREFETCH) simply re-execute; COMPLETE is naturally
idempotent.
"""
from __future__ import annotations

import threading
from collections import deque

import numpy as np

__all__ = ['ParameterService']


class ParameterService(object):
    def __init__(self, num_trainers, sync_mode, get_param, run_round,
                 run_one_grad=None, prefetch=None, save_params=None,
                 rpc_deadline=None):
        """get_param(name) -> value; run_round(merged: {grad: value});
        run_one_grad(grad_name, value) for async; prefetch(table, ids);
        save_params(dirname) checkpoints this server's shard (the
        reference's RequestCheckpointHandler running the save block —
        listen_and_serv_op.cc:251 checkpoint_point_block_id).
        rpc_deadline: seconds of silence after which a trainer is
        declared dead and retired (None -> FLAGS_rpc_deadline)."""
        import time
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self._get_param = get_param
        self._save_params = save_params
        self._run_round = run_round
        self._run_one_grad = run_one_grad
        self._prefetch = prefetch
        if rpc_deadline is None:
            from ..flags import get_flag
            rpc_deadline = float(get_flag('rpc_deadline', 180.0))
        self.rpc_deadline = rpc_deadline
        # a trainer that has NEVER connected gets the larger of the
        # deadline and this grace: process spawn + jit compile of the
        # first step must not count as "silent death"
        self.first_contact_grace = max(rpc_deadline, 120.0)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = {}            # grad name -> {tid: value}
        self._barrier_tids = set()    # tids whose BATCH_BARRIER arrived
        self._trainer_rounds = {}     # tid -> rounds contributed
        self._completed_rounds = 0
        self._done_tids = set()
        self.dead_tids = set()        # retired by the liveness deadline
        self._error = None
        # every expected trainer's clock starts now: one that NEVER
        # connects must still be retireable
        self._start = time.monotonic()
        self._last_seen = {}          # tid -> monotonic last message
        self._barrier_ever = set()    # tids past their FIRST barrier
        # replay dedup: per-trainer window of applied (cli, seq) tokens
        from ..flags import get_flag
        self._dedup_window = int(get_flag('rpc_dedup_window', 512))
        self._seq_seen = {}           # tid -> set of tokens
        self._seq_order = {}          # tid -> deque (eviction order)

    # -- helpers -----------------------------------------------------------
    def _live_count(self):
        return self.num_trainers - len(self._done_tids)

    def _touch(self, tid):
        import time
        with self._lock:
            self._last_seen[tid] = time.monotonic()

    def _retire_dead_locked(self):
        """Retire every trainer silent past the deadline (the silent-
        death path: no COMPLETE will ever come). Known tids use their
        last message time; never-connected tids use service start."""
        import time
        now = time.monotonic()
        changed = False
        for tid in range(self.num_trainers):
            if tid in self._done_tids:
                continue
            # the tight deadline applies only once a trainer is in
            # steady state: past its FIRST barrier in sync mode (the
            # startup recv is followed by client-side program compile,
            # which must not count as silent death), or simply once
            # seen in async mode (which has no barriers at all)
            seen = self._last_seen.get(tid, self._start)
            steady = (tid in self._barrier_ever if self.sync_mode
                      else tid in self._last_seen)
            limit = (self.rpc_deadline if steady
                     else self.first_contact_grace)
            if now - seen > limit:
                self._done_tids.add(tid)
                self.dead_tids.add(tid)
                self._barrier_tids.discard(tid)
                # a trainer that died MID-PUSH must not contribute its
                # stale partial gradients to a round it never
                # barriered into
                for per_tid in self._pending.values():
                    per_tid.pop(tid, None)
                changed = True
        if changed:
            self._maybe_run_round_locked()
            self._cond.notify_all()
        return changed

    def _check_not_dead(self, tid):
        """Reject messages from a trainer already retired by the
        deadline: a slow-but-alive 'zombie' must fail loudly (the
        client surfaces the REPLY_ERR) instead of silently joining
        rounds whose live set no longer counts it."""
        if tid in self.dead_tids:
            raise RuntimeError(
                'trainer %d was retired by the liveness deadline '
                '(%.0f s silent) and may not rejoin this sync session'
                % (tid, self.rpc_deadline))

    def check_liveness(self):
        """Periodic liveness sweep (PSServer reaper thread). Returns
        True when every trainer is accounted for (completed or dead) —
        the server's shutdown condition."""
        with self._lock:
            self._retire_dead_locked()
            return len(self._done_tids) >= self.num_trainers

    def _merge(self, values):
        """Merge one grad's per-trainer values: sum, then average over the
        ORIGINAL trainer count (a retired trainer's mean-grad contribution
        is treated as zero for the remaining steps)."""
        from ..selected_rows import SelectedRows
        scale = 1.0 / float(self.num_trainers)
        vs = list(values)
        if isinstance(vs[0], SelectedRows):
            rows = np.concatenate([np.asarray(v.rows) for v in vs])
            vals = np.concatenate([np.asarray(v.values) for v in vs])
            return SelectedRows(vals * scale, rows.astype('int32'),
                                vs[0].height)
        out = np.asarray(vs[0], dtype=np.result_type(vs[0]))
        for v in vs[1:]:
            out = out + np.asarray(v)
        return out * scale

    def _maybe_run_round_locked(self):
        if not self._barrier_tids:
            return
        if len(self._barrier_tids) < self._live_count():
            return
        merged = {g: self._merge(per_tid.values())
                  for g, per_tid in self._pending.items() if per_tid}
        try:
            self._run_round(merged)
        except Exception as e:
            self._error = e
            raise
        finally:
            self._pending.clear()
            self._barrier_tids.clear()
            self._completed_rounds += 1
            self._cond.notify_all()

    def _wait_for_trainer_round_locked(self, tid):
        """Block until every round this trainer contributed to is applied
        (its own GET arrives, by per-connection ordering, after its
        BATCH_BARRIER). Each wakeup sweeps for dead peers so a silently
        dying trainer cannot stall the waiters forever."""
        import time
        while self._completed_rounds < self._trainer_rounds.get(tid, 0):
            if self._error is not None:
                raise RuntimeError('pserver optimize failed: %s'
                                   % self._error)
            # the waiter itself is NOT silent — it has an in-flight
            # request parked here; without this refresh a long round
            # wait would get the live waiter retired as dead
            self._last_seen[tid] = time.monotonic()
            self._retire_dead_locked()
            if self._completed_rounds >= self._trainer_rounds.get(tid, 0):
                break
            self._cond.wait(timeout=1.0)

    def _enter_locked(self, tid):
        """Touch + liveness check under the CALLER's lock: check and
        state mutation must be one atomic section, or a handler thread
        descheduled between them can re-insert a retired trainer's
        state after the reaper cleaned it."""
        import time
        self._last_seen[tid] = time.monotonic()
        self._check_not_dead(tid)

    def _is_replay_locked(self, tid, token):
        """Has this (cli, seq) token already been applied for tid?"""
        return token is not None and token in self._seq_seen.get(tid, ())

    def _record_seq_locked(self, tid, token):
        """Record an APPLIED mutation token; evict the oldest past the
        window. Recording happens after the mutation so a handler that
        raised leaves the token unrecorded — the client's replay gets a
        real re-attempt, not a phantom ack."""
        if token is None:
            return
        seen = self._seq_seen.setdefault(tid, set())
        if token in seen:
            return
        order = self._seq_order.setdefault(tid, deque())
        seen.add(token)
        order.append(token)
        while len(order) > self._dedup_window:
            seen.discard(order.popleft())

    # -- service interface (called from PSServer threads) ------------------
    def on_send_var(self, name, tid, value, seq=None):
        with self._lock:
            self._enter_locked(tid)
            if self._is_replay_locked(tid, seq):
                return   # applied already; the lost reply is re-acked
            if not self.sync_mode and self._run_one_grad is not None:
                self._run_one_grad(name, value)
                self._record_seq_locked(tid, seq)
                return
            self._pending.setdefault(name, {})[tid] = value
            self._record_seq_locked(tid, seq)

    def on_batch_barrier(self, tid, seq=None):
        with self._lock:
            self._enter_locked(tid)
            if self._is_replay_locked(tid, seq):
                return   # the round this barrier closed already ran
            self._barrier_ever.add(tid)
            self._barrier_tids.add(tid)
            self._trainer_rounds[tid] = self._trainer_rounds.get(tid, 0) + 1
            self._record_seq_locked(tid, seq)
            self._maybe_run_round_locked()

    def on_get_var(self, name, tid):
        with self._lock:
            self._enter_locked(tid)
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            return self._get_param(name)

    def on_prefetch(self, name, tid, ids):
        if self._prefetch is None:
            raise RuntimeError('this pserver hosts no lookup table')
        with self._lock:
            self._enter_locked(tid)
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            return self._prefetch(name, np.asarray(ids))

    def on_checkpoint(self, dirname, tid, seq=None):
        if self._save_params is None:
            raise RuntimeError('this pserver has no checkpoint support')
        with self._lock:
            self._enter_locked(tid)
            if self._is_replay_locked(tid, seq):
                return   # shard already saved for this request
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            self._save_params(dirname)
            self._record_seq_locked(tid, seq)

    def on_fetch_barrier(self, tid):
        self._touch(tid)  # round already closed by the on_get_var wait

    def on_complete(self, tid):
        with self._lock:
            # same zombie rejection as every other handler: a
            # deadline-retired trainer's COMPLETE must fail loudly, not
            # silently shrink the expected-completions set
            self._enter_locked(tid)
            self._done_tids.add(tid)
            self._barrier_tids.discard(tid)
            # a straggler-free round may now be unblocked
            self._maybe_run_round_locked()
            return len(self._done_tids) >= self.num_trainers
