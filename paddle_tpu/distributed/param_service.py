"""Parameter service: the listen_and_serv sync/async loop state machine.

Semantics transplanted from the reference pserver
(operators/listen_and_serv_op.cc — RunSyncLoop :102, RunAsyncLoop :178):

sync mode, per round:
  1. every trainer pushes its gradients (SEND_VAR) then a BATCH_BARRIER;
  2. when all live trainers' barriers arrived, gradients are merged
     (sum / num_trainers — averaging half-batch mean-loss grads
     reproduces the full-batch gradient exactly) and the optimize blocks
     run against the pserver scope;
  3. parameter pulls (GET_VAR / PREFETCH) issued after a trainer's
     barrier block until that round's update is applied, then serve the
     fresh values; FETCH_BARRIER ends the trainer's round.

async mode: each SEND_VAR immediately runs that gradient's optimize
block (no barriers, no merge — the reference's async SGD).

A COMPLETE message retires a trainer; barriers re-evaluate against the
live set so stragglers don't deadlock (reference rpc_server.cc
DecreaseClientNum), and the server shuts down once every trainer
completed.

Liveness (round-4): a trainer that dies WITHOUT sending COMPLETE used
to stall every barrier forever. Every message now refreshes the
trainer's last-seen time, and barrier evaluation retires any trainer
silent for longer than `rpc_deadline` seconds (FLAGS_rpc_deadline —
the reference's client-side deadline, operators/distributed/
rpc_client.cc FLAGS_rpc_deadline, applied server-side where this
design keeps the round state). Retired-dead trainers are recorded in
`dead_tids`; the cluster finishes with the survivors instead of
deadlocking, and the server can shut down once every trainer is
accounted for (completed or dead).

Sparse merge: SelectedRows from several trainers concatenate rows/values
(duplicate rows are legal — optimizer scatter-adds merge them), then
values scale by 1/num_trainers in sync mode (or 1/live_count under
FLAGS_ps_average_live — see _merge).

Idempotent replay: a reconnecting PSClient replays a request whose
reply was lost (see distributed/rpc.py). Every mutating handler
(SEND_VAR / BATCH_BARRIER / CHECKPOINT) consults a bounded per-trainer
dedup window keyed on the request's (incarnation, seq) token: an
already-applied mutation is acknowledged WITHOUT re-applying, so a
retried gradient or barrier never double-counts in a sync round
(`FLAGS_rpc_dedup_window` bounds the memory). Read-only handlers
(GET_VAR / PREFETCH) simply re-execute; COMPLETE is naturally
idempotent.

Elastic recovery (this PR) — either side of the connection may DIE and
come back:

**Trainer rejoin with incarnation fencing.** Every message carries the
trainer's *incarnation* number (`FLAGS_trainer_incarnation`, bumped by
the supervisor on each restart). A message whose incarnation is LOWER
than the registered one is a zombie from before a restart and is
rejected with the non-retryable `StaleIncarnationError`; one with a
HIGHER incarnation triggers `_rejoin_locked` — the permanent
`dead_tids` ban is lifted, the trainer's stale pending grads and
barrier are scrubbed, its dedup window is reset, and it re-enters the
live set at the next round boundary. The REGISTER handshake tells the
restarted trainer which step to resume from (`_trainer_rounds[tid]`);
SEND_VAR / BATCH_BARRIER additionally carry the trainer's step index
(`round_idx`) so a server that already closed that round ack-ignores
the replayed contribution instead of double-counting it — that is what
makes recovery land on bit-exact weights.

**Pserver durability.** With a `snapshot_path`, the service snapshots
params + round counters + dedup windows + incarnations to an atomic
on-disk file every `snapshot_every` rounds (statefile.atomic_replace,
mirroring Master.save_state), and journals every applied mutation
between snapshots as raw wire frames (wire.pack_msg) to
`<snapshot_path>.journal`, flushed per record. A restarted server
restores the snapshot, replays the journal through the same handlers
(`_replaying` suppresses re-journaling and re-snapshotting), and is
bit-exactly back at the kill point: the only in-flight request the
journal can miss is the one whose reply was never sent, and PR 1's
client retry layer replays exactly that one. A crash BETWEEN the
snapshot replace and the journal rotation is safe too — replayed
pre-snapshot records are absorbed by the snapshotted dedup windows and
round tags.

**Corruption defense.** Every snapshot carries a crc32 digest sidecar
(statefile.write_digest) and every journal record carries the wire
frame's own CRC. Snapshots rotate through two generations: writing
snapshot S_k moves the previous one to `<path>.prev` and the journal
(covering [S_{k-1}, S_k)) to `<path>.journal.prev`, so restore can fall
back a full generation: a snapshot that fails its digest (or does not
load) is quarantined to `<path>.corrupt` — kept on disk for
post-mortem — and the `.prev` snapshot plus BOTH journals replay to the
exact same state (pre-snapshot records are absorbed, same argument as
the crash window above). A journal frame that fails its CRC ends
replay at the last good record — the consistent prefix — with a loud
warning, and the damaged file is quarantined. After any quarantine the
service immediately persists a fresh verified snapshot and retires the
older generation (its journal continuity is broken: pairing a stale
snapshot with a later-era journal would silently lose the recovered
prefix). If every generation is corrupt, the service starts from
initial state LOUDLY rather than replay journal deltas against a lost
base. A torn trailing journal record (mid-write crash) is truncated
before the journal reopens for append — appending after torn bytes
would corrupt the framing of everything that follows.

With `check_grad_finite` (FLAGS_ps_check_grad_finite, default on), a
SEND_VAR whose float payload contains NaN/Inf is rejected BEFORE it is
journaled or applied, with a retryable error: a poisoned gradient (bit
corruption that survived transport, or a diverging trainer) never
enters the durable state, and the client's in-place retry re-sends the
value it actually computed — if that one is clean (transient fault),
training proceeds bit-exactly.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque

import numpy as np

from ..obs import telemetry as _tm

__all__ = ['ParameterService']

# pserver durability + dedup health (no-ops while FLAGS_obs_dir is
# unset): a chaos-run rollup with nonzero replay/journal/snapshot
# counters is the evidence the recovery machinery actually fired
_DEDUP_HITS = _tm.counter('ps.dedup_replay_hits')
_STALE_ROUND_ACKS = _tm.counter('ps.stale_round_acks')
_NONFINITE_REJECTED = _tm.counter('ps.nonfinite_grads_rejected')
_ROUNDS = _tm.counter('ps.rounds_completed')
_JOURNAL_APPENDS = _tm.counter('ps.journal.appends')
_JOURNAL_REPLAYED = _tm.counter('ps.journal.replayed_frames')
_SNAP_WRITES = _tm.counter('ps.snapshot.writes')
_SNAP_RESTORES = _tm.counter('ps.snapshot.restores')
# online refresh (paddle_tpu/online/): the version this shard currently
# publishes, and how many GET_VARS shard pulls it served
_PARAM_VERSION = _tm.gauge('ps.param_version')
_VERSION_PULLS = _tm.counter('ps.version_pulls')


class ParameterService(object):
    def __init__(self, num_trainers, sync_mode, get_param, run_round,
                 run_one_grad=None, prefetch=None, save_params=None,
                 rpc_deadline=None, snapshot_path=None,
                 snapshot_every=None, dump_state=None, load_state=None,
                 average_live=None, param_names=None):
        """get_param(name) -> value; run_round(merged: {grad: value});
        run_one_grad(grad_name, value) for async; prefetch(table, ids);
        save_params(dirname) checkpoints this server's shard (the
        reference's RequestCheckpointHandler running the save block —
        listen_and_serv_op.cc:251 checkpoint_point_block_id).
        rpc_deadline: seconds of silence after which a trainer is
        declared dead and retired (None -> FLAGS_rpc_deadline).
        snapshot_path (None -> FLAGS_ps_state_path): enables crash
        durability — dump_state() -> {name: array} and
        load_state({name: array}) must then round-trip this shard's
        persistable scope; snapshot_every (None ->
        FLAGS_ps_snapshot_every) is the round period. average_live
        (None -> FLAGS_ps_average_live) switches _merge to the live-set
        denominator. param_names: the parameter block names this shard
        hosts — enables online-refresh version publication (GET_VERSION
        manifests + GET_VARS multi-pulls); None leaves those handlers
        serving an empty manifest."""
        import time
        from ..flags import get_flag
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self._get_param = get_param
        self._save_params = save_params
        self._run_round = run_round
        self._run_one_grad = run_one_grad
        self._prefetch = prefetch
        if rpc_deadline is None:
            rpc_deadline = float(get_flag('rpc_deadline', 180.0))
        self.rpc_deadline = rpc_deadline
        # a trainer that has NEVER connected gets the larger of the
        # deadline and this grace: process spawn + jit compile of the
        # first step must not count as "silent death"
        self.first_contact_grace = max(rpc_deadline, 120.0)
        if average_live is None:
            average_live = bool(get_flag('ps_average_live', False))
        self.average_live = average_live
        self.check_grad_finite = bool(get_flag('ps_check_grad_finite',
                                               True))

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = {}            # grad name -> {tid: value}
        self._barrier_tids = set()    # tids whose BATCH_BARRIER arrived
        self._trainer_rounds = {}     # tid -> rounds contributed
        self._completed_rounds = 0
        self._done_tids = set()
        self.dead_tids = set()        # retired by the liveness deadline
        self._incarnations = {}       # tid -> highest registered inc
        self._error = None
        # every expected trainer's clock starts now: one that NEVER
        # connects must still be retireable
        self._start = time.monotonic()
        self._last_seen = {}          # tid -> monotonic last message
        self._barrier_ever = set()    # tids past their FIRST barrier
        # replay dedup: per-trainer window of applied (cli, seq) tokens
        self._dedup_window = int(get_flag('rpc_dedup_window', 512))
        self._seq_seen = {}           # tid -> set of tokens
        self._seq_order = {}          # tid -> deque (eviction order)
        # -- online refresh ------------------------------------------------
        # monotonically increasing param version this shard publishes:
        # bumped at every sync round close (and per applied async grad),
        # so version == completed optimizer rounds on a fresh server.
        # The digest manifest (per-param crc32 over the exact bytes a
        # GET_VARS pull ships) is computed lazily per version and cached
        # — pollers pay the hash at most once per round, not per poll.
        self.param_names = list(param_names or ())
        self._param_version = 0
        self._manifest_cache = None   # (version, {name: crc32}) or None
        # -- durability ----------------------------------------------------
        if snapshot_path is None:
            snapshot_path = get_flag('ps_state_path', '') or None
        self.snapshot_path = snapshot_path
        if snapshot_every is None:
            snapshot_every = int(get_flag('ps_snapshot_every', 1))
        self.snapshot_every = max(1, int(snapshot_every))
        self._dump_state = dump_state
        self._load_state = load_state
        self._replaying = False
        self._journal_f = None
        self._async_applied = 0       # async mode: sends since snapshot
        self._restore_dirty = False   # restore quarantined corruption
        if self.snapshot_path:
            self._restore()
            if self._restore_dirty and self._dump_state is not None:
                # corruption was quarantined during restore: the
                # in-memory state (surviving snapshot + journal prefix)
                # is the only trustworthy copy — persist it as a fresh
                # verified generation before serving
                with self._lock:
                    self._recover_generations_locked()
            if self._journal_f is None:
                self._journal_open()

    # -- helpers -----------------------------------------------------------
    def _live_count(self):
        return self.num_trainers - len(self._done_tids)

    def _touch(self, tid):
        import time
        with self._lock:
            self._last_seen[tid] = time.monotonic()

    def _retire_dead_locked(self):
        """Retire every trainer silent past the deadline (the silent-
        death path: no COMPLETE will ever come). Known tids use their
        last message time; never-connected tids use service start."""
        import time
        now = time.monotonic()
        changed = False
        for tid in range(self.num_trainers):
            if tid in self._done_tids:
                continue
            # the tight deadline applies only once a trainer is in
            # steady state: past its FIRST barrier in sync mode (the
            # startup recv is followed by client-side program compile,
            # which must not count as silent death), or simply once
            # seen in async mode (which has no barriers at all)
            seen = self._last_seen.get(tid, self._start)
            steady = (tid in self._barrier_ever if self.sync_mode
                      else tid in self._last_seen)
            limit = (self.rpc_deadline if steady
                     else self.first_contact_grace)
            if now - seen > limit:
                self._done_tids.add(tid)
                self.dead_tids.add(tid)
                self._barrier_tids.discard(tid)
                # a trainer that died MID-PUSH must not contribute its
                # stale partial gradients to a round it never
                # barriered into
                for per_tid in self._pending.values():
                    per_tid.pop(tid, None)
                changed = True
        if changed:
            self._maybe_run_round_locked()
            self._cond.notify_all()
        return changed

    def _check_not_dead(self, tid):
        """Reject messages from a trainer already retired by the
        deadline: a slow-but-alive 'zombie' must fail loudly (the
        client surfaces the REPLY_ERR) instead of silently joining
        rounds whose live set no longer counts it. Rejoining is
        possible, but only as a FRESH incarnation (restart with a
        higher FLAGS_trainer_incarnation and re-register)."""
        if tid in self.dead_tids:
            raise RuntimeError(
                'trainer %d was retired by the liveness deadline '
                '(%.0f s silent) and may not rejoin this sync session '
                'with the same incarnation; restart it with a higher '
                'FLAGS_trainer_incarnation to re-register'
                % (tid, self.rpc_deadline))

    def _fence_locked(self, tid, inc):
        """Incarnation fence. A LOWER incarnation than the registered
        one is a zombie process from before a restart: reject it
        non-retryably. A HIGHER one is the restarted trainer announcing
        itself — rejoin it on the spot (REGISTER is the polite path,
        but any message may arrive first after a restart)."""
        from .resilience import StaleIncarnationError
        inc = int(inc or 0)
        cur = self._incarnations.get(tid, 0)
        if inc < cur:
            raise StaleIncarnationError(
                'trainer %d message carries incarnation %d but '
                'incarnation %d is registered: stale pre-restart '
                'zombie, not retryable' % (tid, inc, cur))
        if inc > cur:
            self._rejoin_locked(tid, inc)
            return True
        return False

    def _rejoin_locked(self, tid, inc):
        """Re-admit a restarted trainer under a new incarnation: lift
        the dead ban, scrub every trace of the previous incarnation
        (pending grads, barrier membership, dedup window), and restart
        the first-contact grace clock — the fresh process has to re-jit
        before its first barrier, exactly like a cold start. The
        trainer re-enters the live set immediately, so the next round
        boundary waits for its barrier. Returns whether the tid had
        been retired as dead."""
        import time
        was_dead = tid in self.dead_tids
        self._incarnations[tid] = int(inc)
        self.dead_tids.discard(tid)
        self._done_tids.discard(tid)
        self._barrier_tids.discard(tid)
        for per_tid in self._pending.values():
            per_tid.pop(tid, None)
        self._barrier_ever.discard(tid)
        self._last_seen[tid] = time.monotonic()
        self._seq_seen.pop(tid, None)
        self._seq_order.pop(tid, None)
        self._cond.notify_all()
        return was_dead

    def check_liveness(self):
        """Periodic liveness sweep (PSServer reaper thread). Returns
        True when every trainer is accounted for (completed or dead) —
        the server's shutdown condition. A retired-then-rejoined
        trainer is live again (rejoin removed it from _done_tids), so
        the server keeps serving while its new incarnation is in
        flight."""
        with self._lock:
            self._retire_dead_locked()
            return len(self._done_tids) >= self.num_trainers

    def _merge(self, values):
        """Merge one grad's per-trainer values: sum, then average.

        Default denominator is the ORIGINAL `num_trainers` — a retired
        trainer's mean-grad contribution is treated as zero, which
        silently SHRINKS the effective LR as trainers die but keeps
        surviving-set runs bit-comparable to the full-set run.
        `FLAGS_ps_average_live` switches to the live-set denominator:
        the update stays a true mean of the contributions (constant
        effective LR), at the cost of weights diverging from the
        full-set baseline the moment a trainer dies."""
        from ..selected_rows import SelectedRows
        denom = self._live_count() if self.average_live \
            else self.num_trainers
        scale = 1.0 / float(max(1, denom))
        vs = list(values)
        if isinstance(vs[0], SelectedRows):
            rows = np.concatenate([np.asarray(v.rows) for v in vs])
            vals = np.concatenate([np.asarray(v.values) for v in vs])
            return SelectedRows(vals * scale, rows.astype('int32'),
                                vs[0].height)
        out = np.asarray(vs[0], dtype=np.result_type(vs[0]))
        for v in vs[1:]:
            out = out + np.asarray(v)
        return out * scale

    def _maybe_run_round_locked(self):
        if not self._barrier_tids:
            return
        if len(self._barrier_tids) < self._live_count():
            return
        merged = {g: self._merge(per_tid.values())
                  for g, per_tid in self._pending.items() if per_tid}
        try:
            self._run_round(merged)
        except Exception as e:
            self._error = e
            raise
        finally:
            self._pending.clear()
            self._barrier_tids.clear()
            self._completed_rounds += 1
            _ROUNDS.inc()
            # the round's weights are final RIGHT HERE: publish them as
            # a new param version (online subscribers poll GET_VERSION
            # and pull the freshly-closed round's params)
            self._bump_version_locked()
            # pending is empty RIGHT NOW — the cheapest instant for a
            # consistent snapshot; the barrier that closed this round
            # is acked only after the snapshot is durable
            self._maybe_snapshot_locked()
            self._cond.notify_all()

    def _wait_for_trainer_round_locked(self, tid):
        """Block until every round this trainer contributed to is applied
        (its own GET arrives, by per-connection ordering, after its
        BATCH_BARRIER). Each wakeup sweeps for dead peers so a silently
        dying trainer cannot stall the waiters forever."""
        import time
        while self._completed_rounds < self._trainer_rounds.get(tid, 0):
            if self._error is not None:
                raise RuntimeError('pserver optimize failed: %s'
                                   % self._error)
            # the waiter itself is NOT silent — it has an in-flight
            # request parked here; without this refresh a long round
            # wait would get the live waiter retired as dead
            self._last_seen[tid] = time.monotonic()
            self._retire_dead_locked()
            if self._completed_rounds >= self._trainer_rounds.get(tid, 0):
                break
            self._cond.wait(timeout=1.0)

    # -- online refresh ----------------------------------------------------
    def _bump_version_locked(self):
        self._param_version += 1
        self._manifest_cache = None
        _PARAM_VERSION.set(self._param_version)

    def _manifest_locked(self):
        """{param block name: crc32 of its wire payload bytes} for the
        CURRENT version, cached until the next bump. The digest covers
        the exact canonical bytes a GET_VARS pull ships (_payload_of),
        so subscriber-side verification is a byte-identity check, not a
        float comparison."""
        if self._manifest_cache is not None \
                and self._manifest_cache[0] == self._param_version:
            return self._manifest_cache[1]
        from . import wire
        from ..integrity import crc32
        digests = {}
        for name in self.param_names:
            _, payload = wire._payload_of(self._get_param(name))
            digests[name] = crc32(payload)
        self._manifest_cache = (self._param_version, digests)
        return digests

    def on_get_version(self, tid, inc=None, with_manifest=False):
        """Current published param version (reply meta). With
        with_manifest, the per-param digest manifest rides along — the
        subscriber learns WHAT this shard hosts and what bytes version
        N's params must hash to."""
        with self._lock:
            self._enter_locked(tid, inc)
            out = {'version': self._param_version}
            if with_manifest:
                out['manifest'] = self._manifest_locked()
            return out

    def on_get_vars(self, names, tid, inc=None):
        """Atomic multi-param read for an online subscriber: every
        requested param plus its digest, all read under ONE lock hold —
        a version-consistent shard image even while trainers are
        pushing the next round. Returns (version, [(entry_meta, value),
        ...]) for the server to pack into one REPLY_VAR frame."""
        with self._lock:
            self._enter_locked(tid, inc)
            manifest = self._manifest_locked()
            items = []
            for name in names:
                e = {'name': name}
                if name in manifest:
                    e['digest'] = manifest[name]
                items.append((e, self._get_param(name)))
            _VERSION_PULLS.inc()
            return self._param_version, items

    def _enter_locked(self, tid, inc=None):
        """Fence + touch + liveness check under the CALLER's lock:
        check and state mutation must be one atomic section, or a
        handler thread descheduled between them can re-insert a retired
        trainer's state after the reaper cleaned it."""
        import time
        self._fence_locked(tid, inc)
        self._last_seen[tid] = time.monotonic()
        self._check_not_dead(tid)

    def _is_replay_locked(self, tid, token):
        """Has this (cli, seq) token already been applied for tid?"""
        hit = token is not None and token in self._seq_seen.get(tid, ())
        if hit:
            _DEDUP_HITS.inc()
        return hit

    def _record_seq_locked(self, tid, token):
        """Record an APPLIED mutation token; evict the oldest past the
        window. Recording happens after the mutation so a handler that
        raised leaves the token unrecorded — the client's replay gets a
        real re-attempt, not a phantom ack."""
        if token is None:
            return
        token = tuple(token)
        seen = self._seq_seen.setdefault(tid, set())
        if token in seen:
            return
        order = self._seq_order.setdefault(tid, deque())
        seen.add(token)
        order.append(token)
        while len(order) > self._dedup_window:
            seen.discard(order.popleft())

    def _stale_round_locked(self, tid, round_idx):
        """True when a SEND_VAR/BATCH_BARRIER carries a step index from
        a round this server already closed for the trainer — a
        restarted trainer resuming at the min-across-servers step
        replays rounds an ahead server has applied; ack-ignoring them
        (rather than erroring) lets the trainer's step counter catch up
        to every shard without double-counting anywhere."""
        stale = (round_idx is not None
                 and int(round_idx) < self._trainer_rounds.get(tid, 0))
        if stale:
            _STALE_ROUND_ACKS.inc()
        return stale

    # -- durability --------------------------------------------------------
    def _journal_path(self):
        return self.snapshot_path + '.journal'

    def _journal_open(self):
        self._journal_f = open(self._journal_path(), 'ab')

    def _journal_rotate_locked(self):
        """Move the journal to `.prev`, pairing it with the snapshot
        generation that was just rotated to `.prev`: everything in it
        is covered by the snapshot that was just written, and a
        fallback restore replays it on the `.prev` snapshot."""
        jpath = self._journal_path()
        if self._journal_f is not None:
            self._journal_f.close()
        if os.path.exists(jpath):
            os.replace(jpath, jpath + '.prev')
        self._journal_f = open(jpath, 'wb')

    def _journal_locked(self, msg_type, meta, value=None):
        """Append one applied mutation as a wire frame, flushed to the
        OS before the handler returns (and therefore before the client
        sees the ack). flush — not fsync — is deliberate: os._exit /
        kill -9 cannot lose kernel page-cache data, and process death
        is the failure mode this journal exists for."""
        if self._journal_f is None or self._replaying:
            return
        from . import wire
        self._journal_f.write(wire.pack_msg(msg_type, meta, value=value))
        self._journal_f.flush()
        _JOURNAL_APPENDS.inc()

    def _maybe_snapshot_locked(self):
        if (self.snapshot_path and self._dump_state is not None
                and not self._replaying
                and self._completed_rounds % self.snapshot_every == 0):
            self._snapshot_locked()

    def _snapshot_locked(self, rotate=True):
        """Atomically persist params + every piece of round/replay state
        a restarted server needs to keep serving mid-session, with a
        crc32 digest sidecar. `rotate` keeps a `.prev` generation of
        both the snapshot and the journal for corruption fallback;
        recovery-time snapshots pass rotate=False because the retired
        generations' journal continuity is broken."""
        from . import statefile
        from .statefile import atomic_replace
        state = {
            'completed_rounds': self._completed_rounds,
            'trainer_rounds': {str(k): v
                               for k, v in self._trainer_rounds.items()},
            'done_tids': sorted(self._done_tids),
            'dead_tids': sorted(self.dead_tids),
            'barrier_ever': sorted(self._barrier_ever),
            'incarnations': {str(k): v
                             for k, v in self._incarnations.items()},
            'seq_order': {str(k): [list(t) for t in v]
                          for k, v in self._seq_order.items()},
            'param_version': self._param_version,
        }
        arrays = {'p:' + name: np.asarray(val)
                  for name, val in self._dump_state().items()}
        arrays['__state__'] = np.frombuffer(
            json.dumps(state).encode('utf-8'), dtype=np.uint8)
        # np.savez appends '.npz' to a path STRING but writes an open
        # handle verbatim — go through the handle so the atomic-replace
        # target name is exact. Stage under `.next` so the generation
        # rotation below is rename-only (no window where the current
        # snapshot is gone and the new one is half-written).
        staging = self.snapshot_path + '.next'
        with atomic_replace(staging) as f:
            np.savez(f, **arrays)
        statefile.write_digest(staging)
        if rotate:
            if os.path.exists(self.snapshot_path):
                statefile.move_with_digest(self.snapshot_path,
                                           self.snapshot_path + '.prev')
            statefile.move_with_digest(staging, self.snapshot_path)
            self._journal_rotate_locked()
        else:
            statefile.move_with_digest(staging, self.snapshot_path)
            if self._journal_f is not None:
                self._journal_f.close()
            self._journal_f = open(self._journal_path(), 'wb')
        _SNAP_WRITES.inc()

    def _recover_generations_locked(self):
        """After a restore that quarantined corruption: retire every
        older on-disk generation and persist the recovered in-memory
        state as a fresh verified snapshot. The old `.prev`/journal
        files must go — after recovery their continuity is broken, and
        a stale snapshot paired with a later-era journal would
        silently lose the recovered prefix on a future fallback."""
        from . import statefile
        jpath = self._journal_path()
        for p in (self.snapshot_path + '.prev', jpath + '.prev', jpath):
            for q in (p, statefile.digest_path(p)):
                try:
                    os.remove(q)
                except OSError:
                    pass
        self._snapshot_locked(rotate=False)

    def _restore(self):
        """Snapshot + journal replay: called once from __init__, before
        any connection is accepted.

        Corruption policy: a snapshot that fails its digest sidecar (or
        does not load) is quarantined and restore falls back to the
        `.prev` generation; replaying `.journal.prev` + `.journal` on
        it reaches the exact same state (pre-snapshot records are
        absorbed by the snapshotted dedup windows and round tags). If
        every generation is corrupt, the journals are quarantined too
        and the service starts from initial state LOUDLY — journal
        records are deltas against a lost base, and replaying them on
        fresh params would fabricate a state that never existed."""
        import sys
        from . import statefile
        snap = self.snapshot_path
        jpath = self._journal_path()
        loaded, existed = None, False
        for cand in (snap, snap + '.prev'):
            if not os.path.exists(cand):
                continue
            existed = True
            status = statefile.verify_digest(cand)
            if status == 'mismatch':
                statefile.quarantine(cand, 'snapshot digest mismatch')
                self._restore_dirty = True
                continue
            if status == 'missing':
                sys.stderr.write(
                    'WARNING: snapshot %s has no digest sidecar '
                    '(pre-digest file or a crash before the sidecar '
                    'write); accepting it unverified\n' % cand)
            try:
                with np.load(cand) as z:
                    state = json.loads(bytes(z['__state__'].data)
                                       .decode('utf-8'))
                    params = {k[len('p:'):]: np.array(z[k])
                              for k in z.files if k.startswith('p:')}
            except Exception as e:
                statefile.quarantine(cand, 'unreadable snapshot: %r' % e)
                self._restore_dirty = True
                continue
            if self._load_state is not None:
                self._load_state(params)
            self._completed_rounds = int(state['completed_rounds'])
            self._trainer_rounds = {int(k): v for k, v
                                    in state['trainer_rounds'].items()}
            self._done_tids = set(state['done_tids'])
            self.dead_tids = set(state['dead_tids'])
            self._barrier_ever = set(state['barrier_ever'])
            self._incarnations = {int(k): v for k, v
                                  in state['incarnations'].items()}
            for k, toks in state['seq_order'].items():
                tid = int(k)
                self._seq_order[tid] = deque(tuple(t) for t in toks)
                self._seq_seen[tid] = set(self._seq_order[tid])
            # pre-online snapshots carry no version: resume publication
            # at the restored round count (the fresh-server identity)
            self._param_version = int(
                state.get('param_version', self._completed_rounds))
            self._manifest_cache = None
            loaded = cand
            _SNAP_RESTORES.inc()
            if cand != snap:
                sys.stderr.write('WARNING: restored from previous '
                                 'snapshot generation %s\n' % cand)
            break
        if existed and loaded is None:
            sys.stderr.write(
                'WARNING: every snapshot generation of %s is corrupt '
                '(quarantined); the journals are deltas against the '
                'lost snapshots and cannot be replayed — starting from '
                'initial state\n' % snap)
            for jp in (jpath + '.prev', jpath):
                if os.path.exists(jp):
                    statefile.quarantine(jp, 'journal without a '
                                             'replayable base snapshot')
            return
        # replay oldest-first; records already covered by the loaded
        # snapshot are absorbed (dedup windows + round tags)
        for jp in (jpath + '.prev', jpath):
            if not os.path.exists(jp):
                continue
            if not self._replay_journal(jp):
                # corruption ends replay at the consistent prefix: the
                # damaged file AND anything after it (a later era that
                # cannot be applied over the gap) are quarantined
                self._restore_dirty = True
                statefile.quarantine(jp, 'corrupt journal frame')
                if jp != jpath and os.path.exists(jpath):
                    statefile.quarantine(
                        jpath, 'era follows a corrupt journal')
                return

    def _replay_journal(self, jp):
        """Replay one journal file through the live handlers. Returns
        False when a corrupt (CRC-failing) frame ended replay early; a
        torn trailing record is truncated in place (appending after
        torn bytes would corrupt the framing of every later record)."""
        import sys
        from . import wire
        with open(jp, 'rb') as f:
            buf = f.read()
        consumed = 0
        self._replaying = True
        try:
            for msg_type, meta, value, end in wire.scan_msgs(buf):
                self._replay_msg(msg_type, meta, value)
                consumed = end
                _JOURNAL_REPLAYED.inc()
        except wire.FrameCorruptError as e:
            sys.stderr.write(
                'WARNING: journal %s corrupt after %d clean bytes (%s); '
                'keeping the consistent prefix, quarantining the file\n'
                % (jp, consumed, e))
            return False
        finally:
            self._replaying = False
        if consumed < len(buf):
            sys.stderr.write(
                'WARNING: journal %s ends in a torn record (%d of %d '
                'bytes replayed) — expected after a mid-write crash; '
                'truncating the tail\n' % (jp, consumed, len(buf)))
            with open(jp, 'r+b') as f:
                f.truncate(consumed)
        return True

    def _replay_msg(self, msg_type, meta, value):
        """Re-dispatch one journaled mutation through the live
        handlers. CHECKPOINT replays token-only (re-saving the shard
        to a possibly-gone dirname is a side effect, not state)."""
        from . import wire
        tid = int(meta['tid'])
        tok = tuple(meta['tok']) if meta.get('tok') else None
        inc = meta.get('inc')
        if msg_type == wire.SEND_VAR:
            self.on_send_var(meta['name'], tid, value, seq=tok, inc=inc,
                             round_idx=meta.get('round'))
        elif msg_type == wire.BATCH_BARRIER:
            self.on_batch_barrier(tid, seq=tok, inc=inc,
                                  round_idx=meta.get('round'))
        elif msg_type == wire.COMPLETE:
            self.on_complete(tid, inc=inc)
        elif msg_type == wire.REGISTER:
            self.on_register(tid, inc=inc)
        elif msg_type == wire.CHECKPOINT:
            with self._lock:
                self._record_seq_locked(tid, tok)

    @staticmethod
    def _tok_meta(tid, seq, inc, round_idx=None, name=None):
        meta = {'tid': tid, 'tok': list(seq) if seq else None}
        if inc is not None:
            meta['inc'] = int(inc)
        if round_idx is not None:
            meta['round'] = int(round_idx)
        if name is not None:
            meta['name'] = name
        return meta

    # -- service interface (called from PSServer threads) ------------------
    def on_send_var(self, name, tid, value, seq=None, inc=None,
                    round_idx=None):
        from . import wire
        if (self.check_grad_finite and value is not None
                and not wire.value_is_finite(value)):
            # rejected BEFORE the journal write and BEFORE the dedup
            # window records the token: a poisoned gradient never
            # enters durable state, and the retryable classification
            # makes the client re-send the value it actually computed
            from .resilience import TransientError
            _NONFINITE_REJECTED.inc()
            raise TransientError(
                'non-finite gradient %r from trainer %s rejected '
                '(FLAGS_ps_check_grad_finite): corrupted or diverging '
                'update; the retry resends the computed value'
                % (name, tid))
        with self._lock:
            self._enter_locked(tid, inc)
            if self._is_replay_locked(tid, seq):
                return   # applied already; the lost reply is re-acked
            if self._stale_round_locked(tid, round_idx):
                return   # a resumed trainer replaying a closed round
            self._journal_locked(
                wire.SEND_VAR,
                self._tok_meta(tid, seq, inc, round_idx, name), value)
            if not self.sync_mode and self._run_one_grad is not None:
                self._run_one_grad(name, value)
                self._record_seq_locked(tid, seq)
                self._async_applied += 1
                # async has no rounds: every applied grad IS a publish
                # point (the reference's async-SGD staleness model)
                self._bump_version_locked()
                # async has no round boundary; snapshot on a send count
                if (self.snapshot_path and not self._replaying
                        and self._async_applied % 256 == 0):
                    self._snapshot_locked()
                return
            self._pending.setdefault(name, {})[tid] = value
            self._record_seq_locked(tid, seq)

    def on_send_vars(self, tid, entries, values, cli=None, inc=None):
        """Apply a batched SEND_VARS frame: each contained var carries
        its OWN (cli, seq) dedup token and round tag and goes through
        on_send_var exactly as an individual push would — including its
        own journal record, so the journal format (and crash replay)
        is unchanged. A replayed batch re-acks the already-applied vars
        and applies the rest: per-var at-most-once. A non-finite var
        rejects the whole frame (retryable); the vars applied before it
        were journaled + token-recorded, so the client's replay of the
        batch cannot double-apply them."""
        for e, value in zip(entries, values):
            tok = ((cli, e['seq']) if e.get('seq') is not None
                   else None)
            self.on_send_var(e['name'], tid, value, seq=tok, inc=inc,
                             round_idx=e.get('round'))

    def on_batch_barrier(self, tid, seq=None, inc=None, round_idx=None):
        from . import wire
        with self._lock:
            self._enter_locked(tid, inc)
            if self._is_replay_locked(tid, seq):
                return   # the round this barrier closed already ran
            if self._stale_round_locked(tid, round_idx):
                return   # ahead of a resumed trainer: round already ran
            self._journal_locked(
                wire.BATCH_BARRIER,
                self._tok_meta(tid, seq, inc, round_idx))
            self._barrier_ever.add(tid)
            self._barrier_tids.add(tid)
            if round_idx is not None:
                self._trainer_rounds[tid] = max(
                    self._trainer_rounds.get(tid, 0), int(round_idx) + 1)
            else:
                self._trainer_rounds[tid] = \
                    self._trainer_rounds.get(tid, 0) + 1
            self._record_seq_locked(tid, seq)
            self._maybe_run_round_locked()

    def on_get_var(self, name, tid, inc=None):
        with self._lock:
            self._enter_locked(tid, inc)
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            return self._get_param(name)

    def on_prefetch(self, name, tid, ids, inc=None):
        if self._prefetch is None:
            raise RuntimeError('this pserver hosts no lookup table')
        with self._lock:
            self._enter_locked(tid, inc)
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            return self._prefetch(name, np.asarray(ids))

    def on_checkpoint(self, dirname, tid, seq=None, inc=None):
        from . import wire
        if self._save_params is None:
            raise RuntimeError('this pserver has no checkpoint support')
        with self._lock:
            self._enter_locked(tid, inc)
            if self._is_replay_locked(tid, seq):
                return   # shard already saved for this request
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            self._save_params(dirname)
            self._journal_locked(wire.CHECKPOINT,
                                 self._tok_meta(tid, seq, inc))
            self._record_seq_locked(tid, seq)

    def on_fetch_barrier(self, tid, inc=None):
        # the round already closed by the on_get_var wait, but a zombie
        # or stale-incarnation FETCH_BARRIER must still fail loudly —
        # same _enter_locked gate as every other handler
        with self._lock:
            self._enter_locked(tid, inc)

    def on_register(self, tid, inc=None, seq=None):
        """The (re)join handshake. Reply tells the trainer where it
        stands on THIS shard: `round` (server rounds applied),
        `expected` (the step index this server expects from the trainer
        next — its resume point), `rejoined` (whether the tid had been
        retired as dead). A restarted trainer resumes at the MINIMUM
        `expected` across shards and relies on the stale-round
        ack-ignore to catch the ahead ones up."""
        import time
        from . import wire
        from .resilience import StaleIncarnationError
        with self._lock:
            inc = int(inc or 0)
            cur = self._incarnations.get(tid, 0)
            if inc < cur:
                raise StaleIncarnationError(
                    'trainer %d REGISTER carries incarnation %d but '
                    'incarnation %d is registered: stale pre-restart '
                    'zombie, not retryable' % (tid, inc, cur))
            rejoined = False
            if inc > cur:
                self._journal_locked(wire.REGISTER,
                                     self._tok_meta(tid, seq, inc))
                rejoined = self._rejoin_locked(tid, inc)
            else:
                # first contact (inc == cur == 0) or a replayed
                # REGISTER whose rejoin already happened: idempotent
                self._check_not_dead(tid)
                self._incarnations.setdefault(tid, inc)
                self._last_seen[tid] = time.monotonic()
            return {'round': self._completed_rounds,
                    'expected': self._trainer_rounds.get(tid, 0),
                    'rejoined': rejoined}

    def on_complete(self, tid, inc=None):
        from . import wire
        with self._lock:
            if tid >= self.num_trainers:
                # a serving-side client (rpc.SERVING_TID_BASE range)
                # closing its connection: it was never part of the
                # training contract, so its COMPLETE must not count
                # toward (or trip) the all-trainers-done shutdown
                return False
            # same zombie rejection as every other handler: a
            # deadline-retired trainer's COMPLETE must fail loudly, not
            # silently shrink the expected-completions set
            self._enter_locked(tid, inc)
            self._journal_locked(wire.COMPLETE,
                                 self._tok_meta(tid, None, inc))
            self._done_tids.add(tid)
            self._barrier_tids.discard(tid)
            # a straggler-free round may now be unblocked
            self._maybe_run_round_locked()
            return len(self._done_tids) >= self.num_trainers
