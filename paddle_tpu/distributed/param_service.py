"""Parameter service: the listen_and_serv sync/async loop state machine.

Semantics transplanted from the reference pserver
(operators/listen_and_serv_op.cc — RunSyncLoop :102, RunAsyncLoop :178):

sync mode, per round:
  1. every trainer pushes its gradients (SEND_VAR) then a BATCH_BARRIER;
  2. when all live trainers' barriers arrived, gradients are merged
     (sum / num_trainers — averaging half-batch mean-loss grads
     reproduces the full-batch gradient exactly) and the optimize blocks
     run against the pserver scope;
  3. parameter pulls (GET_VAR / PREFETCH) issued after a trainer's
     barrier block until that round's update is applied, then serve the
     fresh values; FETCH_BARRIER ends the trainer's round.

async mode: each SEND_VAR immediately runs that gradient's optimize
block (no barriers, no merge — the reference's async SGD).

A COMPLETE message retires a trainer; barriers re-evaluate against the
live set so stragglers don't deadlock (reference rpc_server.cc
DecreaseClientNum), and the server shuts down once every trainer
completed.

Sparse merge: SelectedRows from several trainers concatenate rows/values
(duplicate rows are legal — optimizer scatter-adds merge them), then
values scale by 1/num_trainers in sync mode.
"""
from __future__ import annotations

import threading

import numpy as np

__all__ = ['ParameterService']


class ParameterService(object):
    def __init__(self, num_trainers, sync_mode, get_param, run_round,
                 run_one_grad=None, prefetch=None, save_params=None):
        """get_param(name) -> value; run_round(merged: {grad: value});
        run_one_grad(grad_name, value) for async; prefetch(table, ids);
        save_params(dirname) checkpoints this server's shard (the
        reference's RequestCheckpointHandler running the save block —
        listen_and_serv_op.cc:251 checkpoint_point_block_id)."""
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self._get_param = get_param
        self._save_params = save_params
        self._run_round = run_round
        self._run_one_grad = run_one_grad
        self._prefetch = prefetch

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = {}            # grad name -> {tid: value}
        self._barrier_tids = set()    # tids whose BATCH_BARRIER arrived
        self._trainer_rounds = {}     # tid -> rounds contributed
        self._completed_rounds = 0
        self._done_tids = set()
        self._error = None

    # -- helpers -----------------------------------------------------------
    def _live_count(self):
        return self.num_trainers - len(self._done_tids)

    def _merge(self, values):
        """Merge one grad's per-trainer values: sum, then average over the
        ORIGINAL trainer count (a retired trainer's mean-grad contribution
        is treated as zero for the remaining steps)."""
        from ..selected_rows import SelectedRows
        scale = 1.0 / float(self.num_trainers)
        vs = list(values)
        if isinstance(vs[0], SelectedRows):
            rows = np.concatenate([np.asarray(v.rows) for v in vs])
            vals = np.concatenate([np.asarray(v.values) for v in vs])
            return SelectedRows(vals * scale, rows.astype('int32'),
                                vs[0].height)
        out = np.asarray(vs[0], dtype=np.result_type(vs[0]))
        for v in vs[1:]:
            out = out + np.asarray(v)
        return out * scale

    def _maybe_run_round_locked(self):
        if not self._barrier_tids:
            return
        if len(self._barrier_tids) < self._live_count():
            return
        merged = {g: self._merge(per_tid.values())
                  for g, per_tid in self._pending.items() if per_tid}
        try:
            self._run_round(merged)
        except Exception as e:
            self._error = e
            raise
        finally:
            self._pending.clear()
            self._barrier_tids.clear()
            self._completed_rounds += 1
            self._cond.notify_all()

    def _wait_for_trainer_round_locked(self, tid):
        """Block until every round this trainer contributed to is applied
        (its own GET arrives, by per-connection ordering, after its
        BATCH_BARRIER)."""
        while self._completed_rounds < self._trainer_rounds.get(tid, 0):
            if self._error is not None:
                raise RuntimeError('pserver optimize failed: %s'
                                   % self._error)
            self._cond.wait(timeout=1.0)

    # -- service interface (called from PSServer threads) ------------------
    def on_send_var(self, name, tid, value):
        if not self.sync_mode and self._run_one_grad is not None:
            with self._lock:
                self._run_one_grad(name, value)
            return
        with self._lock:
            self._pending.setdefault(name, {})[tid] = value

    def on_batch_barrier(self, tid):
        with self._lock:
            self._barrier_tids.add(tid)
            self._trainer_rounds[tid] = self._trainer_rounds.get(tid, 0) + 1
            self._maybe_run_round_locked()

    def on_get_var(self, name, tid):
        with self._lock:
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            return self._get_param(name)

    def on_prefetch(self, name, tid, ids):
        if self._prefetch is None:
            raise RuntimeError('this pserver hosts no lookup table')
        with self._lock:
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            return self._prefetch(name, np.asarray(ids))

    def on_checkpoint(self, dirname, tid):
        if self._save_params is None:
            raise RuntimeError('this pserver has no checkpoint support')
        with self._lock:
            if self.sync_mode:
                self._wait_for_trainer_round_locked(tid)
            self._save_params(dirname)

    def on_fetch_barrier(self, tid):
        pass    # round already closed by the sync wait in on_get_var

    def on_complete(self, tid):
        with self._lock:
            self._done_tids.add(tid)
            self._barrier_tids.discard(tid)
            # a straggler-free round may now be unblocked
            self._maybe_run_round_locked()
            return len(self._done_tids) >= self.num_trainers
