"""Binary wire format for the parameter-server RPC layer.

Replaces the reference's protobuf `send_recv.proto.in` (VariableMessage:
varname, type, dims, serialized LoDTensor/SelectedRows bytes) with a
compact JSON-header + raw-bytes framing — same capability (dense tensors
and SelectedRows cross the wire; sparse ships rows+values only), no
protobuf dependency.

Frame layout, version 2 (all integers little-endian):

    u32 crc | u32 body_len | u8 version | u8 msg_type | u32 meta_len
    | meta (JSON, utf-8) | payload

`crc` is zlib crc32 (the same definition recordio chunks use, via
`integrity.crc32`) over EVERYTHING after the crc field — the remaining
header fields plus meta plus payload — so a flipped bit anywhere in the
frame fails verification. `body_len` counts meta + payload bytes only.
A frame that fails its CRC or carries an unknown version raises
`FrameCorruptError`, a ConnectionError subclass: the RPC clients
already treat ConnectionError as retryable (drop the socket, reconnect,
replay the same seq), so a corrupted frame costs one round trip and the
retry delivers a clean copy — it is never applied.

Dense payload:        raw C-contiguous array bytes (dtype/shape in meta).
SelectedRows payload: values bytes followed by int32 rows bytes
                      (meta: value dtype/shape, nrows, height).

Version 3 (FLAGS_wire_binary_meta) keeps the identical frame layout but
encodes `meta` with the in-house binary codec below (embedded-length
tag bytes, zigzag varints, per-message dict-key interning — no external
dependency) instead of JSON. The win is WIRE BYTES, not CPU: an 80-var
SEND_VARS meta encodes ~2x smaller than its JSON form (key interning
collapses the repeated per-entry keys), which is exactly the frame-
header share PERF round 10 measured as the remaining 2x on the
320x256B row; the pure-Python encode/decode itself does not beat the C
json module, so loopback ms is a wash (dist_bench's `pipelined_bmeta`
row reports both axes honestly). The upgrade is NEGOTIATED PER CONNECTION, JSON remaining the
fallback for old peers: a flag-on sender adds 'bmeta': 1 to its v2 JSON
metas (old receivers ignore the unknown key); a receiver that sees the
advert — or an actual v3 frame — marks the socket, and a flag-on sender
emits v3 only to a peer so proven. Readers accept BOTH versions
unconditionally (the journal decoder too: a pserver journal may mix
versions across restarts with different flag settings).
"""
from __future__ import annotations

import json
import struct
import sys
import weakref

import numpy as np

from ..integrity import crc32
from ..obs import telemetry as _tm

# telemetry series (no-ops while FLAGS_obs_dir is unset): every frame
# on the socket path counts here, both directions, plus every CRC
# verification failure — wire or journal
_FRAMES_OUT = _tm.counter('wire.frames_out')
_BYTES_OUT = _tm.counter('wire.bytes_out')
_FRAMES_IN = _tm.counter('wire.frames_in')
_BYTES_IN = _tm.counter('wire.bytes_in')
_CRC_FAILURES = _tm.counter('wire.crc_failures')

# message types
SEND_VAR = 1        # trainer -> pserver: push a gradient (dense or sparse)
GET_VAR = 2         # trainer -> pserver: pull a parameter
SEND_VARS = 12      # trainer -> pserver: MANY small dense gradients in
                    # one frame (meta['vars'] lists per-var name/dtype/
                    # shape/len/seq/round; payload is their concatenated
                    # bytes). One CRC + one JSON header + one reply
                    # covers the whole batch; each contained var keeps
                    # its OWN (cli, seq) dedup token and round tag, so a
                    # replayed batch is applied per-var at-most-once
                    # exactly like individual SEND_VARs
PREFETCH = 3        # trainer -> pserver: distributed-lookup-table row fetch
BATCH_BARRIER = 4   # trainer -> pserver: all grads for this step sent
FETCH_BARRIER = 5   # trainer -> pserver: all params for this step fetched
COMPLETE = 6        # trainer -> pserver: this trainer is done training
CHECKPOINT = 10     # trainer -> pserver: save your param shard to dir
REGISTER = 11       # trainer -> pserver: (re)join handshake — carries the
                    # trainer's incarnation; reply meta reports the
                    # server's round state so a restarted trainer knows
                    # where to resume (elastic recovery)
GET_VARS = 13       # serving -> pserver: pull MANY params in one frame
                    # (meta['names']); the REPLY_VAR carries meta['vars']
                    # entries (name/dtype/shape/len/digest) + the params'
                    # concatenated bytes, all read atomically under the
                    # service lock and stamped with the param version
                    # they belong to (online refresh pulls one
                    # version-consistent shard per round trip)
GET_VERSION = 14    # serving -> pserver: current param version; with
                    # meta['manifest'] the REPLY_OK also carries the
                    # per-param crc32 digest manifest the subscriber
                    # verifies pulled bytes against
SRV_SUBMIT = 20     # router -> replica: open a generation stream
                    # (meta rid/max_new_tokens/eos_id + 'prio', the SLO
                    # tier — higher = more important, absent reads as
                    # the lowest tier 0; value = prompt token ids). A
                    # failover re-submit carries the original prompt
                    # PLUS the tokens already decoded — greedy
                    # determinism makes the re-prefilled stream
                    # bit-exact with the unkilled one
SRV_POLL = 21       # router -> replica: progress of meta['rids'];
                    # reply meta['streams'] maps rid -> {state, tokens}
                    # (UNKNOWN for a rid the replica never saw — a
                    # restarted replica's answer for pre-kill streams)
SRV_CANCEL = 22     # router -> replica: cancel stream meta['rid']
SRV_HEALTH = 23     # router -> replica: liveness + load probe; reply
                    # carries queue_depth/active/capacity/max_len/
                    # param_version/draining (and with meta['digests']
                    # the per-param crc32s a deploy convergence check
                    # compares against the pserver manifest)
SRV_DRAIN = 24      # router -> replica: drain fence — meta['on'] stops
                    # (or resumes) THIS replica admitting new streams;
                    # in-flight streams keep decoding to completion
SRV_REFRESH = 25    # router -> replica: pull + install the pservers'
                    # newest params NOW (ParamSubscriber.refresh_once);
                    # the rolling-deploy step after the drain completes
SRV_PAGES = 26      # disaggregated serving (serving/disagg.py): a
                    # first-class KV-page shipment. meta carries the
                    # hash-chain keys ('keys', hex, in chain order),
                    # how many leading chain pages the receiver already
                    # held ('skip' — content-addressed dedup: a page
                    # already present is acknowledged without
                    # transfer), the prompt tokens and page geometry;
                    # the value is one float32 array
                    # [pools, pages, page_tokens, heads, dk] under the
                    # usual CRC/bmeta discipline. Sent prefill ->
                    # decode as the SRV_PAGE_FETCH reply, or pushed
                    # directly at a replica, which installs via
                    # PagePool.restore_pages + PrefixCache and acks
                    # REPLY_OK {'installed', 'deduped'}
SRV_PAGE_FETCH = 27  # decode replica -> prefill replica: prefill
                    # meta-described prompt (value: token ids) if its
                    # pages are not already cached, then reply with an
                    # SRV_PAGES frame shipping every full prefix page
                    # the requester's 'have' key list lacks
REPLY_VAR = 7       # pserver -> trainer: a variable value
REPLY_OK = 8        # pserver -> trainer: ack
REPLY_ERR = 9       # pserver -> trainer: error (meta['error'])

WIRE_VERSION = 2        # JSON meta (the on-disk journal default)
WIRE_VERSION_BMETA = 3  # binary meta (negotiated; FLAGS_wire_binary_meta)
_WIRE_VERSIONS = (WIRE_VERSION, WIRE_VERSION_BMETA)

# crc, body_len, version, msg_type, meta_len
_HDR = struct.Struct('<IIBBI')
_CRC_SKIP = 4   # the crc field itself is excluded from its own coverage


class FrameCorruptError(ConnectionError):
    """A frame failed its CRC32 or version check. Subclassing
    ConnectionError makes the existing retry machinery handle it: the
    client drops the socket and replays the request (same seq), the
    server closes the connection — a corrupt frame is never parsed past
    its header, let alone applied."""


_resilience = None


def _faults():
    """Fault-injection hook module (resilience.py), resolved lazily so
    wire stays import-light; the hooks are no-ops without an active
    FaultPlan (FLAGS_fault_plan)."""
    global _resilience
    if _resilience is None:
        from . import resilience
        _resilience = resilience
    return _resilience


def _bytes_view(arr):
    """A flat byte view over a C-contiguous array WITHOUT copying —
    tobytes() duplicates the tensor before the frame build copies it
    again, so the hot send path skips it. Falls back to tobytes() for
    the shapes memoryview.cast cannot flatten (0-d, exotic buffers)."""
    try:
        return memoryview(arr).cast('B')
    except (TypeError, ValueError):
        return arr.tobytes()


def _payload_of(value):
    """(meta_fields, payload_bytes) for a dense array or SelectedRows.
    The payload may be a memoryview aliasing the array's buffer (dense,
    already-contiguous case) — every consumer (crc32, len, b''.join,
    sendall) speaks the buffer protocol."""
    from ..selected_rows import SelectedRows
    if isinstance(value, SelectedRows):
        vals = np.ascontiguousarray(np.asarray(value.values))
        rows = np.ascontiguousarray(np.asarray(value.rows, dtype=np.int32))
        meta = {'sparse': True, 'dtype': vals.dtype.name,
                'shape': list(vals.shape), 'height': int(value.height)}
        return meta, b''.join((_bytes_view(vals), _bytes_view(rows)))
    arr = np.asarray(value)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    meta = {'sparse': False, 'dtype': arr.dtype.name,
            'shape': list(arr.shape)}
    return meta, _bytes_view(arr)


def _value_of(meta, payload):
    """Inverse of _payload_of."""
    from ..selected_rows import SelectedRows
    dtype = np.dtype(meta['dtype'])
    shape = tuple(meta['shape'])
    if meta.get('sparse'):
        nval = int(np.prod(shape)) * dtype.itemsize
        values = np.frombuffer(payload[:nval], dtype=dtype).reshape(shape)
        rows = np.frombuffer(payload[nval:], dtype=np.int32)
        return SelectedRows(values, rows, meta['height'])
    n = int(np.prod(shape)) * dtype.itemsize
    return np.frombuffer(payload[:n], dtype=dtype).reshape(shape)


def value_is_finite(value):
    """True iff every float element of a dense array / SelectedRows is
    finite. Non-float dtypes are vacuously finite. Shared by the
    client-side pre-send check and the pserver's gradient guard
    (FLAGS_ps_check_grad_finite)."""
    from ..selected_rows import SelectedRows
    if isinstance(value, SelectedRows):
        value = value.values
    arr = np.asarray(value)
    if arr.dtype.kind != 'f':
        return True
    return bool(np.isfinite(arr).all())


# -- binary meta codec (wire version 3) -----------------------------------
# Compact tag-byte encoding built to beat JSON on SIZE (pure-Python
# can't beat the C json module on CPU time; the win this codec buys is
# bytes on the wire). Three tricks:
#   * embedded lengths: small ints, short strings, and small
#     lists/dicts pack their value/length into the tag byte's low 5
#     bits (one byte of overhead total for the common case)
#   * LEB128 varints for everything bigger (ints are zigzagged first
#     so small negatives stay small)
#   * per-message dict-key interning: a key's utf-8 spells out once;
#     every repeat is a 1-byte (or varint) back-reference — SEND_VARS
#     metas repeat {'name','seq','round','dtype','shape','len'} per
#     entry, so the entry-list overhead collapses
# Dict keys keep JSON semantics (non-string keys stringify, decode
# always yields str keys), so the two meta encodings round-trip to the
# same Python object. Tag map:
#   0x00 None | 0x01 True | 0x02 False | 0x03 int (zigzag varint)
#   0x04 float (f64) | 0x05 str (varint len) | 0x06 bytes (varint len)
#   0x07 list (varint count) | 0x08 dict (varint count)
#   0x09 long new key (varint len) | 0x0A key backref (varint index)
#   0x20|z  small int, zigzag value z in the tag  (-16..15)
#   0x40|n  short str of n bytes | 0x60|n short list | 0x80|n short dict
#   0xC0|n  short new key of n bytes | 0xE0|i key backref, index i < 32
# Anything else (0x0B..0x1F, 0xA0..0xBF) is an unknown tag ->
# FrameCorruptError.

_BM_INT, _BM_FLOAT, _BM_STR = 0x03, 0x04, 0x05
_BM_BYTES, _BM_LIST, _BM_DICT = 0x06, 0x07, 0x08
_BM_KEYDEF, _BM_KEYREF = 0x09, 0x0A
_F64 = struct.Struct('<d')


def _bm_uvarint(out, n):
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _bm_read_uvarint(buf, off):
    shift = result = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _bm_encode(obj, out, keys):
    if obj is None:
        out.append(0x00)
    elif obj is True:
        out.append(0x01)
    elif obj is False:
        out.append(0x02)
    elif isinstance(obj, int):
        zz = (obj << 1) if obj >= 0 else ((-obj << 1) - 1)
        if zz < 0x20:
            out.append(0x20 | zz)
        else:
            out.append(_BM_INT)
            _bm_uvarint(out, zz)
    elif isinstance(obj, float):
        out.append(_BM_FLOAT)
        out += _F64.pack(obj)
    elif isinstance(obj, str):
        b = obj.encode('utf-8')
        n = len(b)
        if n < 0x20:
            out.append(0x40 | n)
        else:
            out.append(_BM_STR)
            _bm_uvarint(out, n)
        out += b
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(_BM_BYTES)
        _bm_uvarint(out, len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        n = len(obj)
        if n < 0x20:
            out.append(0x60 | n)
        else:
            out.append(_BM_LIST)
            _bm_uvarint(out, n)
        for v in obj:
            _bm_encode(v, out, keys)
    elif isinstance(obj, dict):
        n = len(obj)
        if n < 0x20:
            out.append(0x80 | n)
        else:
            out.append(_BM_DICT)
            _bm_uvarint(out, n)
        for k, v in obj.items():
            ks = str(k)
            idx = keys.get(ks)
            if idx is None:
                keys[ks] = len(keys)
                kb = ks.encode('utf-8')
                kn = len(kb)
                if kn < 0x20:
                    out.append(0xC0 | kn)
                else:
                    out.append(_BM_KEYDEF)
                    _bm_uvarint(out, kn)
                out += kb
            elif idx < 0x20:
                out.append(0xE0 | idx)
            else:
                out.append(_BM_KEYREF)
                _bm_uvarint(out, idx)
            _bm_encode(v, out, keys)
    else:
        raise TypeError('binary wire meta cannot encode %r'
                        % type(obj).__name__)


def bm_dumps(meta):
    """Meta dict -> version-3 binary bytes (the v3 json.dumps)."""
    out = bytearray()
    _bm_encode(meta, out, {})
    return bytes(out)


def _bm_read_key(buf, off, keys):
    tag = buf[off]
    off += 1
    hi = tag & 0xE0
    if hi == 0xE0:
        return keys[tag & 0x1F], off
    if hi == 0xC0:
        n = tag & 0x1F
    elif tag == _BM_KEYDEF:
        n, off = _bm_read_uvarint(buf, off)
    elif tag == _BM_KEYREF:
        idx, off = _bm_read_uvarint(buf, off)
        return keys[idx], off
    else:
        raise FrameCorruptError(
            'binary wire meta: invalid key tag 0x%02x at offset %d'
            % (tag, off - 1))
    k = bytes(buf[off:off + n]).decode('utf-8')
    keys.append(k)
    return k, off + n


def _bm_decode(buf, off, keys):
    tag = buf[off]
    off += 1
    if tag < 0x20:
        if tag == 0x00:
            return None, off
        if tag == 0x01:
            return True, off
        if tag == 0x02:
            return False, off
        if tag == _BM_INT:
            zz, off = _bm_read_uvarint(buf, off)
            return ((zz >> 1) if not zz & 1 else -((zz + 1) >> 1)), off
        if tag == _BM_FLOAT:
            return _F64.unpack_from(buf, off)[0], off + 8
        if tag in (_BM_STR, _BM_BYTES):
            n, off = _bm_read_uvarint(buf, off)
            raw = bytes(buf[off:off + n])
            if len(raw) != n:
                raise FrameCorruptError(
                    'binary wire meta: truncated at offset %d' % off)
            return ((raw.decode('utf-8') if tag == _BM_STR else raw),
                    off + n)
        if tag == _BM_LIST:
            n, off = _bm_read_uvarint(buf, off)
        elif tag == _BM_DICT:
            n, off = _bm_read_uvarint(buf, off)
            out = {}
            for _ in range(n):
                k, off = _bm_read_key(buf, off, keys)
                out[k], off = _bm_decode(buf, off, keys)
            return out, off
        else:
            raise FrameCorruptError(
                'binary wire meta: unknown tag 0x%02x at offset %d'
                % (tag, off - 1))
        out = []
        for _ in range(n):
            v, off = _bm_decode(buf, off, keys)
            out.append(v)
        return out, off
    hi = tag & 0xE0
    low = tag & 0x1F
    if hi == 0x20:
        return ((low >> 1) if not low & 1 else -((low + 1) >> 1)), off
    if hi == 0x40:
        raw = bytes(buf[off:off + low])
        if len(raw) != low:
            raise FrameCorruptError(
                'binary wire meta: truncated at offset %d' % off)
        return raw.decode('utf-8'), off + low
    if hi == 0x60:
        out = []
        for _ in range(low):
            v, off = _bm_decode(buf, off, keys)
            out.append(v)
        return out, off
    if hi == 0x80:
        out = {}
        for _ in range(low):
            k, off = _bm_read_key(buf, off, keys)
            out[k], off = _bm_decode(buf, off, keys)
        return out, off
    raise FrameCorruptError('binary wire meta: unknown tag 0x%02x at '
                            'offset %d' % (tag, off - 1))


def bm_loads(buf):
    """Version-3 binary meta bytes -> dict (the v3 json.loads)."""
    try:
        obj, off = _bm_decode(memoryview(buf), 0, [])
    except (IndexError, struct.error) as e:
        raise FrameCorruptError('binary wire meta: truncated (%s)' % e)
    if off != len(buf):
        raise FrameCorruptError(
            'binary wire meta: %d trailing bytes after the root value'
            % (len(buf) - off))
    return obj


# sockets proven to decode v3 (socket.socket has __slots__, so the
# capability lives in a WeakSet keyed by the socket object — it dies
# with the connection, exactly the negotiation scope we want)
_BMETA_PEERS = weakref.WeakSet()


def _peer_speaks_bmeta(sock):
    if getattr(sock, '_wire_peer_bmeta', False):  # test doubles
        return True
    try:
        return sock in _BMETA_PEERS
    except TypeError:
        return False


def _mark_peer_bmeta(sock):
    try:
        _BMETA_PEERS.add(sock)
    except TypeError:
        try:
            sock._wire_peer_bmeta = True
        except AttributeError:
            pass                  # unmarkable peer: stay on JSON


def _sender_wants_bmeta():
    from ..flags import get_flag
    return bool(get_flag('wire_binary_meta'))


def pack_msg(msg_type, meta=None, value=None, payload=b'',
             version=WIRE_VERSION):
    """Serialize one frame to bytes. Shared by the socket path
    (write_msg) and the pserver's on-disk mutation journal
    (param_service) — a journal record IS a wire frame, so replay and
    socket dispatch share one decoder (and one CRC check). `version`
    picks the meta encoding: 2 = JSON (default — journals stay readable
    by any build), 3 = binary (bm_dumps)."""
    meta = dict(meta or {})
    if value is not None:
        vmeta, payload = _payload_of(value)
        meta.update(vmeta)
    if version == WIRE_VERSION_BMETA:
        mb = bm_dumps(meta)
    else:
        mb = json.dumps(meta).encode('utf-8')
    rest = b''.join((struct.pack('<IBBI', len(mb) + len(payload),
                                 version, msg_type, len(mb)),
                     mb, payload))
    return struct.pack('<I', crc32(rest)) + rest


def _check_frame(buf, off, end, crc):
    if crc32(bytes(buf[off + _CRC_SKIP:end])) != crc:
        _CRC_FAILURES.inc()
        raise FrameCorruptError(
            'frame at offset %d failed its CRC32 check (corrupt bytes '
            'on the wire or on disk)' % off)


def _values_of_batch(meta, payload):
    """Decode a SEND_VARS body: meta['vars'] entries each carry their
    own dtype/shape plus 'len' (payload byte count); the payload is the
    vars' bytes back to back. Returns the values in entry order."""
    values, off = [], 0
    for e in meta['vars']:
        n = int(e['len'])
        values.append(_value_of(e, payload[off:off + n]))
        off += n
    return values


def pack_vars_body(items):
    """(entries, payload) for a multi-var frame body: items is
    [(entry_meta, value), ...]; each entry gets the value's dtype/shape
    plus 'len' filled in, the payload is the values' bytes back to back
    — the exact body _values_of_batch decodes. The inverse pairing lets
    a server build a multi-var REPLY_VAR through the ordinary write_msg
    path (fault hooks see ONE reply frame, matching the one logical
    GET_VARS request)."""
    entries, chunks = [], []
    for emeta, value in items:
        vmeta, payload = _payload_of(value)
        e = dict(emeta)
        e.update(vmeta)
        e['len'] = len(payload)
        entries.append(e)
        chunks.append(payload)
    return entries, b''.join(chunks)


def _parse_body(body, meta_len, version=WIRE_VERSION):
    # body may be bytes (journal scans) or a memoryview (socket path) —
    # only the meta is copied out; tensor payloads decode zero-copy
    if not meta_len:
        meta = {}
    elif version == WIRE_VERSION_BMETA:
        meta = bm_loads(body[:meta_len])
    else:
        meta = json.loads(bytes(body[:meta_len]).decode('utf-8'))
    payload = body[meta_len:]
    if 'vars' in meta:
        return meta, _values_of_batch(meta, payload)
    value = _value_of(meta, payload) if 'dtype' in meta else None
    return meta, value


def scan_msgs(buf):
    """Yield (msg_type, meta, value, end_offset) for each complete,
    CRC-verified frame in `buf`; `end_offset` is the byte offset just
    past the frame (journal replay truncates a torn tail to the last
    yielded end_offset before reopening for append).

    A truncated trailing frame (a journal torn by a mid-write crash, or
    a corrupt body_len that claims bytes past EOF — indistinguishable)
    ends the scan without error: the caller sees end_offset < len(buf)
    and decides how loudly to report it. A frame that is fully present
    but fails its CRC, or carries an unknown wire version, raises
    FrameCorruptError — everything yielded before it is a consistent
    prefix; nothing after it can be trusted (framing is lost)."""
    off, n = 0, len(buf)
    while off + _HDR.size <= n:
        crc, body_len, version, msg_type, meta_len = \
            _HDR.unpack_from(buf, off)
        end = off + _HDR.size + body_len
        if end > n:
            return          # torn tail
        if version not in _WIRE_VERSIONS:
            raise FrameCorruptError(
                'frame at offset %d: wire version %d (expected one of '
                '%s) — corrupt header or a file from an incompatible '
                'build' % (off, version, list(_WIRE_VERSIONS)))
        if meta_len > body_len:
            raise FrameCorruptError(
                'frame at offset %d: meta_len %d exceeds body_len %d'
                % (off, meta_len, body_len))
        _check_frame(buf, off, end, crc)
        body = bytes(buf[off + _HDR.size:end])
        meta, value = _parse_body(body, meta_len, version)
        yield msg_type, meta, value, end
        off = end


def unpack_msgs(buf):
    """Yield (msg_type, meta, value) for each complete, verified frame
    in `buf` — scan_msgs without the offsets."""
    for msg_type, meta, value, _ in scan_msgs(buf):
        yield msg_type, meta, value


def write_msg(sock, msg_type, meta=None, value=None, payload=b''):
    meta = dict(meta or {})
    if value is not None:
        vmeta, payload = _payload_of(value)
        meta.update(vmeta)
    # binary-meta negotiation: emit v3 only once the peer is PROVEN to
    # speak it (it advertised, or already sent us a v3 frame); until
    # then keep advertising inside the v2 JSON meta — an old peer just
    # ignores the unknown key and the connection stays on JSON
    version = WIRE_VERSION
    if _sender_wants_bmeta():
        if _peer_speaks_bmeta(sock):
            version = WIRE_VERSION_BMETA
        else:
            meta['bmeta'] = 1
    # fault hook BEFORE any bytes hit the wire: an injected drop/error
    # never leaves a half-written frame on the socket. The hook fires
    # exactly once per send, so a retry of this message advances the
    # plan's counters past the rule that faulted it.
    effect = _faults().on_send(sock, msg_type, meta)
    action = getattr(effect, 'action', None)
    if action in ('corrupt', 'nan'):
        # same stderr audit line the exit action leaves: corrupt/nan
        # damage is meant to be INVISIBLE at the application layer
        # (detected and retried), so chaos tests grep the log to prove
        # the fault actually fired
        sys.stderr.write('fault injection: %s on send of msg type %s '
                         '(rule %s)\n' % (action, msg_type,
                                          effect.rule.to_dict()))
        sys.stderr.flush()
    if action == 'nan':
        # poison the float payload BEFORE framing: the frame carries a
        # valid CRC — this is a numeric fault (a bad gradient), not a
        # transport fault, and must get past the CRC check to exercise
        # the finite-guard path
        payload = _poison_payload(meta, payload)
    frame = pack_msg(msg_type, meta, payload=payload, version=version)
    if action == 'corrupt':
        # flip bits AFTER framing, inside the CRC-covered region: the
        # receiver must detect the damage and never apply the frame
        frame = effect.mutate_frame(frame, _HDR.size)
    sock.sendall(frame)
    _FRAMES_OUT.inc()
    _BYTES_OUT.inc(len(frame))
    if action == 'close':
        effect.post_send()   # frame delivered, connection then dies


def write_vars_msg(sock, frame_meta, items):
    """Write ONE SEND_VARS frame carrying many dense vars.

    `items` is a list of (entry_meta, value) pairs: entry_meta holds the
    per-var fields (name/seq/round), and the value's dtype/shape/len are
    filled in here; `frame_meta` holds the frame-level fields
    (trainer_id/cli/inc/trace). Fault hooks advance once PER LOGICAL VAR
    — a batch of 8 vars steps a `send SEND_VAR` rule's counter 8 times —
    so seeded plans fire at the same logical points whether or not
    batching is on. Frame-scoped actions (drop/close/corrupt) hit the
    whole batch; the per-var (cli, seq) dedup tokens make the replay
    apply each contained var at-most-once. A `nan` action poisons only
    the matched var's bytes (valid CRC, numeric fault).
    """
    entries, chunks = [], []
    for emeta, value in items:
        vmeta, payload = _payload_of(value)
        e = dict(emeta)
        e.update(vmeta)
        e['len'] = len(payload)
        entries.append(e)
        chunks.append(payload)
    effect = _faults().on_send_vars(sock, SEND_VAR, entries)
    action = getattr(effect, 'action', None)
    if action in ('corrupt', 'nan'):
        sys.stderr.write('fault injection: %s on send of msg type %s '
                         '(rule %s, batch of %d)\n'
                         % (action, SEND_VARS, effect.rule.to_dict(),
                            len(entries)))
        sys.stderr.flush()
    if action == 'nan':
        i = effect.index or 0
        chunks[i] = _poison_payload(entries[i], chunks[i])
    meta = dict(frame_meta)
    meta['vars'] = entries
    version = WIRE_VERSION
    if _sender_wants_bmeta():
        if _peer_speaks_bmeta(sock):
            version = WIRE_VERSION_BMETA
        else:
            meta['bmeta'] = 1
    frame = pack_msg(SEND_VARS, meta, payload=b''.join(chunks),
                     version=version)
    if action == 'corrupt':
        frame = effect.mutate_frame(frame, _HDR.size)
    sock.sendall(frame)
    _FRAMES_OUT.inc()
    _BYTES_OUT.inc(len(frame))
    if action == 'close':
        effect.post_send()
    return len(frame)


def _poison_payload(meta, payload):
    """Replace the dense float region of a payload with NaNs of the
    same dtype/length (the 'nan' FaultPlan action — a deterministic
    stand-in for a diverging or corrupted gradient computation)."""
    if 'dtype' not in meta:
        return payload
    dtype = np.dtype(meta['dtype'])
    if dtype.kind != 'f':
        return payload
    count = int(np.prod(tuple(meta.get('shape', ())) or (0,)))
    nval = min(count * dtype.itemsize, len(payload))
    if nval <= 0:
        return payload
    bad = np.full(count, np.nan, dtype=dtype).tobytes()[:nval]
    return bad + bytes(payload[nval:])


def _read_exact(sock, n):
    """Read exactly n bytes straight into one freshly allocated buffer
    via recv_into — no per-chunk bytes objects, no b''.join copy.
    Returns a memoryview over the buffer; decoded tensors alias it
    zero-copy, so the buffer is never reused across calls."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError('peer closed the connection')
        got += r
    return view


def read_msg(sock):
    """-> (msg_type, meta dict, value or None). value is a numpy array or
    SelectedRows when the meta describes one. The frame's CRC is
    verified before the meta is even parsed; a mismatch raises
    FrameCorruptError (the stream may be desynced — the connection is
    unusable either way)."""
    while True:
        hdr = _read_exact(sock, _HDR.size)
        crc, body_len, version, msg_type, meta_len = _HDR.unpack(hdr)
        if version not in _WIRE_VERSIONS:
            _CRC_FAILURES.inc()
            raise FrameCorruptError(
                'bad wire version %d (expected one of %s) — corrupt '
                'header or desynced stream'
                % (version, list(_WIRE_VERSIONS)))
        body = _read_exact(sock, body_len) if body_len else b''
        # incremental CRC (crc32 chains): covers header-after-crc then
        # body without materializing their concatenation
        if crc32(body, crc32(hdr[_CRC_SKIP:])) != crc:
            _CRC_FAILURES.inc()
            raise FrameCorruptError(
                'frame (msg type %d, %d body bytes) failed its CRC32 '
                'check — corrupt bytes on the wire' % (msg_type, body_len))
        if meta_len > body_len:
            _CRC_FAILURES.inc()
            raise FrameCorruptError(
                'frame meta_len %d exceeds body_len %d'
                % (meta_len, body_len))
        meta, value = _parse_body(body, meta_len, version)
        # capability latch: a v3 frame, or a v2 meta carrying the
        # 'bmeta' advert, proves this peer decodes binary metas — our
        # flag-on replies to THIS socket may upgrade from here on
        if version == WIRE_VERSION_BMETA or meta.get('bmeta'):
            _mark_peer_bmeta(sock)
        _FRAMES_IN.inc()
        _BYTES_IN.inc(len(hdr) + len(body))
        # fault hook AFTER the full frame was consumed (framing stays
        # intact); 'drop' discards this message and reads the next. A
        # SEND_VARS batch advances the counters once per contained var
        # (same logical firing points whether or not batching is on).
        if msg_type == SEND_VARS:
            act = _faults().on_recv_vars(sock, SEND_VAR, len(meta['vars']))
        else:
            act = _faults().on_recv(sock, msg_type, meta)
        if act == 'drop':
            continue
        return msg_type, meta, value
