"""Binary wire format for the parameter-server RPC layer.

Replaces the reference's protobuf `send_recv.proto.in` (VariableMessage:
varname, type, dims, serialized LoDTensor/SelectedRows bytes) with a
compact JSON-header + raw-bytes framing — same capability (dense tensors
and SelectedRows cross the wire; sparse ships rows+values only), no
protobuf dependency.

Frame layout (all integers little-endian):

    u32 body_len | u8 msg_type | u32 meta_len | meta (JSON, utf-8) | payload

Dense payload:        raw C-contiguous array bytes (dtype/shape in meta).
SelectedRows payload: values bytes followed by int32 rows bytes
                      (meta: value dtype/shape, nrows, height).
"""
from __future__ import annotations

import json
import struct

import numpy as np

# message types
SEND_VAR = 1        # trainer -> pserver: push a gradient (dense or sparse)
GET_VAR = 2         # trainer -> pserver: pull a parameter
PREFETCH = 3        # trainer -> pserver: distributed-lookup-table row fetch
BATCH_BARRIER = 4   # trainer -> pserver: all grads for this step sent
FETCH_BARRIER = 5   # trainer -> pserver: all params for this step fetched
COMPLETE = 6        # trainer -> pserver: this trainer is done training
CHECKPOINT = 10     # trainer -> pserver: save your param shard to dir
REGISTER = 11       # trainer -> pserver: (re)join handshake — carries the
                    # trainer's incarnation; reply meta reports the
                    # server's round state so a restarted trainer knows
                    # where to resume (elastic recovery)
REPLY_VAR = 7       # pserver -> trainer: a variable value
REPLY_OK = 8        # pserver -> trainer: ack
REPLY_ERR = 9       # pserver -> trainer: error (meta['error'])

_HDR = struct.Struct('<IBI')   # body_len, msg_type, meta_len

_resilience = None


def _faults():
    """Fault-injection hook module (resilience.py), resolved lazily so
    wire stays import-light; the hooks are no-ops without an active
    FaultPlan (FLAGS_fault_plan)."""
    global _resilience
    if _resilience is None:
        from . import resilience
        _resilience = resilience
    return _resilience


def _payload_of(value):
    """(meta_fields, payload_bytes) for a dense array or SelectedRows."""
    from ..selected_rows import SelectedRows
    if isinstance(value, SelectedRows):
        vals = np.ascontiguousarray(np.asarray(value.values))
        rows = np.ascontiguousarray(np.asarray(value.rows, dtype=np.int32))
        meta = {'sparse': True, 'dtype': vals.dtype.name,
                'shape': list(vals.shape), 'height': int(value.height)}
        return meta, vals.tobytes() + rows.tobytes()
    arr = np.ascontiguousarray(np.asarray(value))
    meta = {'sparse': False, 'dtype': arr.dtype.name,
            'shape': list(arr.shape)}
    return meta, arr.tobytes()


def _value_of(meta, payload):
    """Inverse of _payload_of."""
    from ..selected_rows import SelectedRows
    dtype = np.dtype(meta['dtype'])
    shape = tuple(meta['shape'])
    if meta.get('sparse'):
        nval = int(np.prod(shape)) * dtype.itemsize
        values = np.frombuffer(payload[:nval], dtype=dtype).reshape(shape)
        rows = np.frombuffer(payload[nval:], dtype=np.int32)
        return SelectedRows(values, rows, meta['height'])
    n = int(np.prod(shape)) * dtype.itemsize
    return np.frombuffer(payload[:n], dtype=dtype).reshape(shape)


def pack_msg(msg_type, meta=None, value=None, payload=b''):
    """Serialize one frame to bytes. Shared by the socket path
    (write_msg) and the pserver's on-disk mutation journal
    (param_service) — a journal record IS a wire frame, so replay and
    socket dispatch share one decoder."""
    meta = dict(meta or {})
    if value is not None:
        vmeta, payload = _payload_of(value)
        meta.update(vmeta)
    mb = json.dumps(meta).encode('utf-8')
    body_len = 1 + 4 + len(mb) + len(payload)
    return _HDR.pack(body_len, msg_type, len(mb)) + mb + payload


def unpack_msgs(buf):
    """Yield (msg_type, meta, value) for each complete frame in `buf`.
    A truncated trailing frame (a journal torn by a mid-write crash) is
    silently ignored — everything before it was written whole."""
    off, n = 0, len(buf)
    while off + _HDR.size <= n:
        body_len, msg_type, meta_len = _HDR.unpack_from(buf, off)
        end = off + _HDR.size + body_len - 1 - 4
        if end > n:
            return
        body = buf[off + _HDR.size:end]
        meta = json.loads(body[:meta_len].decode('utf-8')) if meta_len \
            else {}
        payload = body[meta_len:]
        value = _value_of(meta, payload) if 'dtype' in meta else None
        yield msg_type, meta, value
        off = end


def write_msg(sock, msg_type, meta=None, value=None, payload=b''):
    meta = dict(meta or {})
    if value is not None:
        vmeta, payload = _payload_of(value)
        meta.update(vmeta)
    # fault hook BEFORE any bytes hit the wire: an injected drop/error
    # never leaves a half-written frame on the socket
    post_send = _faults().on_send(sock, msg_type, meta)
    sock.sendall(pack_msg(msg_type, meta, payload=payload))
    if post_send is not None:
        post_send()   # 'close' action: frame delivered, connection dies


def _read_exact(sock, n):
    chunks = []
    while n > 0:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError('peer closed the connection')
        chunks.append(b)
        n -= len(b)
    return b''.join(chunks)


def read_msg(sock):
    """-> (msg_type, meta dict, value or None). value is a numpy array or
    SelectedRows when the meta describes one."""
    while True:
        hdr = _read_exact(sock, _HDR.size)
        body_len, msg_type, meta_len = _HDR.unpack(hdr)
        body = _read_exact(sock, body_len - 1 - 4) if body_len > 5 else b''
        meta = json.loads(body[:meta_len].decode('utf-8')) if meta_len \
            else {}
        payload = body[meta_len:]
        # fault hook AFTER the full frame was consumed (framing stays
        # intact); 'drop' discards this message and reads the next
        if _faults().on_recv(sock, msg_type, meta) == 'drop':
            continue
        value = _value_of(meta, payload) if 'dtype' in meta else None
        return msg_type, meta, value
