"""Process supervisor for elastic cluster recovery.

Launches cluster roles (trainers, pservers, a master) as subprocesses,
watches for exits, and restarts failed roles under a backoff +
restart-budget policy — the local-process analog of what a k8s
restartPolicy or the reference's paddlecloud supervisor does for a real
cluster, sized for the subprocess cluster tests and tools/chaos_sweep.

Restart semantics:

- exit 0 is DONE: the role finished; it is never restarted.
- nonzero exit is a FAILURE: the role is restarted after a backoff
  (exponential per role, capped), until its restart budget
  (`max_restarts`) is spent — then the role is FAILED and stays down.
- a role that stayed up for ``healthy_secs`` (FLAGS_sup_healthy_secs)
  before dying gets its restart BUDGET and backoff exponent reset
  first: a replica that crashes once a day is a healthy role having a
  bad moment, not a crash loop. The LIFETIME restart count (and the
  incarnation fence it feeds) keeps growing monotonically.
- every restart sets ``FLAGS_trainer_incarnation`` to the role's
  restart count in the child's environment, so a restarted trainer
  re-registers with a higher incarnation and the pserver's fence
  admits it while rejecting its zombie predecessor
  (param_service._fence_locked).
- ``FLAGS_fault_plan`` is STRIPPED from the restart environment by
  default: the plan that killed the process (the `exit` fault action)
  would deterministically kill the restarted process at the same
  message count again.

Output handling: each role's stdout+stderr append to a per-role log
file (pipes would deadlock once a 64 KB buffer fills with nobody
draining it — the supervisor must keep watching, not reading).

Observability: with ``obs_dir`` set, every role gets its OWN subdir
planted into its environment as ``FLAGS_obs_dir`` (plus
``FLAGS_obs_role`` = the role name), so each process's telemetry and
trace JSONL land side by side and ``tools/obs_report.py`` can merge
the whole run into one timeline. The supervisor itself appends its
spawn/restart counters under ``<obs_dir>/supervisor/`` — written
directly (not through the process-wide registry) so supervising from
inside a test process never flips global telemetry state.
"""
from __future__ import annotations

import json
import os
import subprocess
import threading
import time

__all__ = ['Supervisor']


class _Role(object):
    def __init__(self, name, argv, env, restartable, max_restarts):
        self.name = name
        self.argv = list(argv)
        self.env = dict(env) if env is not None else None
        self.restartable = restartable
        self.max_restarts = max_restarts
        self.proc = None
        self.restarts = 0             # LIFETIME — feeds the incarnation
        self.budget_used = 0          # restarts since last healthy run
        self.spawned_at = None        # monotonic; healthy-secs clock
        self.state = 'pending'        # pending|running|done|failed|removed
        self.next_restart_at = None   # monotonic; backoff gate
        self.log_path = None


class Supervisor(object):
    """Launch roles, restart the ones that die, report how it went.

    usage::

        sup = Supervisor(log_dir=tmpdir)
        sup.add_role('pserver0', [sys.executable, worker], env=ps_env)
        sup.add_role('trainer0', [sys.executable, worker], env=tr_env)
        sup.start()
        states = sup.wait(timeout=120)   # {'pserver0': 'done', ...}
        sup.stop()
    """

    def __init__(self, max_restarts=3, backoff=0.5,
                 backoff_multiplier=2.0, max_backoff=10.0, log_dir=None,
                 clear_fault_plan_on_restart=True, obs_dir=None,
                 clear_env_on_restart=(), healthy_secs=None):
        from ..flags import get_flag
        self.max_restarts = int(max_restarts)
        self.healthy_secs = float(healthy_secs
                                  if healthy_secs is not None
                                  else get_flag('sup_healthy_secs'))
        self.backoff = float(backoff)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff = float(max_backoff)
        self.log_dir = log_dir
        self.clear_fault_plan_on_restart = clear_fault_plan_on_restart
        # extra env vars dropped from every RESTART environment (the
        # FLAGS_fault_plan strip, generalized): anything that must only
        # apply to the FIRST incarnation — a one-shot kill trigger, a
        # cold-start-only knob — goes here
        self.clear_env_on_restart = tuple(clear_env_on_restart)
        self.obs_dir = obs_dir
        self._roles = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor = None
        self._started = False
        self.events = []   # [(monotonic, role, event_str), ...]

    # -- configuration -----------------------------------------------------
    def add_role(self, name, argv, env=None, restartable=True,
                 max_restarts=None):
        """Register a role; `env` replaces os.environ for the child
        when given; restartable=False makes any nonzero exit terminal
        (a role whose failure the test wants to SEE). After start()
        this is the fleet scale-OUT primitive: the role is spawned
        immediately and the monitor picks it up. Returns the role
        name."""
        if max_restarts is None:
            max_restarts = self.max_restarts
        role = _Role(name, argv, env, restartable, int(max_restarts))
        with self._lock:
            self._roles.append(role)
        if self._started:
            self._spawn(role)
            self._ensure_monitor()
        return name

    def remove_role(self, name, kill=True):
        """Retire a role at runtime (fleet scale-IN): the monitor stops
        watching it and — with kill=True — its process is killed. A
        removed role counts as settled for wait()."""
        with self._lock:
            role = next((r for r in self._roles if r.name == name), None)
        if role is None:
            raise ValueError('unknown role %r' % name)
        role.state = 'removed'
        self._event(role, 'removed')
        if kill and role.proc is not None and role.proc.poll() is None:
            role.proc.kill()
            try:
                role.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._started = True
        for role in list(self._roles):
            self._spawn(role)
        self._ensure_monitor()

    def _ensure_monitor(self):
        if self._monitor is not None and self._monitor.is_alive():
            return
        if self._stop.is_set():
            return
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True)
        self._monitor.start()

    def _log_file(self, role):
        if self.log_dir is None:
            return subprocess.DEVNULL
        if role.log_path is None:
            role.log_path = os.path.join(self.log_dir,
                                         '%s.log' % role.name)
        return open(role.log_path, 'ab')

    def _spawn(self, role):
        env = dict(role.env if role.env is not None else os.environ)
        if role.restarts:
            env['FLAGS_trainer_incarnation'] = str(role.restarts)
            if self.clear_fault_plan_on_restart:
                env.pop('FLAGS_fault_plan', None)
            for key in self.clear_env_on_restart:
                env.pop(key, None)
        if self.obs_dir:
            # one obs subdir per role: each incarnation appends its own
            # metrics-/events- files there (filenames carry the pid),
            # and the role name becomes the timeline lane
            role_obs = os.path.join(self.obs_dir, role.name)
            os.makedirs(role_obs, exist_ok=True)
            env['FLAGS_obs_dir'] = role_obs
            env['FLAGS_obs_role'] = role.name
        logf = self._log_file(role)
        try:
            role.proc = subprocess.Popen(role.argv, env=env,
                                         stdout=logf, stderr=logf)
        finally:
            if logf is not subprocess.DEVNULL:
                logf.close()   # the child holds its own fd now
        role.spawned_at = time.monotonic()
        role.state = 'running'
        self._event(role, 'spawned' if not role.restarts
                    else 'restarted #%d' % role.restarts)

    def _event(self, role, what):
        with self._lock:
            self.events.append((time.monotonic(), role.name, what))
        if self.obs_dir:
            self._write_obs(role, what)

    def _write_obs(self, role, what):
        """Append the supervisor's own obs records: an instant event
        per lifecycle transition plus a running metrics snapshot —
        rewritten on every event so the counters survive even if the
        supervising process is killed without a stop()."""
        d = os.path.join(self.obs_dir, 'supervisor')
        try:
            os.makedirs(d, exist_ok=True)
            pid = os.getpid()
            now = time.time()
            with open(os.path.join(
                    d, 'events-supervisor-%d.jsonl' % pid), 'a') as f:
                f.write(json.dumps(
                    {'type': 'fault', 't': now, 'role': 'supervisor',
                     'pid': pid, 'action': what,
                     'target': role.name}) + '\n')
            with self._lock:
                restarts = sum(r.restarts for r in self._roles)
                spawns = sum(1 for e in self.events
                             if e[2].startswith(('spawned', 'restarted')))
            with open(os.path.join(
                    d, 'metrics-supervisor-%d.jsonl' % pid), 'a') as f:
                f.write(json.dumps(
                    {'ts': now, 'role': 'supervisor', 'pid': pid,
                     'counters': {'supervisor.restarts': restarts,
                                  'supervisor.spawns': spawns},
                     'gauges': {}, 'hists': {}}) + '\n')
        except OSError:
            pass   # observability must never take the supervisor down

    def _monitor_loop(self):
        # runs until stop(): roles can be added at runtime (fleet
        # scale-out), so "everything settled" is never final
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                roles = list(self._roles)
            for role in roles:
                if role.state == 'running':
                    rc = role.proc.poll()
                    if rc is None:
                        continue
                    if rc == 0:
                        role.state = 'done'
                        self._event(role, 'exit 0')
                        continue
                    self._event(role, 'exit %d' % rc)
                    if (role.spawned_at is not None and self.healthy_secs
                            and now - role.spawned_at
                            >= self.healthy_secs
                            and role.budget_used):
                        # healthy long enough: this crash starts a
                        # fresh budget + backoff ladder; the lifetime
                        # count (incarnation fence) keeps climbing
                        role.budget_used = 0
                        self._event(role, 'budget reset (healthy %.1fs)'
                                    % (now - role.spawned_at))
                    if (not role.restartable
                            or role.budget_used >= role.max_restarts):
                        role.state = 'failed'
                        continue
                    role.budget_used += 1
                    role.restarts += 1
                    delay = min(
                        self.backoff * self.backoff_multiplier
                        ** (role.budget_used - 1), self.max_backoff)
                    role.state = 'backoff'
                    role.next_restart_at = now + delay
                elif role.state == 'backoff':
                    if now >= role.next_restart_at:
                        self._spawn(role)
            self._stop.wait(timeout=0.05)

    def wait(self, timeout=None):
        """Block until every role settled (done/failed) or `timeout`
        elapsed. -> {name: state} snapshot ('running'/'backoff' entries
        mean the timeout hit first — the caller's hang verdict)."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            states = self.states()
            if all(s in ('done', 'failed', 'removed')
                   for s in states.values()):
                return states
            if deadline is not None and time.monotonic() >= deadline:
                return states
            time.sleep(0.05)

    def states(self):
        return {r.name: r.state for r in self._roles}

    @property
    def restarts(self):
        return {r.name: r.restarts for r in self._roles}

    def output(self, name):
        """Accumulated log of a role across all its incarnations."""
        for r in self._roles:
            if r.name == name and r.log_path \
                    and os.path.exists(r.log_path):
                with open(r.log_path, 'rb') as f:
                    return f.read().decode('utf-8', 'replace')
        return ''

    def stop(self):
        """Kill anything still running and stop the monitor."""
        self._stop.set()
        for role in self._roles:
            if role.proc is not None and role.proc.poll() is None:
                role.proc.kill()
                try:
                    role.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
