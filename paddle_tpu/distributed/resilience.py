"""Resilience layer for the distributed stack: deterministic fault
injection, retry policy, and the RPC failure taxonomy.

The reference stack survives failures with dedicated machinery
(GRPCClient channel retry, the Go master's lease/TaskFailed cycle) but
offers no way to *provoke* those failures deterministically in tests.
This module provides both halves:

**Failure taxonomy** — every RPC failure is either

- `RetryableRPCError` (subclass of ConnectionError): transport-level or
  explicitly transient — a reconnect + idempotent replay is safe and is
  performed transparently by `PSClient`/`MasterClient`;
- `FatalRPCError` (subclass of RuntimeError): the server executed the
  request and rejected it (zombie trainer, optimize failure, bad
  message) — replaying cannot help; `Trainer.train` reacts by rolling
  back to the last SUCCESS-marked checkpoint.

`REPLY_ERR` wire metas carry a `retryable` bool so the classification
crosses the wire.

**RetryPolicy** — shared exponential-backoff-plus-jitter schedule used
by every reconnecting client (flags: `rpc_max_retries`,
`rpc_retry_backoff`, `rpc_retry_max_backoff`, `rpc_reconnect_secs`).

**FaultPlan** — a declarative, seeded description of faults to inject
at the wire layer (hooks in `wire.write_msg`/`read_msg`) and at the
Trainer step boundary. Enabled per-process via `FLAGS_fault_plan`
(a JSON plan, or ``seed:N`` for a generated plan), so a subprocess
cluster test can fault exactly one role. Schema::

    {"rules": [
       {"when": "send",           # send | recv | step
        "type": "SEND_VAR",       # wire/master msg-type name, or "*"
        "nth": 3,                 # fire on the Nth matching event
        "action": "drop",         # drop | close | delay | error | exit
                                  #   | corrupt | nan | stall
        "secs": 0.2,              # delay / stall only
        "retryable": true,        # error only (default true)
        "code": 137,              # exit only (default 137, = kill -9)
        "bits": 1}]}              # corrupt only: bits to flip (default 1)

Counting is per-process and per (when, type): the plan is fully
deterministic given the message sequence, which host-side RPC ops emit
in deterministic order. Actions:

- ``drop``  (send): the message is never sent; the connection is closed
  so the failure surfaces immediately (a TCP message is only ever
  "lost" because its connection died) — replay must re-apply it.
- ``close`` (send): the message IS sent, then the connection closes
  before the reply — replay of an applied mutation must be deduped.
- ``delay``: sleep `secs`, then proceed normally.
- ``error``: raise `RetryableRPCError` or `FatalRPCError` in place.
- ``exit``: `os._exit(code)` — the process dies instantly with no
  cleanup, no atexit, no socket shutdown: the deterministic analog of
  `kill -9` at an exact point in the message sequence, used by the
  elastic-recovery chaos tests to kill a trainer or pserver mid-round.
- ``corrupt`` (send only): the frame is sent with `bits` bits flipped
  inside its CRC-covered region — a deterministic wire bit-flip. The
  receiver's CRC check must reject it (FrameCorruptError) and the
  retry resends a clean copy: the corrupt payload is never applied.
- ``nan`` (send or step): on send, the dense float payload is replaced
  with NaNs BEFORE framing (valid CRC — a numeric fault, not a
  transport fault) so the pserver's finite-gradient guard rejects it;
  on step, the trainer poisons one feed value so the numeric-anomaly
  guard (FLAGS_anomaly_action) sees a non-finite loss.
- ``stall`` (send or recv): hold the connection open for `secs`
  without letting the message proceed — the gray-failure primitive
  (Huang et al.): the process is alive, the socket stays connected,
  health probes on OTHER connections keep answering, but the stalled
  connection makes no progress. Unlike ``delay`` (a short, silent
  hiccup the retry layer absorbs), ``stall`` writes an audit line to
  stderr when it fires and is sized to outlast progress timeouts, so
  chaos harnesses can assert the watchdog — not the stall ending —
  unwedged the stream.

The wire layer cooperates on ``close``/``corrupt``/``nan``: `on_send`
returns a `SendEffect` whose `action` tells `wire.write_msg` what to do
to the frame (flip bits after framing, poison the payload before it, or
close the socket after sending). The hook fires exactly once per send,
so the counters advance past a fired rule and the retry goes clean.

On the recv side, ``drop`` discards the parsed message and reads the
next one; ``close``/``delay``/``error`` mirror the send side. ``step``
rules fire in `Trainer.train` just before a step executes (`on_step`
returns ``'nan'`` when a nan step rule fires).
"""
from __future__ import annotations

import json
import os
import random
import threading
import time

__all__ = ['RetryableRPCError', 'FatalRPCError', 'TransientError',
           'StaleIncarnationError', 'RetryPolicy', 'FaultRule',
           'FaultPlan', 'SendEffect', 'install_plan', 'clear_plan',
           'active_plan', 'current_plan', 'fired_faults', 'on_send',
           'on_recv', 'on_send_vars', 'on_recv_vars', 'on_step']


class RetryableRPCError(ConnectionError):
    """Transient RPC failure: reconnect + idempotent replay is safe."""


# alias: injected transient faults and server-side transient rejections
TransientError = RetryableRPCError


class FatalRPCError(RuntimeError):
    """Non-retryable RPC failure: the server rejected the request (or
    retries were escalated); replay cannot help."""


class StaleIncarnationError(FatalRPCError):
    """A message carried an incarnation older than the one the pserver
    has registered for that trainer id: a zombie process from before a
    restart. Non-retryable by definition — the fresh incarnation owns
    the trainer id now, and replaying a stale message can only corrupt
    its rounds."""


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

class RetryPolicy(object):
    """Exponential backoff with jitter, shared by every RPC client.

    `schedule()` yields the sleep-before-attempt time for each attempt:
    0.0 for the first try, then backoff * multiplier^k (capped at
    max_backoff) with up to `jitter` fractional randomization so a
    cluster of replaying trainers doesn't thundering-herd the pserver.
    """

    def __init__(self, max_attempts=5, backoff=0.05, max_backoff=2.0,
                 multiplier=2.0, jitter=0.25, reconnect_secs=3.0,
                 seed=None):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.reconnect_secs = float(reconnect_secs)
        self.seed = seed

    @classmethod
    def from_flags(cls):
        from ..flags import get_flag
        return cls(max_attempts=int(get_flag('rpc_max_retries', 5)),
                   backoff=float(get_flag('rpc_retry_backoff', 0.05)),
                   max_backoff=float(get_flag('rpc_retry_max_backoff',
                                              2.0)),
                   reconnect_secs=float(get_flag('rpc_reconnect_secs',
                                                 3.0)))

    def schedule(self):
        rng = random.Random(self.seed)
        delay = self.backoff
        for attempt in range(self.max_attempts):
            if attempt == 0:
                yield 0.0
            else:
                yield delay * (1.0 + self.jitter * rng.random())
                delay = min(delay * self.multiplier, self.max_backoff)


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------

_ACTIONS = ('drop', 'close', 'delay', 'error', 'exit', 'corrupt', 'nan',
            'stall')
_WHENS = ('send', 'recv', 'step')


def _type_names():
    """Message-type name -> int over the wire + master namespaces."""
    from . import wire, master
    names = {'*': '*'}
    for mod in (wire, master):
        for k, v in vars(mod).items():
            if not k.startswith('_') and k.isupper() and isinstance(v, int):
                names[k] = v
    return names


class FaultRule(object):
    def __init__(self, when, nth, action, type='*', secs=0.1,
                 retryable=True, code=137, bits=1):
        if when not in _WHENS:
            raise ValueError('bad when %r (one of %s)' % (when, _WHENS))
        if action not in _ACTIONS:
            raise ValueError('bad action %r (one of %s)'
                             % (action, _ACTIONS))
        if action == 'corrupt' and when != 'send':
            raise ValueError("action 'corrupt' requires when='send' "
                             '(bits are flipped in the outbound frame)')
        if action == 'nan' and when == 'recv':
            raise ValueError("action 'nan' requires when='send' or "
                             "'step' (the poison is injected at the "
                             'producer)')
        if action == 'stall' and when == 'step':
            raise ValueError("action 'stall' requires when='send' or "
                             "'recv' (it holds a wire connection open)")
        self.when = when
        self.type = type
        self.nth = int(nth)
        self.action = action
        self.secs = float(secs)
        self.retryable = bool(retryable)
        self.code = int(code)
        self.bits = max(1, int(bits))

    def to_dict(self):
        d = {'when': self.when, 'type': self.type, 'nth': self.nth,
             'action': self.action}
        if self.action in ('delay', 'stall'):
            d['secs'] = self.secs
        if self.action == 'error':
            d['retryable'] = self.retryable
        if self.action == 'exit':
            d['code'] = self.code
        if self.action == 'corrupt':
            d['bits'] = self.bits
        return d


class FaultPlan(object):
    """An ordered set of FaultRules; see the module docstring schema."""

    def __init__(self, rules, seed=None):
        self.rules = list(rules)
        self.seed = seed

    # -- construction ------------------------------------------------------
    @classmethod
    def from_dict(cls, d):
        return cls([FaultRule(**r) for r in d.get('rules', [])],
                   seed=d.get('seed'))

    @classmethod
    def from_json(cls, s):
        return cls.from_dict(json.loads(s))

    @classmethod
    def from_spec(cls, spec):
        """``seed:N`` | ``kill:ROLE:N`` | ``corrupt:N`` |
        ``grayfail:ROLE:N`` | a JSON object string | a path to a JSON
        file.

        A malformed spec fails HERE, loudly, with the offending text —
        install time is the only moment anyone is looking; a deferred
        parse error would surface mid-training as a mystery."""
        spec = spec.strip()
        try:
            if spec.startswith('seed:'):
                return cls.from_seed(int(spec[len('seed:'):]))
            if spec.startswith('kill:'):
                role, seed = spec[len('kill:'):].split(':', 1)
                return cls.from_kill_seed(int(seed), role)
            if spec.startswith('corrupt:'):
                return cls.from_corrupt_seed(int(spec[len('corrupt:'):]))
            if spec.startswith('grayfail:'):
                role, seed = spec[len('grayfail:'):].split(':', 1)
                return cls.from_grayfail_seed(int(seed), role)
            if spec.startswith('{'):
                return cls.from_json(spec)
            with open(spec) as f:
                return cls.from_json(f.read())
        except (ValueError, KeyError, TypeError, OSError,
                json.JSONDecodeError) as e:
            raise ValueError('unparseable fault plan %r: %s: %s'
                             % (spec, type(e).__name__, e))

    @classmethod
    def from_seed(cls, seed, max_rules=3, max_nth=10):
        """Deterministically generate a plan from a seed: 1..max_rules
        send-side faults over the trainer->pserver message types, mostly
        transient (drop/close/delay/retryable error) with a small chance
        of a fatal error — the chaos_sweep distribution."""
        rng = random.Random(seed)
        types = ['SEND_VAR', 'BATCH_BARRIER', 'GET_VAR', 'FETCH_BARRIER']
        rules = []
        for _ in range(rng.randint(1, max_rules)):
            roll = rng.random()
            if roll < 0.30:
                action, kw = 'drop', {}
            elif roll < 0.60:
                action, kw = 'close', {}
            elif roll < 0.80:
                action, kw = 'delay', {'secs': round(
                    0.05 + 0.25 * rng.random(), 3)}
            elif roll < 0.95:
                action, kw = 'error', {'retryable': True}
            else:
                action, kw = 'error', {'retryable': False}
            rules.append(FaultRule('send', rng.randint(1, max_nth),
                                   action, type=rng.choice(types), **kw))
        return cls(rules, seed=seed)

    @classmethod
    def from_kill_seed(cls, seed, role, max_nth=8):
        """One seeded ``exit`` rule: kill the process at the Nth message
        event of a randomly chosen type — the chaos_sweep --kill
        distribution.

        Kill points are limited to those from which recovery is EXACT:

        - pserver: any inbound mutation (``recv`` of SEND_VAR /
          BATCH_BARRIER / GET_VAR) — the journal + client replay
          restore the precise pre-kill state.
        - trainer: ``send`` of SEND_VAR / GET_VAR / FETCH_BARRIER. A
          kill between the two per-pserver BATCH_BARRIER sends is
          deliberately excluded: one shard would close the round while
          the other waits, and the restarted trainer would pull
          mixed-round params — recovery would converge but not
          bit-exactly, which the sweep cannot distinguish from a bug.
        """
        rng = random.Random(('kill', role, seed).__repr__())
        if role == 'pserver':
            when = 'recv'
            types = ['SEND_VAR', 'BATCH_BARRIER', 'GET_VAR']
        elif role == 'trainer':
            when = 'send'
            types = ['SEND_VAR', 'GET_VAR', 'FETCH_BARRIER']
        else:
            raise ValueError('bad kill role %r (trainer | pserver)'
                             % (role,))
        rule = FaultRule(when, rng.randint(2, max_nth), 'exit',
                         type=rng.choice(types))
        return cls([rule], seed=seed)

    @classmethod
    def from_grayfail_seed(cls, seed, role, max_nth=6):
        """One seeded ``stall`` rule: at the Nth inbound SRV_POLL the
        replica's data connection freezes for 20-40s — alive-but-slow,
        the chaos_sweep --grayfail distribution.

        SRV_POLL recv is the canonical gray-failure point: the stream
        was accepted, tokens are being generated, health probes (their
        own connection, their own server thread) keep passing — but the
        router's view of progress stops dead. The stall is sized to
        outlast any sane FLAGS_fleet_progress_timeout_secs, so a run
        that completes did so because the watchdog gray-marked the
        replica and failed streams over, never because the stall
        expired first. max_nth stays small relative to the polls a
        driver burst actually generates (one batched SRV_POLL per
        FLAGS_fleet_poll_secs tick while streams are live) so the rule
        reliably fires before the burst drains."""
        rng = random.Random(('grayfail', role, seed).__repr__())
        rule = FaultRule('recv', rng.randint(2, max_nth), 'stall',
                         type='SRV_POLL',
                         secs=round(20.0 + 20.0 * rng.random(), 1))
        return cls([rule], seed=seed)

    @classmethod
    def from_corrupt_seed(cls, seed, max_rules=2, max_nth=10):
        """Seeded integrity faults: 1..max_rules send-side ``corrupt``
        (bit flips in a frame — the CRC must catch them) and ``nan``
        (poisoned gradient — the finite guard must catch it) rules, the
        chaos_sweep --corrupt distribution. Every rule is recoverable
        by design: the sweep expects bit-exact convergence, never
        fatal."""
        rng = random.Random(('corrupt', seed).__repr__())
        types = ['SEND_VAR', 'BATCH_BARRIER', 'GET_VAR', 'FETCH_BARRIER']
        rules = []
        for _ in range(rng.randint(1, max_rules)):
            if rng.random() < 0.7:
                rules.append(FaultRule(
                    'send', rng.randint(1, max_nth), 'corrupt',
                    type=rng.choice(types), bits=rng.randint(1, 8)))
            else:
                # nan only makes sense on a gradient push
                rules.append(FaultRule(
                    'send', rng.randint(1, max_nth), 'nan',
                    type='SEND_VAR'))
        return cls(rules, seed=seed)

    def to_json(self):
        d = {'rules': [r.to_dict() for r in self.rules]}
        if self.seed is not None:
            d['seed'] = self.seed
        return json.dumps(d)


# ---------------------------------------------------------------------------
# per-process installation + hook implementation
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_plan = None          # active FaultPlan or None
_counts = {}          # (when, type_key) -> messages seen
_fired = []           # audit log of fired rules
_names = None         # msg-type name map, resolved lazily


def install_plan(plan):
    """Activate `plan` process-wide and reset the event counters."""
    global _plan
    with _lock:
        _plan = plan
        _counts.clear()
        del _fired[:]


def clear_plan():
    install_plan(None)


def current_plan():
    return _plan


def fired_faults():
    """Audit log: [{'when','type','nth','action'}, ...] fired so far."""
    with _lock:
        return [dict(f) for f in _fired]


class active_plan(object):
    """Context manager: install a plan for the block, then restore."""

    def __init__(self, plan):
        self.plan = plan

    def __enter__(self):
        self._prev = _plan
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc):
        install_plan(self._prev)


def _match_locked(when, msg_type):
    """Advance counters for one event; return the rule to fire or None.
    Must run under _lock so concurrent connections count atomically."""
    global _names
    if _names is None:
        _names = _type_names()
    hit = None
    keys = (msg_type, '*') if msg_type != '*' else ('*',)
    for key in keys:
        n = _counts.get((when, key), 0) + 1
        _counts[(when, key)] = n
        for rule in _plan.rules:
            if rule.when != when or rule.nth != n:
                continue
            rtype = _names.get(rule.type, rule.type)
            if rtype != key:
                continue
            hit = rule
    if hit is not None:
        _fired.append({'when': when, 'type': hit.type, 'nth': hit.nth,
                       'action': hit.action})
        from ..obs import telemetry
        telemetry.counter('faults.injected').inc()
        # snapshot NOW, before the action takes effect: an 'exit' rule
        # (the kill -9 analog) dies with os._exit — no atexit, no final
        # periodic export — and a short-lived incarnation would
        # otherwise leave no metrics line at all. Firing a fault is the
        # one moment a chaos run's counters must be durable.
        try:
            telemetry.flush()
        except Exception:
            pass   # observability must never alter the injected fault
    return hit


def _close_quietly(sock):
    try:
        sock.close()
    except OSError:
        pass


class SendEffect(object):
    """Returned by on_send for the actions the wire layer must
    cooperate on. `action` is one of 'close' (send the frame, then run
    post_send), 'corrupt' (send mutate_frame(frame)) or 'nan' (poison
    the float payload before framing)."""

    def __init__(self, rule, sock, index=0):
        self.action = rule.action
        self.rule = rule
        # for batched sends: which contained var's rule fired ('nan'
        # poisons only that var's bytes; frame-scoped actions ignore it)
        self.index = index
        self._sock = sock

    def post_send(self):
        _close_quietly(self._sock)

    def mutate_frame(self, frame, lo):
        """Deterministically flip `rule.bits` bits in frame[lo:] — the
        CRC-covered body region. (Flipping header length fields instead
        would desync the stream, a failure the read deadline surfaces;
        body flips are what the CRC exists to catch.)"""
        rng = random.Random(
            ('corrupt-bits', self.rule.type, self.rule.nth,
             self.rule.bits).__repr__())
        buf = bytearray(frame)
        for _ in range(self.rule.bits):
            pos = rng.randrange(lo, len(buf))
            buf[pos] ^= 1 << rng.randrange(8)
        return bytes(buf)


def _raise_for(rule, where):
    msg = 'fault injection: %s at %s (rule %s)' % (rule.action, where,
                                                   rule.to_dict())
    if rule.action == 'error' and not rule.retryable:
        raise FatalRPCError(msg)
    raise RetryableRPCError(msg)


def _stall_for(rule, where):
    """The 'stall' action: freeze this connection for rule.secs while
    the process stays alive and every other connection keeps serving.
    The audit line lands on stderr BEFORE the sleep — a chaos harness
    greps for it to prove the gray failure actually fired even when the
    watchdog unwedges the victim long before the stall expires."""
    import sys
    sys.stderr.write('fault injection: stall %.1fs at %s (rule %s)\n'
                     % (rule.secs, where, rule.to_dict()))
    sys.stderr.flush()
    time.sleep(rule.secs)


def _exit_for(rule, where):
    """The 'exit' action: die NOW, with no cleanup of any kind.
    sys.stderr is flushed (it carries the audit line chaos tests grep
    for) but sockets, locks and atexit handlers are abandoned exactly
    as kill -9 would abandon them."""
    import sys
    sys.stderr.write('fault injection: exit(%d) at %s (rule %s)\n'
                     % (rule.code, where, rule.to_dict()))
    sys.stderr.flush()
    os._exit(rule.code)


def on_send(sock, msg_type, meta):
    """wire.write_msg hook, called BEFORE the frame hits the socket.
    Returns None, or a SendEffect the wire layer applies ('close':
    frame delivered then connection dies; 'corrupt': bits flipped in
    the outbound frame; 'nan': float payload poisoned before
    framing)."""
    if _plan is None:
        return None
    with _lock:
        rule = _match_locked('send', msg_type)
    if rule is None:
        return None
    if rule.action == 'delay':
        time.sleep(rule.secs)
        return None
    if rule.action == 'stall':
        _stall_for(rule, 'send of msg type %s' % msg_type)
        return None
    if rule.action == 'drop':
        _close_quietly(sock)
        raise RetryableRPCError(
            'fault injection: dropped msg type %s (rule %s)'
            % (msg_type, rule.to_dict()))
    if rule.action in ('close', 'corrupt', 'nan'):
        return SendEffect(rule, sock)
    if rule.action == 'exit':
        _exit_for(rule, 'send of msg type %s' % msg_type)
    _raise_for(rule, 'send of msg type %s' % msg_type)


def on_send_vars(sock, msg_type, entries):
    """wire.write_vars_msg hook: a SEND_VARS batch advances the 'send'
    counters once PER CONTAINED VAR — the exact logical firing points a
    per-var send loop would hit — so a seeded plan faults the same Nth
    gradient whether or not batching is on. The frame is a single
    physical send, so frame-scoped actions apply once to the whole
    batch, with precedence when several rules land in one batch: every
    'exit' first (kill determinism), 'delay' sleeps accumulate, then
    the first drop/close/corrupt/nan/error in entry order wins. A 'nan'
    SendEffect carries the index of the entry whose rule fired so only
    that var's payload is poisoned."""
    if _plan is None:
        return None
    fired = []
    with _lock:
        for i in range(len(entries)):
            rule = _match_locked('send', msg_type)
            if rule is not None:
                fired.append((i, rule))
    if not fired:
        return None
    for i, rule in fired:
        if rule.action == 'exit':
            _exit_for(rule, 'send of msg type %s (batch var %d)'
                      % (msg_type, i))
    for i, rule in fired:
        if rule.action == 'delay':
            time.sleep(rule.secs)
        elif rule.action == 'stall':
            _stall_for(rule, 'send of msg type %s (batch var %d)'
                       % (msg_type, i))
    for i, rule in fired:
        if rule.action == 'drop':
            _close_quietly(sock)
            raise RetryableRPCError(
                'fault injection: dropped batch of %d (msg type %s, '
                'rule %s)' % (len(entries), msg_type, rule.to_dict()))
        if rule.action in ('close', 'corrupt', 'nan'):
            return SendEffect(rule, sock, index=i)
        if rule.action == 'error':
            _raise_for(rule, 'send of msg type %s (batch var %d)'
                       % (msg_type, i))
    return None


def on_recv_vars(sock, msg_type, count):
    """wire.read_msg hook for an inbound SEND_VARS frame: advances the
    'recv' counters once per contained var (mirroring on_send_vars).
    'drop' discards the WHOLE batch frame — per-var dedup tokens make
    the client's replay apply each var at-most-once; exit/delay/close/
    error follow the same precedence as on_send_vars."""
    if _plan is None:
        return None
    fired = []
    with _lock:
        for i in range(count):
            rule = _match_locked('recv', msg_type)
            if rule is not None:
                fired.append((i, rule))
    if not fired:
        return None
    for i, rule in fired:
        if rule.action == 'exit':
            _exit_for(rule, 'recv of msg type %s (batch var %d)'
                      % (msg_type, i))
    for i, rule in fired:
        if rule.action == 'delay':
            time.sleep(rule.secs)
        elif rule.action == 'stall':
            _stall_for(rule, 'recv of msg type %s (batch var %d)'
                       % (msg_type, i))
    for i, rule in fired:
        if rule.action == 'drop':
            return 'drop'
        if rule.action == 'close':
            _close_quietly(sock)
            raise ConnectionError(
                'fault injection: closed on recv of msg type %s '
                '(batch var %d)' % (msg_type, i))
        if rule.action == 'error':
            _raise_for(rule, 'recv of msg type %s (batch var %d)'
                       % (msg_type, i))
    return None


def on_recv(sock, msg_type, meta):
    """wire.read_msg hook, called AFTER a full frame was parsed (framing
    stays intact). Returns 'drop' to discard the message and read the
    next one, else None."""
    if _plan is None:
        return None
    with _lock:
        rule = _match_locked('recv', msg_type)
    if rule is None:
        return None
    if rule.action == 'delay':
        time.sleep(rule.secs)
        return None
    if rule.action == 'stall':
        _stall_for(rule, 'recv of msg type %s' % msg_type)
        return None
    if rule.action == 'drop':
        return 'drop'
    if rule.action == 'close':
        _close_quietly(sock)
        raise ConnectionError(
            'fault injection: closed on recv of msg type %s' % msg_type)
    if rule.action == 'exit':
        _exit_for(rule, 'recv of msg type %s' % msg_type)
    _raise_for(rule, 'recv of msg type %s' % msg_type)


def on_step():
    """Trainer step hook: fires 'step' rules (delay sleeps; 'nan'
    returns the string 'nan' so the Trainer poisons one feed value;
    drop/close/error all raise per the rule's retryable
    classification)."""
    if _plan is None:
        return None
    with _lock:
        rule = _match_locked('step', '*')
    if rule is None:
        return None
    if rule.action == 'delay':
        time.sleep(rule.secs)
        return None
    if rule.action == 'nan':
        return 'nan'
    if rule.action == 'exit':
        _exit_for(rule, 'trainer step')
    _raise_for(rule, 'trainer step')


def _install_from_flags():
    """FLAGS_fault_plan (env-bootstrapped) activates a plan for this
    process — how subprocess cluster tests fault exactly one role."""
    from ..flags import get_flag
    spec = get_flag('fault_plan', '') or ''
    if spec:
        install_plan(FaultPlan.from_spec(spec))


_install_from_flags()
