"""Atomic on-disk state files, shared by every durable role.

Both the task master (`master.py`) and the parameter service
(`param_service.py`) persist recovery state the same way: write the
full new state to a temp file in the destination directory, fsync it,
then `os.replace` over the target. A reader therefore always sees
either the previous complete state or the new complete state — never a
torn file — and a crash mid-write leaves the previous state intact.
"""
from __future__ import annotations

import contextlib
import json
import os


@contextlib.contextmanager
def atomic_replace(path, mode='wb'):
    """Context manager yielding an open temp-file handle; on clean exit
    the temp file is fsynced and atomically renamed onto `path`, on
    exception it is removed and `path` is untouched.

    The temp name carries the pid so two processes racing to snapshot
    the same path (a restarted role overlapping its zombie) cannot
    interleave writes; last `os.replace` wins with a complete file.
    """
    tmp = '%s.%d.tmp' % (path, os.getpid())
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
    except BaseException:
        f.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path, obj):
    with atomic_replace(path, 'w') as f:
        json.dump(obj, f)


def read_json(path, default=None):
    """Load a JSON state file; `default` if it does not exist yet."""
    if not os.path.exists(path):
        return default
    with open(path) as f:
        return json.load(f)
