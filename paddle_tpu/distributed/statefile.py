"""Atomic on-disk state files, shared by every durable role.

Both the task master (`master.py`) and the parameter service
(`param_service.py`) persist recovery state the same way: write the
full new state to a temp file in the destination directory, fsync it,
then `os.replace` over the target. A reader therefore always sees
either the previous complete state or the new complete state — never a
torn file — and a crash mid-write leaves the previous state intact.

Atomicity protects against *torn* files; it does nothing against
*corrupt* ones (a bad disk, a truncating copy, a stray write). For
that, every durable payload gets a content-digest sidecar
(`<path>.crc`: crc32 + size, written after the payload lands) that
`verify_digest` checks before a load, and `quarantine` renames a file
that fails verification aside (`<path>.corrupt`) — loudly, and leaving
the bytes on disk for post-mortem — so recovery falls back to an older
generation instead of loading garbage.
"""
from __future__ import annotations

import contextlib
import json
import os
import sys

from ..integrity import crc32_file


@contextlib.contextmanager
def atomic_replace(path, mode='wb'):
    """Context manager yielding an open temp-file handle; on clean exit
    the temp file is fsynced and atomically renamed onto `path`, on
    exception it is removed and `path` is untouched.

    The temp name carries the pid so two processes racing to snapshot
    the same path (a restarted role overlapping its zombie) cannot
    interleave writes; last `os.replace` wins with a complete file.
    """
    tmp = '%s.%d.tmp' % (path, os.getpid())
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
    except BaseException:
        f.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path, obj):
    with atomic_replace(path, 'w') as f:
        json.dump(obj, f)


def read_json(path, default=None):
    """Load a JSON state file; `default` if it does not exist yet."""
    if not os.path.exists(path):
        return default
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# content digests + quarantine
# ---------------------------------------------------------------------------

def digest_path(path):
    return path + '.crc'


def write_digest(path):
    """Write `<path>.crc` = {"crc32", "size"} for the current contents
    of `path`. Written AFTER the payload is in place: a crash in the
    window leaves a payload without a sidecar, which verify_digest
    reports as 'missing' (accepted with a warning), never 'mismatch'."""
    crc, size = crc32_file(path)
    atomic_write_json(digest_path(path), {'crc32': crc, 'size': size})


def verify_digest(path):
    """-> 'ok' | 'missing' (no sidecar — pre-digest file or a crash
    between payload and sidecar writes) | 'mismatch' (the payload does
    not match its recorded digest: corrupt, quarantine it)."""
    want = read_json(digest_path(path))
    if not isinstance(want, dict) or 'crc32' not in want:
        return 'missing'
    crc, size = crc32_file(path)
    if crc != int(want['crc32']) or size != int(want.get('size', size)):
        return 'mismatch'
    return 'ok'


def move_with_digest(src, dst):
    """os.replace `src` -> `dst`, carrying its digest sidecar along (or
    removing a stale sidecar at `dst` if `src` has none)."""
    os.replace(src, dst)
    sp, dp = digest_path(src), digest_path(dst)
    if os.path.exists(sp):
        os.replace(sp, dp)
    else:
        try:
            os.remove(dp)
        except OSError:
            pass


def quarantine_dir(path, reason):
    """Directory flavor of `quarantine`: rename a corrupt checkpoint
    directory aside to `<path>.corrupt` (suffixed `-N` if that name is
    taken) so recovery can fall back to an older generation while the
    bytes stay on disk for post-mortem. Returns the quarantine path, or
    None if the dir vanished underneath us."""
    qpath = path + '.corrupt'
    n = 0
    while os.path.exists(qpath):
        n += 1
        qpath = '%s.corrupt-%d' % (path, n)
    try:
        os.replace(path, qpath)
    except OSError as e:
        sys.stderr.write('WARNING: could not quarantine dir %s (%s): %s\n'
                         % (path, reason, e))
        return None
    from ..obs import telemetry
    telemetry.counter('ps.snapshot.quarantines').inc()
    sys.stderr.write('WARNING: quarantined corrupt checkpoint dir %s -> %s '
                     '(%s); kept for post-mortem\n' % (path, qpath, reason))
    sys.stderr.flush()
    return qpath


def quarantine(path, reason):
    """Rename a corrupt file (and its sidecar) aside to `<path>.corrupt`
    — loudly. The bytes stay on disk for post-mortem; the original name
    is freed so recovery can rebuild it. Returns the quarantine path,
    or None if the file vanished underneath us."""
    qpath = path + '.corrupt'
    try:
        move_with_digest(path, qpath)
    except OSError as e:
        sys.stderr.write('WARNING: could not quarantine %s (%s): %s\n'
                         % (path, reason, e))
        return None
    from ..obs import telemetry
    telemetry.counter('ps.snapshot.quarantines').inc()
    sys.stderr.write('WARNING: quarantined corrupt file %s -> %s (%s); '
                     'kept for post-mortem\n' % (path, qpath, reason))
    sys.stderr.flush()
    return qpath
