"""TCP RPC client/server for parameter-server mode.

The capability analog of the reference's GRPCClient (operators/distributed/
grpc_client.h:175: AsyncSendVar/AsyncGetVar/AsyncPrefetchVar/
AsyncSendBatchBarrier/AsyncSendFetchBarrier/AsyncSendComplete) and
AsyncGRPCServer (grpc_server.h:46), re-based on plain sockets + the binary
wire format in wire.py. Each trainer holds one persistent connection per
pserver; the server runs one thread per connection and dispatches into a
service object (param_service.ParameterService) — the threading shape of
the reference's RunSyncLoop server.
"""
from __future__ import annotations

import socket
import threading
import time

from . import wire

__all__ = ['PSClient', 'PSServer', 'get_client', 'close_all_clients']


class PSClient(object):
    """One trainer's connection to one pserver endpoint."""

    def __init__(self, endpoint, trainer_id=0, timeout=120.0,
                 connect_retry_secs=60.0):
        self.endpoint = endpoint
        self.trainer_id = trainer_id
        host, port = endpoint.rsplit(':', 1)
        # trainers routinely start before their pservers finish binding
        # (reference GRPC clients block on channel readiness) — retry
        deadline = time.monotonic() + connect_retry_secs
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=timeout)
                break
            except (ConnectionRefusedError, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _call(self, msg_type, meta=None, value=None):
        meta = dict(meta or {})
        meta['trainer_id'] = self.trainer_id
        with self._lock:
            wire.write_msg(self._sock, msg_type, meta, value)
            rtype, rmeta, rvalue = wire.read_msg(self._sock)
        if rtype == wire.REPLY_ERR:
            raise RuntimeError('pserver %s: %s'
                               % (self.endpoint, rmeta.get('error')))
        return rmeta, rvalue

    def send_var(self, name, value):
        """Push a gradient (dense array or SelectedRows)."""
        self._call(wire.SEND_VAR, {'name': name}, value)

    def get_var(self, name):
        """Pull a parameter value."""
        _, value = self._call(wire.GET_VAR, {'name': name})
        return value

    def prefetch(self, table_name, ids):
        """Distributed lookup table: local row ids -> embedding rows."""
        import numpy as np
        _, rows = self._call(wire.PREFETCH, {'name': table_name},
                             np.asarray(ids, dtype='int32'))
        return rows

    def batch_barrier(self):
        self._call(wire.BATCH_BARRIER)

    def fetch_barrier(self):
        self._call(wire.FETCH_BARRIER)

    def checkpoint_notify(self, dirname):
        """Ask the pserver to save its parameter shard (reference
        checkpoint_notify_op.cc -> RequestCheckpointHandler)."""
        self._call(wire.CHECKPOINT, {'dirname': dirname})

    def complete(self):
        self._call(wire.COMPLETE)

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


# module-level client pool: one PSClient per endpoint for this process
# (the analog of GRPCClient's channel cache); Executor.close() drains it.
_clients = {}
_clients_lock = threading.Lock()


def get_client(endpoint, trainer_id=0):
    with _clients_lock:
        c = _clients.get(endpoint)
        if c is None:
            c = _clients[endpoint] = PSClient(endpoint, trainer_id)
        return c


def close_all_clients(send_complete=True):
    """Notify every connected pserver this trainer is done and drop the
    connections (reference Executor::Close -> SendComplete)."""
    with _clients_lock:
        for c in _clients.values():
            if send_complete:
                try:
                    c.complete()
                except (RuntimeError, OSError, ConnectionError):
                    pass
            c.close()
        _clients.clear()


class PSServer(object):
    """Threaded TCP server dispatching wire messages into a service.

    service interface (see param_service.ParameterService):
      on_send_var(name, trainer_id, value)
      on_get_var(name, trainer_id) -> value
      on_prefetch(name, trainer_id, ids) -> rows
      on_batch_barrier(trainer_id)
      on_fetch_barrier(trainer_id)
      on_checkpoint(dirname, trainer_id)
      on_complete(trainer_id)  -> True when ALL trainers completed
    """

    def __init__(self, endpoint, service):
        host, port = endpoint.rsplit(':', 1)
        self.service = service
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._done = threading.Event()
        self._threads = []

    def serve_forever(self):
        """Accept + dispatch until the service reports all trainers
        complete (the RunSyncLoop exit condition, listen_and_serv_op.cc:
        exit_flag on COMPLETE messages). A reaper thread sweeps trainer
        liveness (service.check_liveness) so a silently-dead trainer is
        retired after its rpc_deadline and the server still exits —
        the round-4 no-silent-deadlock guarantee."""
        accept_t = threading.Thread(target=self._accept_loop, daemon=True)
        accept_t.start()
        if hasattr(self.service, 'check_liveness'):
            reaper = threading.Thread(target=self._reap_loop, daemon=True)
            reaper.start()
        self._done.wait()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def _reap_loop(self):
        warned = False
        while not self._done.is_set():
            try:
                if self.service.check_liveness():
                    self.shutdown()
                    return
            except Exception as e:
                if not warned:   # a broken sweep must not fail silently
                    import sys
                    print('pserver liveness sweep failed: %r' % e,
                          file=sys.stderr)
                    warned = True
            self._done.wait(timeout=1.0)

    def shutdown(self):
        self._done.set()

    def _accept_loop(self):
        while not self._done.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        svc = self.service
        try:
            while True:
                try:
                    msg_type, meta, value = wire.read_msg(conn)
                except (ConnectionError, OSError):
                    return
                tid = int(meta.get('trainer_id', 0))
                name = meta.get('name')
                try:
                    if msg_type == wire.SEND_VAR:
                        svc.on_send_var(name, tid, value)
                        wire.write_msg(conn, wire.REPLY_OK)
                    elif msg_type == wire.GET_VAR:
                        out = svc.on_get_var(name, tid)
                        wire.write_msg(conn, wire.REPLY_VAR, value=out)
                    elif msg_type == wire.PREFETCH:
                        out = svc.on_prefetch(name, tid, value)
                        wire.write_msg(conn, wire.REPLY_VAR, value=out)
                    elif msg_type == wire.BATCH_BARRIER:
                        svc.on_batch_barrier(tid)
                        wire.write_msg(conn, wire.REPLY_OK)
                    elif msg_type == wire.FETCH_BARRIER:
                        svc.on_fetch_barrier(tid)
                        wire.write_msg(conn, wire.REPLY_OK)
                    elif msg_type == wire.CHECKPOINT:
                        svc.on_checkpoint(meta.get('dirname'), tid)
                        wire.write_msg(conn, wire.REPLY_OK)
                    elif msg_type == wire.COMPLETE:
                        all_done = svc.on_complete(tid)
                        wire.write_msg(conn, wire.REPLY_OK)
                        if all_done:
                            self.shutdown()
                    else:
                        wire.write_msg(conn, wire.REPLY_ERR,
                                       {'error': 'bad msg type %d' % msg_type})
                except Exception as e:   # surface server-side op errors
                    wire.write_msg(conn, wire.REPLY_ERR, {'error': str(e)})
        finally:
            try:
                conn.close()
            except OSError:
                pass
