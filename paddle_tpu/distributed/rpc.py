"""TCP RPC client/server for parameter-server mode.

The capability analog of the reference's GRPCClient (operators/distributed/
grpc_client.h:175: AsyncSendVar/AsyncGetVar/AsyncPrefetchVar/
AsyncSendBatchBarrier/AsyncSendFetchBarrier/AsyncSendComplete) and
AsyncGRPCServer (grpc_server.h:46), re-based on plain sockets + the binary
wire format in wire.py. Each trainer holds one persistent connection per
pserver; the server runs one thread per connection and dispatches into a
service object (param_service.ParameterService) — the threading shape of
the reference's RunSyncLoop server.

Resilience (see distributed/resilience.py): a PSClient survives a dropped
connection mid-training. Every request carries a `seq` number plus a
per-client incarnation nonce; on any transport failure the client closes
the poisoned socket, reconnects under the shared RetryPolicy
(exponential backoff + jitter), and REPLAYS the request with the SAME
seq. The ParameterService keeps a per-trainer dedup window, so a replay
of an already-applied mutation (SEND_VAR / BATCH_BARRIER / CHECKPOINT)
is acknowledged without being applied twice — a retried gradient never
double-counts in a sync round. REPLY_ERR metas carry `retryable`:
transient server rejections re-enter the retry loop, fatal ones raise
FatalRPCError (the reference GRPCClient's channel-retry/backoff model
plus at-most-once semantics that gRPC got from request ids).
"""
from __future__ import annotations

import binascii
import os
import socket
import threading
import time

from . import wire
from .resilience import FatalRPCError, RetryableRPCError, RetryPolicy
from ..obs import telemetry as _tm
from ..obs import trace as _trace

__all__ = ['PSClient', 'PSServer', 'get_client', 'close_all_clients',
           'RetryableRPCError', 'FatalRPCError']

# client-side RPC health: every logical call, every replay of one
# (retries), every fresh connection made to replace a dropped socket
# (reconnects), and read-deadline expiries specifically — the silent
# peer case (FLAGS_rpc_read_deadline)
_CALLS = _tm.counter('rpc.client.calls')
_RETRIES = _tm.counter('rpc.client.retries')
_RECONNECTS = _tm.counter('rpc.client.reconnects')
_DEADLINE_TIMEOUTS = _tm.counter('rpc.client.read_deadline_timeouts')
_CALL_LATENCY = _tm.histogram('rpc.client.call_latency')

_MSG_NAMES = {
    wire.SEND_VAR: 'SEND_VAR', wire.GET_VAR: 'GET_VAR',
    wire.PREFETCH: 'PREFETCH', wire.BATCH_BARRIER: 'BATCH_BARRIER',
    wire.FETCH_BARRIER: 'FETCH_BARRIER', wire.COMPLETE: 'COMPLETE',
    wire.CHECKPOINT: 'CHECKPOINT', wire.REGISTER: 'REGISTER',
}


def _msg_name(msg_type):
    return _MSG_NAMES.get(msg_type, 'MSG%d' % msg_type)


class PSClient(object):
    """One trainer's (self-healing) connection to one pserver endpoint."""

    def __init__(self, endpoint, trainer_id=0, timeout=None,
                 connect_retry_secs=60.0, retry_policy=None,
                 incarnation=None):
        self.endpoint = endpoint
        self.trainer_id = trainer_id
        if timeout is None:
            # read deadline (FLAGS_rpc_read_deadline): create_connection
            # leaves its timeout set on the socket, so every recv also
            # times out — a peer that accepts but never replies (a wedged
            # pserver) surfaces as socket.timeout, which _call_locked
            # already treats as a retryable connection failure, instead
            # of hanging the trainer forever
            from ..flags import get_flag
            timeout = float(get_flag('rpc_read_deadline', 120.0))
        self.timeout = timeout
        host, port = endpoint.rsplit(':', 1)
        self._addr = (host, int(port))
        self._retry = retry_policy or RetryPolicy.from_flags()
        # incarnation nonce: a RESTARTED trainer process re-using this
        # trainer_id must not collide with seqs the server already saw
        self._incarnation = binascii.hexlify(os.urandom(6)).decode()
        # LOGICAL incarnation: the supervisor bumps
        # FLAGS_trainer_incarnation on every restart; the pserver fences
        # lower values (zombie) and rejoins higher ones (see
        # param_service._fence_locked)
        if incarnation is None:
            from ..flags import get_flag
            incarnation = int(get_flag('trainer_incarnation', 0))
        self.incarnation = int(incarnation)
        # this trainer's step index, tagged onto SEND_VAR/BATCH_BARRIER
        # so a pserver that already closed the round ack-ignores a
        # resumed trainer's replay of it
        self._round = 0
        self._seq = 0
        self._sock = None
        self._lock = threading.Lock()
        # trainers routinely start before their pservers finish binding
        # (reference GRPC clients block on channel readiness) — retry
        self._connect(connect_retry_secs)

    # -- connection lifecycle ---------------------------------------------
    def _connect(self, retry_secs):
        deadline = time.monotonic() + retry_secs
        while True:
            try:
                sock = socket.create_connection(self._addr,
                                                timeout=self.timeout)
                break
            except (ConnectionRefusedError, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def _drop_socket(self):
        """Close a (possibly half-framed) socket; the next attempt
        reconnects fresh. Never reuse a connection whose framing state
        is unknown."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _invalidate(self):
        """Connection is beyond saving: close it AND evict this client
        from the module pool so no later get_client() hands out a
        poisoned instance."""
        self._drop_socket()
        _evict_client(self)

    # -- request path ------------------------------------------------------
    def _call(self, msg_type, meta=None, value=None):
        meta = dict(meta or {})
        meta['trainer_id'] = self.trainer_id
        with self._lock:
            self._seq += 1
            meta['seq'] = self._seq
            meta['cli'] = self._incarnation
            meta['inc'] = self.incarnation
            # one client span per LOGICAL call (the span covers every
            # retry); its id rides the optional meta 'trace' field so
            # the server's handler span shares it — absent field means
            # untraced, no wire-version bump
            _CALLS.inc()
            t0 = time.monotonic()
            with _trace.span('rpc.%s' % _msg_name(msg_type),
                             kind='client', endpoint=self.endpoint,
                             seq=self._seq) as sp:
                tr = _trace.wire_trace(sp)
                if tr is not None:
                    meta['trace'] = tr
                out = self._call_locked(msg_type, meta, value)
            _CALL_LATENCY.observe(time.monotonic() - t0)
            return out

    def _call_locked(self, msg_type, meta, value):
        last_err = None
        first = True
        for delay in self._retry.schedule():
            if not first:
                _RETRIES.inc()
            first = False
            if delay:
                time.sleep(delay)
            try:
                if self._sock is None:
                    _RECONNECTS.inc()
                    self._connect(self._retry.reconnect_secs)
                wire.write_msg(self._sock, msg_type, meta, value)
                rtype, rmeta, rvalue = wire.read_msg(self._sock)
            except FatalRPCError:
                self._invalidate()
                raise
            except (ConnectionError, OSError) as e:
                # transport failure mid-frame (socket.timeout included):
                # the socket may hold misframed garbage — drop it and
                # replay this request (same seq) on a fresh connection
                if isinstance(e, socket.timeout):
                    _DEADLINE_TIMEOUTS.inc()
                last_err = e
                self._drop_socket()
                continue
            if rtype == wire.REPLY_ERR:
                err = 'pserver %s: %s' % (self.endpoint,
                                          rmeta.get('error'))
                if rmeta.get('retryable'):
                    last_err = RetryableRPCError(err)
                    continue
                raise FatalRPCError(err)
            return rmeta, rvalue
        self._invalidate()
        raise RetryableRPCError(
            'pserver %s unreachable after %d attempts (%s: %s)'
            % (self.endpoint, self._retry.max_attempts,
               type(last_err).__name__, last_err)) from last_err

    def send_var(self, name, value):
        """Push a gradient (dense array or SelectedRows). A non-finite
        value fails fast HERE (retryable — the Trainer's step retry
        recomputes it) rather than spending a round trip on the
        pserver's rejection; the server-side guard still backstops
        corruption introduced downstream of this check."""
        from ..flags import get_flag
        if (get_flag('ps_check_grad_finite', True)
                and not wire.value_is_finite(value)):
            raise RetryableRPCError(
                'refusing to send non-finite gradient %r to %s '
                '(FLAGS_ps_check_grad_finite)' % (name, self.endpoint))
        self._call(wire.SEND_VAR, {'name': name, 'round': self._round},
                   value)

    def get_var(self, name):
        """Pull a parameter value."""
        _, value = self._call(wire.GET_VAR, {'name': name})
        return value

    def prefetch(self, table_name, ids):
        """Distributed lookup table: local row ids -> embedding rows."""
        import numpy as np
        _, rows = self._call(wire.PREFETCH, {'name': table_name},
                             np.asarray(ids, dtype='int32'))
        return rows

    def batch_barrier(self):
        self._call(wire.BATCH_BARRIER, {'round': self._round})
        self._round += 1

    def register(self):
        """(Re)join handshake: announce this incarnation and learn the
        shard's round state. -> {'round', 'expected', 'rejoined'}; a
        restarted trainer resumes at min('expected') across shards and
        set_round()s each client there (elastic recovery)."""
        rmeta, _ = self._call(wire.REGISTER)
        return rmeta

    def set_round(self, round_idx):
        """Pin the step index tagged onto subsequent sends — the resume
        point a restarted trainer computed from register() replies."""
        self._round = int(round_idx)

    def fetch_barrier(self):
        self._call(wire.FETCH_BARRIER)

    def checkpoint_notify(self, dirname):
        """Ask the pserver to save its parameter shard (reference
        checkpoint_notify_op.cc -> RequestCheckpointHandler)."""
        self._call(wire.CHECKPOINT, {'dirname': dirname})

    def complete(self):
        self._call(wire.COMPLETE)

    def close(self):
        self._drop_socket()


# module-level client pool: one PSClient per (endpoint, trainer_id) for
# this process (the analog of GRPCClient's channel cache);
# Executor.close() drains it.
_clients = {}
_clients_lock = threading.Lock()


def get_client(endpoint, trainer_id=0):
    key = (endpoint, trainer_id)
    with _clients_lock:
        c = _clients.get(key)
        if c is None:
            c = _clients[key] = PSClient(endpoint, trainer_id)
        return c


def _evict_client(client):
    """Drop a poisoned client from the pool (called by the client itself
    while holding its own lock — take only the pool lock here)."""
    with _clients_lock:
        for key, c in list(_clients.items()):
            if c is client:
                del _clients[key]


def close_all_clients(send_complete=True):
    """Notify every connected pserver this trainer is done and drop the
    connections (reference Executor::Close -> SendComplete)."""
    with _clients_lock:
        clients = list(_clients.values())
        _clients.clear()
    # complete() takes each client's own lock and may evict from the
    # pool — keep the pool lock released to avoid lock-order inversion
    for c in clients:
        if send_complete:
            try:
                c.complete()
            except (RuntimeError, OSError, ConnectionError):
                pass
        c.close()


class PSServer(object):
    """Threaded TCP server dispatching wire messages into a service.

    service interface (see param_service.ParameterService); `seq` is an
    opaque replay-dedup token threaded from the request meta, `inc` the
    trainer's logical incarnation (fencing), `round_idx` the trainer's
    step index (resume idempotency):
      on_send_var(name, trainer_id, value, seq=None, inc=None,
                  round_idx=None)
      on_get_var(name, trainer_id, inc=None) -> value
      on_prefetch(name, trainer_id, ids, inc=None) -> rows
      on_batch_barrier(trainer_id, seq=None, inc=None, round_idx=None)
      on_fetch_barrier(trainer_id, inc=None)
      on_checkpoint(dirname, trainer_id, seq=None, inc=None)
      on_register(trainer_id, inc=None, seq=None) -> reply meta dict
      on_complete(trainer_id, inc=None) -> True when ALL completed

    A restarted pserver re-binding its endpoint may race the dying
    process's listener (or its TIME_WAIT): bind retries for
    `bind_retry_secs` so supervisor restarts resume on the SAME
    endpoint the trainers' retry layer is already reconnecting to.
    """

    def __init__(self, endpoint, service, bind_retry_secs=30.0):
        host, port = endpoint.rsplit(':', 1)
        self.service = service
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        deadline = time.monotonic() + bind_retry_secs
        while True:
            try:
                self._lsock.bind((host, int(port)))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._done = threading.Event()
        self._threads = []

    def serve_forever(self):
        """Accept + dispatch until the service reports all trainers
        complete (the RunSyncLoop exit condition, listen_and_serv_op.cc:
        exit_flag on COMPLETE messages). A reaper thread sweeps trainer
        liveness (service.check_liveness) so a silently-dead trainer is
        retired after its rpc_deadline and the server still exits —
        the round-4 no-silent-deadlock guarantee."""
        accept_t = threading.Thread(target=self._accept_loop, daemon=True)
        accept_t.start()
        if hasattr(self.service, 'check_liveness'):
            reaper = threading.Thread(target=self._reap_loop, daemon=True)
            reaper.start()
        self._done.wait()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def _reap_loop(self):
        warned = False
        while not self._done.is_set():
            try:
                if self.service.check_liveness():
                    self.shutdown()
                    return
            except Exception as e:
                if not warned:   # a broken sweep must not fail silently
                    import sys
                    print('pserver liveness sweep failed: %r' % e,
                          file=sys.stderr)
                    warned = True
            self._done.wait(timeout=1.0)

    def shutdown(self):
        self._done.set()

    def _accept_loop(self):
        while not self._done.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        svc = self.service
        try:
            while True:
                msg_type, meta, value = wire.read_msg(conn)
                tid = int(meta.get('trainer_id', 0))
                name = meta.get('name')
                # replay-dedup token: (incarnation, seq) — None for
                # legacy clients that don't number their requests
                seq = meta.get('seq')
                key = (meta.get('cli'), seq) if seq is not None else None
                inc = meta.get('inc')
                round_idx = meta.get('round')
                try:
                    # handler span shares the CLIENT's span id (meta
                    # 'trace', when present and tracing is on here):
                    # the cross-process link obs/report.py draws flow
                    # events and clock-offset estimates from
                    with _trace.server_span(_msg_name(msg_type),
                                            meta.get('trace'),
                                            trainer_id=tid):
                        self._dispatch(conn, svc, msg_type, meta, value,
                                       tid, name, key, inc, round_idx)
                except (ConnectionError, OSError):
                    return   # peer vanished mid-dispatch
                except Exception as e:   # surface server-side op errors
                    # classification crosses the wire: transient errors
                    # invite a replay, everything else is fatal
                    wire.write_msg(conn, wire.REPLY_ERR,
                                   {'error': str(e),
                                    'retryable': isinstance(
                                        e, RetryableRPCError)})
        except (ConnectionError, OSError):
            return   # read failed / reply write failed: connection dead
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, svc, msg_type, meta, value, tid, name,
                  key, inc, round_idx):
        if msg_type == wire.SEND_VAR:
            svc.on_send_var(name, tid, value, seq=key,
                            inc=inc, round_idx=round_idx)
            wire.write_msg(conn, wire.REPLY_OK)
        elif msg_type == wire.GET_VAR:
            out = svc.on_get_var(name, tid, inc=inc)
            wire.write_msg(conn, wire.REPLY_VAR, value=out)
        elif msg_type == wire.PREFETCH:
            out = svc.on_prefetch(name, tid, value, inc=inc)
            wire.write_msg(conn, wire.REPLY_VAR, value=out)
        elif msg_type == wire.BATCH_BARRIER:
            svc.on_batch_barrier(tid, seq=key, inc=inc,
                                 round_idx=round_idx)
            wire.write_msg(conn, wire.REPLY_OK)
        elif msg_type == wire.FETCH_BARRIER:
            svc.on_fetch_barrier(tid, inc=inc)
            wire.write_msg(conn, wire.REPLY_OK)
        elif msg_type == wire.CHECKPOINT:
            svc.on_checkpoint(meta.get('dirname'), tid,
                              seq=key, inc=inc)
            wire.write_msg(conn, wire.REPLY_OK)
        elif msg_type == wire.REGISTER:
            out = svc.on_register(tid, inc=inc, seq=key)
            wire.write_msg(conn, wire.REPLY_OK, out)
        elif msg_type == wire.COMPLETE:
            all_done = svc.on_complete(tid, inc=inc)
            wire.write_msg(conn, wire.REPLY_OK)
            if all_done:
                self.shutdown()
        else:
            wire.write_msg(conn, wire.REPLY_ERR,
                           {'error': 'bad msg type %d'
                            % msg_type, 'retryable': False})
