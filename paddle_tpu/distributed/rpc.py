"""TCP RPC client/server for parameter-server mode.

The capability analog of the reference's GRPCClient (operators/distributed/
grpc_client.h:175: AsyncSendVar/AsyncGetVar/AsyncPrefetchVar/
AsyncSendBatchBarrier/AsyncSendFetchBarrier/AsyncSendComplete) and
AsyncGRPCServer (grpc_server.h:46), re-based on plain sockets + the binary
wire format in wire.py. Each trainer holds one persistent connection per
pserver; the server runs one thread per connection and dispatches into a
service object (param_service.ParameterService) — the threading shape of
the reference's RunSyncLoop server.

Resilience (see distributed/resilience.py): a PSClient survives a dropped
connection mid-training. Every request carries a `seq` number plus a
per-client incarnation nonce; on any transport failure the client closes
the poisoned socket, reconnects under the shared RetryPolicy
(exponential backoff + jitter), and REPLAYS the request with the SAME
seq. The ParameterService keeps a per-trainer dedup window, so a replay
of an already-applied mutation (SEND_VAR / BATCH_BARRIER / CHECKPOINT)
is acknowledged without being applied twice — a retried gradient never
double-counts in a sync round. REPLY_ERR metas carry `retryable`:
transient server rejections re-enter the retry loop, fatal ones raise
FatalRPCError (the reference GRPCClient's channel-retry/backoff model
plus at-most-once semantics that gRPC got from request ids).

Pipelining (the reference's AsyncSendVar/AsyncGetVar completion-queue
model): `send_var_async`/`get_var_async`/`prefetch_async` and the
barrier/checkpoint `_async` variants return concurrent.futures.Futures.
The caller's thread streams request frames onto the connection while a
per-client reader thread matches replies back by the `seq` the server
echoes in every reply meta (an additive optional field, like `trace`) —
up to FLAGS_rpc_inflight_window requests ride one connection, so N
small pushes cost ~1 RTT instead of N. On ANY transport failure the
reader rebuilds the connection and replays every unacked request in seq
order; the server's (cli, seq) dedup window makes that at-most-once
exactly as it does for sync retries. Small dense gradients bound for
the same endpoint coalesce into one SEND_VARS frame (FLAGS_rpc_batch_*)
whose per-var entries each keep their own dedup token. The engine
starts lazily on the first *_async call; until then (and for clients
used purely synchronously) the original blocking path runs unchanged.
Submissions are expected from one thread at a time per client (the
host-op emitter thread) — the engine serializes writes internally, but
interleaving sync calls from OTHER threads while async requests are in
flight is not supported.
"""
from __future__ import annotations

import binascii
import os
import socket
import threading
import time
from concurrent import futures as _futures

from . import wire
from .resilience import FatalRPCError, RetryableRPCError, RetryPolicy
from ..obs import telemetry as _tm
from ..obs import trace as _trace

__all__ = ['PSClient', 'PSServer', 'get_client', 'close_all_clients',
           'get_serving_client', 'SERVING_TID_BASE',
           'RetryableRPCError', 'FatalRPCError']

# client-side RPC health: every logical call, every replay of one
# (retries), every fresh connection made to replace a dropped socket
# (reconnects), and read-deadline expiries specifically — the silent
# peer case (FLAGS_rpc_read_deadline)
_CALLS = _tm.counter('rpc.client.calls')
_RETRIES = _tm.counter('rpc.client.retries')
_RECONNECTS = _tm.counter('rpc.client.reconnects')
_DEADLINE_TIMEOUTS = _tm.counter('rpc.client.read_deadline_timeouts')
_CALL_LATENCY = _tm.histogram('rpc.client.call_latency')
# pipelined-engine health: how many requests are riding the connection
# unacked right now, and how many vars each SEND_VARS frame coalesced
_INFLIGHT = _tm.gauge('rpc.client.inflight')
_BATCH_VARS = _tm.histogram('rpc.client.batch_vars')

_MSG_NAMES = {
    wire.SEND_VAR: 'SEND_VAR', wire.GET_VAR: 'GET_VAR',
    wire.SEND_VARS: 'SEND_VARS', wire.GET_VARS: 'GET_VARS',
    wire.GET_VERSION: 'GET_VERSION',
    wire.PREFETCH: 'PREFETCH', wire.BATCH_BARRIER: 'BATCH_BARRIER',
    wire.FETCH_BARRIER: 'FETCH_BARRIER', wire.COMPLETE: 'COMPLETE',
    wire.CHECKPOINT: 'CHECKPOINT', wire.REGISTER: 'REGISTER',
}

# serving-side trainer-id range: a ParamSubscriber co-located with a
# trainer process must never share the server's per-tid (cli, seq)
# dedup/replay windows, liveness clocks, or round state with the real
# trainer 0..num_trainers-1 — tids at or above this base are READ-ONLY
# peers the ParameterService treats as inert (no liveness retirement,
# no round waits, COMPLETE ignored).
SERVING_TID_BASE = 1 << 16


def _msg_name(msg_type):
    return _MSG_NAMES.get(msg_type, 'MSG%d' % msg_type)


class _Pending(object):
    """One in-flight pipelined request: the wire meta frozen at submit
    time (a replay reuses the SAME seq/round — the server's dedup
    contract), the future its caller waits on, and the connection
    generation it was last written on (-1: on no socket yet; recovery
    or a rewrite puts it back on the wire)."""
    __slots__ = ('seq', 'msg_type', 'meta', 'value', 'items', 'future',
                 'gen', 'attempts', 'sid', 't0', 'tm0')

    def __init__(self, seq, msg_type, meta, value, items, sid):
        self.seq = seq
        self.msg_type = msg_type
        self.meta = meta
        self.value = value
        self.items = items       # SEND_VARS: [(entry_meta, value), ...]
        self.future = _futures.Future()
        self.gen = -1
        self.attempts = 0        # REPLY_ERR-retryable resubmissions
        self.sid = sid           # trace span id (None: untraced)
        self.t0 = time.time()    # span clock
        self.tm0 = time.monotonic()   # latency clock


def _chain(fut, fn):
    """A future resolving to fn(parent.result()) — runs on the reader
    thread the moment the reply lands."""
    out = _futures.Future()

    def _done(f):
        try:
            out.set_result(fn(f.result()))
        except BaseException as e:
            out.set_exception(e)
    fut.add_done_callback(_done)
    return out


class PSClient(object):
    """One trainer's (self-healing) connection to one pserver endpoint."""

    def __init__(self, endpoint, trainer_id=0, timeout=None,
                 connect_retry_secs=60.0, retry_policy=None,
                 incarnation=None):
        self.endpoint = endpoint
        self.trainer_id = trainer_id
        if timeout is None:
            # read deadline (FLAGS_rpc_read_deadline): create_connection
            # leaves its timeout set on the socket, so every recv also
            # times out — a peer that accepts but never replies (a wedged
            # pserver) surfaces as socket.timeout, which _call_locked
            # already treats as a retryable connection failure, instead
            # of hanging the trainer forever
            from ..flags import get_flag
            timeout = float(get_flag('rpc_read_deadline', 120.0))
        self.timeout = timeout
        host, port = endpoint.rsplit(':', 1)
        self._addr = (host, int(port))
        self._retry = retry_policy or RetryPolicy.from_flags()
        # incarnation nonce: a RESTARTED trainer process re-using this
        # trainer_id must not collide with seqs the server already saw
        self._incarnation = binascii.hexlify(os.urandom(6)).decode()
        # LOGICAL incarnation: the supervisor bumps
        # FLAGS_trainer_incarnation on every restart; the pserver fences
        # lower values (zombie) and rejoins higher ones (see
        # param_service._fence_locked)
        if incarnation is None:
            from ..flags import get_flag
            incarnation = int(get_flag('trainer_incarnation', 0))
        self.incarnation = int(incarnation)
        # this trainer's step index, tagged onto SEND_VAR/BATCH_BARRIER
        # so a pserver that already closed the round ack-ignores a
        # resumed trainer's replay of it
        self._round = 0
        self._seq = 0
        self._sock = None
        self._lock = threading.Lock()
        # pipelined engine (started lazily by the first *_async call).
        # Lock order where both are held: _wlock (write serialization)
        # OUTSIDE _mu (seq/inflight/socket state). The reader thread is
        # the only place sockets are closed while the engine runs;
        # writers that hit a dead socket shutdown() it (waking the
        # reader blocked in recv) and leave recovery to the reader.
        self._mu = threading.Condition(threading.Lock())
        self._wlock = threading.Lock()
        self._inflight = {}      # seq -> _Pending
        self._gen = 0            # connection generation
        self._reader = None
        self._closed = False
        self._reconnect_tries = 0
        self._window_sem = None
        # trainers routinely start before their pservers finish binding
        # (reference GRPC clients block on channel readiness) — retry
        self._connect(connect_retry_secs)

    # -- connection lifecycle ---------------------------------------------
    def _connect(self, retry_secs):
        deadline = time.monotonic() + retry_secs
        while True:
            try:
                sock = socket.create_connection(self._addr,
                                                timeout=self.timeout)
                break
            except (ConnectionRefusedError, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def _drop_socket(self):
        """Close a (possibly half-framed) socket; the next attempt
        reconnects fresh. Never reuse a connection whose framing state
        is unknown."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _invalidate(self):
        """Connection is beyond saving: close it AND evict this client
        from the module pool so no later get_client() hands out a
        poisoned instance."""
        self._drop_socket()
        _evict_client(self)

    # -- request path ------------------------------------------------------
    def _call(self, msg_type, meta=None, value=None):
        if self._reader is not None:
            # the pipelined engine owns the socket once started: the
            # reader thread is the sole reply consumer, so sync calls
            # become submit-and-wait (same blocking semantics, same
            # exceptions — fut.result() re-raises)
            return self._submit(msg_type, dict(meta or {}), value).result()
        meta = dict(meta or {})
        meta['trainer_id'] = self.trainer_id
        with self._lock:
            self._seq += 1
            meta['seq'] = self._seq
            meta['cli'] = self._incarnation
            meta['inc'] = self.incarnation
            # one client span per LOGICAL call (the span covers every
            # retry); its id rides the optional meta 'trace' field so
            # the server's handler span shares it — absent field means
            # untraced, no wire-version bump
            _CALLS.inc()
            t0 = time.monotonic()
            with _trace.span('rpc.%s' % _msg_name(msg_type),
                             kind='client', endpoint=self.endpoint,
                             seq=self._seq) as sp:
                tr = _trace.wire_trace(sp)
                if tr is not None:
                    meta['trace'] = tr
                out = self._call_locked(msg_type, meta, value)
            _CALL_LATENCY.observe(time.monotonic() - t0)
            return out

    def _call_locked(self, msg_type, meta, value):
        last_err = None
        first = True
        for delay in self._retry.schedule():
            if not first:
                _RETRIES.inc()
            first = False
            if delay:
                time.sleep(delay)
            try:
                if self._sock is None:
                    _RECONNECTS.inc()
                    self._connect(self._retry.reconnect_secs)
                wire.write_msg(self._sock, msg_type, meta, value)
                rtype, rmeta, rvalue = wire.read_msg(self._sock)
                rseq = rmeta.get('seq')
                if rseq is not None and rseq != meta['seq']:
                    # stream-desync detector: the reply belongs to a
                    # DIFFERENT request, so framing alignment on this
                    # connection cannot be trusted. FrameCorruptError
                    # is a ConnectionError — caught below, socket
                    # dropped, request replayed on a fresh connection.
                    raise wire.FrameCorruptError(
                        'pserver %s echoed seq %s for request seq %s — '
                        'desynced reply stream'
                        % (self.endpoint, rseq, meta['seq']))
            except FatalRPCError:
                self._invalidate()
                raise
            except (ConnectionError, OSError) as e:
                # transport failure mid-frame (socket.timeout included):
                # the socket may hold misframed garbage — drop it and
                # replay this request (same seq) on a fresh connection
                if isinstance(e, socket.timeout):
                    _DEADLINE_TIMEOUTS.inc()
                last_err = e
                self._drop_socket()
                continue
            if rtype == wire.REPLY_ERR:
                err = 'pserver %s: %s' % (self.endpoint,
                                          rmeta.get('error'))
                if rmeta.get('retryable'):
                    last_err = RetryableRPCError(err)
                    continue
                raise FatalRPCError(err)
            return rmeta, rvalue
        self._invalidate()
        raise RetryableRPCError(
            'pserver %s unreachable after %d attempts (%s: %s)'
            % (self.endpoint, self._retry.max_attempts,
               type(last_err).__name__, last_err)) from last_err

    # -- pipelined engine --------------------------------------------------
    def _ensure_engine(self):
        """Start the reader thread + in-flight window on the first
        async call (idempotent; serialized against in-progress sync
        calls by self._lock, so the engine never steals a reply a sync
        caller is blocked on)."""
        if self._reader is not None:
            return
        with self._lock:
            if self._reader is not None:
                return
            from ..flags import get_flag
            window = max(1, int(get_flag('rpc_inflight_window', 32)))
            self._window_sem = threading.BoundedSemaphore(window)
            t = threading.Thread(
                target=self._read_loop, daemon=True,
                name='psclient-reader-%s' % self.endpoint)
            self._reader = t
            t.start()

    def _submit(self, msg_type, meta, value=None, pairs=None):
        """Register a request in the in-flight window and stream its
        frame onto the connection; returns the future the reader thread
        resolves when the matching (seq-echoed) reply arrives. Blocks
        only when the window is full. A write failure here does NOT
        fail the request: the pending stays registered and the reader's
        recovery replays it on a fresh connection."""
        self._ensure_engine()
        self._window_sem.acquire()
        p = None
        try:
            with self._wlock:
                with self._mu:
                    items = None
                    if pairs is not None:
                        # one seq per CONTAINED var (its dedup token)
                        # plus one frame seq below (reply matching)
                        items = []
                        for name, v in pairs:
                            self._seq += 1
                            items.append(({'name': name,
                                           'seq': self._seq,
                                           'round': self._round}, v))
                    self._seq += 1
                    seq = self._seq
                    meta = dict(meta)
                    meta['trainer_id'] = self.trainer_id
                    meta['seq'] = seq
                    meta['cli'] = self._incarnation
                    meta['inc'] = self.incarnation
                    sid = _trace.new_id() if _trace.enabled() else None
                    if sid is not None:
                        meta['trace'] = {'sid': sid}
                    p = _Pending(seq, msg_type, meta, value, items, sid)
                    self._inflight[seq] = p
                    _CALLS.inc()
                    _INFLIGHT.set(len(self._inflight))
                    if items is not None:
                        _BATCH_VARS.observe(len(items))
                    sock = self._sock
                    gen = self._gen
                    self._mu.notify_all()   # wake the reader
                if sock is not None:
                    try:
                        self._write_pending(sock, p)
                        p.gen = gen
                    except FatalRPCError as e:
                        # injected fatal on THIS request, raised before
                        # any bytes hit the wire: fail it alone, the
                        # connection is unharmed
                        self._finish(p, err=e)
                    except (ConnectionError, OSError):
                        # poisoned socket: wake the reader (shutdown,
                        # NOT close — it may be blocked in recv on this
                        # fd) and leave the pending for its recovery
                        self._shutdown_sock(sock)
                # sock is None: reader is mid-recovery and will replay
                # this pending (gen == -1) along with the others
        except BaseException:
            if p is not None:
                self._finish(p, err=RetryableRPCError('submit failed'))
            else:
                self._window_sem.release()
            raise
        return p.future

    def _write_pending(self, sock, p):
        if p.items is not None:
            wire.write_vars_msg(sock, p.meta, p.items)
        else:
            wire.write_msg(sock, p.msg_type, p.meta, p.value)

    @staticmethod
    def _shutdown_sock(sock):
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _retire_locked(self, sock):
        """Close a dead engine socket; caller holds _wlock (so no
        writer is mid-sendall on the fd when it closes)."""
        with self._mu:
            if self._sock is sock:
                self._sock = None
                self._gen += 1
        try:
            sock.close()
        except OSError:
            pass

    def _read_loop(self):
        """Reader thread: the engine's sole reply consumer and sole
        recovery agent. Sleeps (no deadline churn) while nothing is in
        flight; recovers + replays whenever the connection dies."""
        while True:
            with self._mu:
                while not self._closed and not self._inflight:
                    self._mu.wait()
                if self._closed:
                    break
                sock = self._sock
            if sock is None:
                self._recover()
                continue
            try:
                rtype, rmeta, rvalue = wire.read_msg(sock)
            except (ConnectionError, OSError) as e:
                if isinstance(e, socket.timeout):
                    _DEADLINE_TIMEOUTS.inc()
                with self._wlock:
                    self._retire_locked(sock)
                continue
            self._on_reply(rtype, rmeta, rvalue)
        self._fail_all(RetryableRPCError(
            'client for %s closed with requests in flight'
            % self.endpoint))

    def _recover(self):
        """Rebuild the connection and replay EVERY unacked in-flight
        request in seq order — the server's per-var (cli, seq) dedup
        window turns the replay into at-most-once delivery. Gives up
        (failing all pendings) after the retry policy's attempt budget
        of consecutive recoveries with no successful reply."""
        with self._mu:
            if not self._inflight:
                return
            self._reconnect_tries += 1
            tries = self._reconnect_tries
        if tries > self._retry.max_attempts:
            self._fail_all(RetryableRPCError(
                'pserver %s unreachable after %d attempts — failing '
                'all in-flight requests'
                % (self.endpoint, self._retry.max_attempts)))
            with self._mu:
                self._reconnect_tries = 0
            return
        if tries > 1:
            time.sleep(min(
                self._retry.backoff
                * (self._retry.multiplier ** (tries - 2)),
                self._retry.max_backoff))
        _RECONNECTS.inc()
        try:
            sock = socket.create_connection(self._addr,
                                            timeout=self.timeout)
        except OSError:
            return   # next loop iteration backs off longer and retries
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with self._wlock:
            with self._mu:
                self._sock = sock
                self._gen += 1
                gen = self._gen
                pend = sorted(self._inflight.values(),
                              key=lambda q: q.seq)
            for q in pend:
                _RETRIES.inc()
                try:
                    self._write_pending(sock, q)
                    q.gen = gen
                except FatalRPCError as e:
                    self._finish(q, err=e)
                except (ConnectionError, OSError):
                    # died again mid-replay: retire and try once more
                    # on the next loop iteration (unwritten pendings
                    # kept gen == -1)
                    self._retire_locked(sock)
                    break

    def _on_reply(self, rtype, rmeta, rvalue):
        seq = rmeta.get('seq')
        replay = []
        with self._mu:
            self._reconnect_tries = 0
            if seq is not None:
                p = self._inflight.get(seq)
            else:
                # legacy peer that doesn't echo seq: the server answers
                # in request order on one connection, so the oldest
                # WRITTEN pending owns this reply
                written = [q for q in self._inflight.values()
                           if q.gen >= 0]
                p = min(written, key=lambda q: q.seq) if written else None
            if p is None:
                return   # stale duplicate ack for a replayed request
            # dropped-request inference: the server replies in arrival
            # order per connection, so a reply for seq S proves every
            # lower seq written on the SAME generation was consumed
            # without a reply (an injected recv-drop ate it) — replay
            # those now instead of waiting for the read deadline.
            # (Spurious inferences are possible when a rewrite put an
            # old seq back on the wire after newer ones; the server's
            # dedup makes the extra replay harmless.)
            for q in self._inflight.values():
                if q is not p and q.seq < p.seq and q.gen == p.gen \
                        and q.gen >= 0:
                    q.gen = -1
                    replay.append(q)
            replay.sort(key=lambda q: q.seq)
        if rtype == wire.REPLY_ERR:
            err = 'pserver %s: %s' % (self.endpoint, rmeta.get('error'))
            if rmeta.get('retryable'):
                p.attempts += 1
                if p.attempts >= self._retry.max_attempts:
                    self._finish(p, err=RetryableRPCError(err))
                else:
                    with self._mu:
                        p.gen = -1
                    replay.append(p)
            else:
                self._finish(p, err=FatalRPCError(err))
        else:
            self._finish(p, result=(rmeta, rvalue))
        for q in replay:
            _RETRIES.inc()
            self._rewrite(q)

    def _rewrite(self, q):
        """Put a still-pending request back on the wire (recv-drop
        inference or a retryable server rejection). Reader thread
        only."""
        with self._wlock:
            with self._mu:
                if q.seq not in self._inflight:
                    return
                sock = self._sock
                gen = self._gen
            if sock is None:
                return   # recovery in progress replays it anyway
            try:
                self._write_pending(sock, q)
                q.gen = gen
            except FatalRPCError as e:
                self._finish(q, err=e)
            except (ConnectionError, OSError):
                self._retire_locked(sock)

    def _finish(self, p, err=None, result=None):
        """Resolve one pending exactly once: pop it (the pop is the
        claim — a pending already failed by _fail_all is skipped),
        release its window slot, record latency + the client span, then
        wake the caller."""
        with self._mu:
            if self._inflight.pop(p.seq, None) is None:
                return
            _INFLIGHT.set(len(self._inflight))
        self._window_sem.release()
        _CALL_LATENCY.observe(time.monotonic() - p.tm0)
        if p.sid is not None:
            _trace.record_span('rpc.%s' % _msg_name(p.msg_type),
                               'client', p.sid, p.t0, time.time(),
                               endpoint=self.endpoint, seq=p.seq)
        if err is not None:
            p.future.set_exception(err)
        else:
            p.future.set_result(result)

    def _fail_all(self, err):
        """Fail every in-flight request (recovery budget exhausted or
        close with work outstanding) and retire the connection + this
        client's pool slot, mirroring the sync path's _invalidate."""
        with self._wlock:
            with self._mu:
                pend = sorted(self._inflight.values(),
                              key=lambda q: q.seq)
                self._inflight.clear()
                _INFLIGHT.set(0)
                sock, self._sock = self._sock, None
                if sock is not None:
                    self._gen += 1
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        for p in pend:
            self._window_sem.release()
            _CALL_LATENCY.observe(time.monotonic() - p.tm0)
            if p.sid is not None:
                _trace.record_span('rpc.%s' % _msg_name(p.msg_type),
                                   'client', p.sid, p.t0, time.time(),
                                   endpoint=self.endpoint, seq=p.seq,
                                   error=True)
            p.future.set_exception(err)
        if pend:
            _evict_client(self)

    # -- async API (the reference's AsyncSendVar/AsyncGetVar shape) --------
    def send_var_async(self, name, value):
        """Pipelined send_var: returns a future resolving to the reply
        meta (or raising the same taxonomy the sync path raises). The
        non-finite pre-check fires HERE at submit time, exactly like
        send_var."""
        from ..flags import get_flag
        if (get_flag('ps_check_grad_finite', True)
                and not wire.value_is_finite(value)):
            raise RetryableRPCError(
                'refusing to send non-finite gradient %r to %s '
                '(FLAGS_ps_check_grad_finite)' % (name, self.endpoint))
        return self._submit(wire.SEND_VAR,
                            {'name': name, 'round': self._round}, value)

    def send_vars_async(self, pairs):
        """Push many gradients to this endpoint; dense values at most
        FLAGS_rpc_batch_bytes big coalesce into SEND_VARS frames (one
        CRC + one JSON header + one reply for dozens of BN scales and
        biases), flushed at FLAGS_rpc_batch_max_bytes /
        FLAGS_rpc_batch_max_vars. Sparse or large values go as
        individual SEND_VARs, in order. Returns one future per frame."""
        import numpy as np
        from ..flags import get_flag
        from ..selected_rows import SelectedRows
        check = get_flag('ps_check_grad_finite', True)
        thresh = int(get_flag('rpc_batch_bytes', 65536))
        max_bytes = max(1, int(get_flag('rpc_batch_max_bytes', 1 << 20)))
        max_vars = max(2, int(get_flag('rpc_batch_max_vars', 64)))
        futs = []
        batch = []          # [(name, value), ...] accumulating
        nbytes = 0

        def flush():
            nonlocal nbytes
            if not batch:
                return
            if len(batch) == 1:
                name, v = batch[0]
                futs.append(self._submit(
                    wire.SEND_VAR,
                    {'name': name, 'round': self._round}, v))
            else:
                futs.append(self._submit(wire.SEND_VARS, {},
                                         pairs=list(batch)))
            del batch[:]
            nbytes = 0

        for name, value in pairs:
            if check and not wire.value_is_finite(value):
                raise RetryableRPCError(
                    'refusing to send non-finite gradient %r to %s '
                    '(FLAGS_ps_check_grad_finite)'
                    % (name, self.endpoint))
            nb = 0
            small = False
            if thresh > 0 and not isinstance(value, SelectedRows):
                nb = int(np.asarray(value).nbytes)
                small = nb <= thresh
            if not small:
                flush()
                futs.append(self._submit(
                    wire.SEND_VAR,
                    {'name': name, 'round': self._round}, value))
                continue
            if batch and (nbytes + nb > max_bytes
                          or len(batch) >= max_vars):
                flush()
            batch.append((name, value))
            nbytes += nb
        flush()
        return futs

    def get_var_async(self, name):
        """Pipelined get_var: future resolving to the parameter value."""
        return _chain(self._submit(wire.GET_VAR, {'name': name}),
                      lambda r: r[1])

    def get_vars_async(self, names):
        """Pipelined multi-param pull (online refresh): ONE GET_VARS
        frame for all of `names`, read atomically on the server. Future
        resolves to (version, entries, values) — entries carry the
        per-param digest stamped under the same lock hold as the read,
        values decode in entry order."""
        return _chain(self._submit(wire.GET_VARS,
                                   {'names': [str(n) for n in names]}),
                      lambda r: (int(r[0].get('version', 0)),
                                 r[0].get('vars', []), r[1]))

    def get_version_async(self, with_manifest=False):
        """Pipelined version poll: future resolving to {'version': int
        [, 'manifest': {name: crc32}]} for this shard."""
        def _strip(r):
            out = dict(r[0])
            out.pop('seq', None)
            return out
        meta = {'manifest': True} if with_manifest else {}
        return _chain(self._submit(wire.GET_VERSION, meta), _strip)

    def get_version(self, with_manifest=False):
        """This shard's current published param version (optionally
        with the per-param digest manifest)."""
        if self._reader is not None:
            return self.get_version_async(with_manifest).result()
        meta = {'manifest': True} if with_manifest else {}
        rmeta, _ = self._call(wire.GET_VERSION, meta)
        out = dict(rmeta)
        out.pop('seq', None)
        return out

    def get_vars(self, names):
        """Blocking multi-param pull — see get_vars_async."""
        if self._reader is not None:
            return self.get_vars_async(names).result()
        rmeta, values = self._call(
            wire.GET_VARS, {'names': [str(n) for n in names]})
        return (int(rmeta.get('version', 0)),
                rmeta.get('vars', []), values)

    def prefetch_async(self, table_name, ids):
        """Pipelined prefetch: future resolving to the embedding rows."""
        import numpy as np
        return _chain(self._submit(wire.PREFETCH, {'name': table_name},
                                   np.asarray(ids, dtype='int32')),
                      lambda r: r[1])

    def batch_barrier_async(self):
        fut = self._submit(wire.BATCH_BARRIER, {'round': self._round})
        # the round advances at SUBMIT time: the tagged index already
        # rode the meta, and a replay reuses that frozen meta
        self._round += 1
        return fut

    def fetch_barrier_async(self):
        return self._submit(wire.FETCH_BARRIER, {})

    def checkpoint_notify_async(self, dirname):
        return self._submit(wire.CHECKPOINT, {'dirname': dirname})

    def send_var(self, name, value):
        """Push a gradient (dense array or SelectedRows). A non-finite
        value fails fast HERE (retryable — the Trainer's step retry
        recomputes it) rather than spending a round trip on the
        pserver's rejection; the server-side guard still backstops
        corruption introduced downstream of this check."""
        from ..flags import get_flag
        if (get_flag('ps_check_grad_finite', True)
                and not wire.value_is_finite(value)):
            raise RetryableRPCError(
                'refusing to send non-finite gradient %r to %s '
                '(FLAGS_ps_check_grad_finite)' % (name, self.endpoint))
        self._call(wire.SEND_VAR, {'name': name, 'round': self._round},
                   value)

    def get_var(self, name):
        """Pull a parameter value."""
        _, value = self._call(wire.GET_VAR, {'name': name})
        return value

    def prefetch(self, table_name, ids):
        """Distributed lookup table: local row ids -> embedding rows."""
        import numpy as np
        _, rows = self._call(wire.PREFETCH, {'name': table_name},
                             np.asarray(ids, dtype='int32'))
        return rows

    def batch_barrier(self):
        self._call(wire.BATCH_BARRIER, {'round': self._round})
        self._round += 1

    def register(self):
        """(Re)join handshake: announce this incarnation and learn the
        shard's round state. -> {'round', 'expected', 'rejoined'}; a
        restarted trainer resumes at min('expected') across shards and
        set_round()s each client there (elastic recovery)."""
        rmeta, _ = self._call(wire.REGISTER)
        rmeta = dict(rmeta)
        rmeta.pop('seq', None)   # transport echo, not handshake state
        return rmeta

    def set_round(self, round_idx):
        """Pin the step index tagged onto subsequent sends — the resume
        point a restarted trainer computed from register() replies."""
        self._round = int(round_idx)

    def fetch_barrier(self):
        self._call(wire.FETCH_BARRIER)

    def checkpoint_notify(self, dirname):
        """Ask the pserver to save its parameter shard (reference
        checkpoint_notify_op.cc -> RequestCheckpointHandler)."""
        self._call(wire.CHECKPOINT, {'dirname': dirname})

    def complete(self):
        self._call(wire.COMPLETE)

    def close(self):
        r = self._reader
        if r is not None:
            with self._mu:
                self._closed = True
                sock = self._sock
                self._mu.notify_all()
            if sock is not None:
                self._shutdown_sock(sock)   # wake a reader blocked in recv
            r.join(timeout=5.0)
            self._reader = None
        self._drop_socket()


# module-level client pool: one PSClient per (endpoint, trainer_id) for
# this process (the analog of GRPCClient's channel cache);
# Executor.close() drains it.
_clients = {}
_clients_lock = threading.Lock()


def get_client(endpoint, trainer_id=0):
    key = (endpoint, trainer_id)
    with _clients_lock:
        c = _clients.get(key)
        if c is None:
            c = _clients[key] = PSClient(endpoint, trainer_id)
        return c


def get_serving_client(endpoint, subscriber_id=0):
    """A pooled PSClient in the serving tid range (SERVING_TID_BASE +
    subscriber_id): its (cli, seq) tokens, liveness clock and dedup
    window on the server are disjoint from every co-located trainer's
    client pool — a subscriber pull can never be mistaken for (or
    replay-collide with) trainer traffic."""
    return get_client(endpoint, SERVING_TID_BASE + int(subscriber_id))


def _evict_client(client):
    """Drop a poisoned client from the pool (called by the client itself
    while holding its own lock — take only the pool lock here)."""
    with _clients_lock:
        for key, c in list(_clients.items()):
            if c is client:
                del _clients[key]


def close_all_clients(send_complete=True):
    """Notify every connected pserver this trainer is done and drop the
    connections (reference Executor::Close -> SendComplete)."""
    with _clients_lock:
        clients = list(_clients.values())
        _clients.clear()
    # complete() takes each client's own lock and may evict from the
    # pool — keep the pool lock released to avoid lock-order inversion
    for c in clients:
        if send_complete:
            try:
                c.complete()
            except (RuntimeError, OSError, ConnectionError):
                pass
        c.close()


class PSServer(object):
    """Threaded TCP server dispatching wire messages into a service.

    service interface (see param_service.ParameterService); `seq` is an
    opaque replay-dedup token threaded from the request meta, `inc` the
    trainer's logical incarnation (fencing), `round_idx` the trainer's
    step index (resume idempotency):
      on_send_var(name, trainer_id, value, seq=None, inc=None,
                  round_idx=None)
      on_get_var(name, trainer_id, inc=None) -> value
      on_prefetch(name, trainer_id, ids, inc=None) -> rows
      on_batch_barrier(trainer_id, seq=None, inc=None, round_idx=None)
      on_fetch_barrier(trainer_id, inc=None)
      on_checkpoint(dirname, trainer_id, seq=None, inc=None)
      on_register(trainer_id, inc=None, seq=None) -> reply meta dict
      on_complete(trainer_id, inc=None) -> True when ALL completed
      on_get_vars(names, trainer_id, inc=None) -> (version, items)
      on_get_version(trainer_id, inc=None, with_manifest=False) -> meta

    A restarted pserver re-binding its endpoint may race the dying
    process's listener (or its TIME_WAIT): bind retries for
    `bind_retry_secs` so supervisor restarts resume on the SAME
    endpoint the trainers' retry layer is already reconnecting to.
    """

    def __init__(self, endpoint, service, bind_retry_secs=30.0):
        host, port = endpoint.rsplit(':', 1)
        self.service = service
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        deadline = time.monotonic() + bind_retry_secs
        while True:
            try:
                self._lsock.bind((host, int(port)))
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._done = threading.Event()
        self._threads = []

    def serve_forever(self):
        """Accept + dispatch until the service reports all trainers
        complete (the RunSyncLoop exit condition, listen_and_serv_op.cc:
        exit_flag on COMPLETE messages). A reaper thread sweeps trainer
        liveness (service.check_liveness) so a silently-dead trainer is
        retired after its rpc_deadline and the server still exits —
        the round-4 no-silent-deadlock guarantee."""
        accept_t = threading.Thread(target=self._accept_loop, daemon=True)
        accept_t.start()
        if hasattr(self.service, 'check_liveness'):
            reaper = threading.Thread(target=self._reap_loop, daemon=True)
            reaper.start()
        self._done.wait()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def _reap_loop(self):
        warned = False
        while not self._done.is_set():
            try:
                if self.service.check_liveness():
                    self.shutdown()
                    return
            except Exception as e:
                if not warned:   # a broken sweep must not fail silently
                    import sys
                    print('pserver liveness sweep failed: %r' % e,
                          file=sys.stderr)
                    warned = True
            self._done.wait(timeout=1.0)

    def shutdown(self):
        self._done.set()

    def _accept_loop(self):
        while not self._done.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        svc = self.service
        try:
            while True:
                msg_type, meta, value = wire.read_msg(conn)
                tid = int(meta.get('trainer_id', 0))
                name = meta.get('name')
                # replay-dedup token: (incarnation, seq) — None for
                # legacy clients that don't number their requests
                seq = meta.get('seq')
                key = (meta.get('cli'), seq) if seq is not None else None
                inc = meta.get('inc')
                round_idx = meta.get('round')
                # every reply echoes the request's seq (additive
                # optional meta field, like 'trace'): the pipelined
                # client matches replies to in-flight requests by it,
                # and the sync client uses it as a desync detector
                ack = {'seq': seq} if seq is not None else {}
                try:
                    # handler span shares the CLIENT's span id (meta
                    # 'trace', when present and tracing is on here):
                    # the cross-process link obs/report.py draws flow
                    # events and clock-offset estimates from
                    with _trace.server_span(_msg_name(msg_type),
                                            meta.get('trace'),
                                            trainer_id=tid):
                        self._dispatch(conn, svc, msg_type, meta, value,
                                       tid, name, key, inc, round_idx,
                                       ack)
                except (ConnectionError, OSError):
                    return   # peer vanished mid-dispatch
                except Exception as e:   # surface server-side op errors
                    # classification crosses the wire: transient errors
                    # invite a replay, everything else is fatal
                    err = dict(ack)
                    err.update({'error': str(e),
                                'retryable': isinstance(
                                    e, RetryableRPCError)})
                    wire.write_msg(conn, wire.REPLY_ERR, err)
        except (ConnectionError, OSError):
            return   # read failed / reply write failed: connection dead
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, svc, msg_type, meta, value, tid, name,
                  key, inc, round_idx, ack=None):
        ack = ack or {}
        if msg_type == wire.SEND_VAR:
            svc.on_send_var(name, tid, value, seq=key,
                            inc=inc, round_idx=round_idx)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.SEND_VARS:
            # one reply acks the whole batch; each contained var
            # carries its OWN (cli, seq) dedup token + round tag and is
            # applied/journaled exactly like an individual SEND_VAR
            svc.on_send_vars(tid, meta['vars'], value,
                             cli=meta.get('cli'), inc=inc)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.GET_VAR:
            out = svc.on_get_var(name, tid, inc=inc)
            wire.write_msg(conn, wire.REPLY_VAR, ack, value=out)
        elif msg_type == wire.PREFETCH:
            out = svc.on_prefetch(name, tid, value, inc=inc)
            wire.write_msg(conn, wire.REPLY_VAR, ack, value=out)
        elif msg_type == wire.BATCH_BARRIER:
            svc.on_batch_barrier(tid, seq=key, inc=inc,
                                 round_idx=round_idx)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.FETCH_BARRIER:
            svc.on_fetch_barrier(tid, inc=inc)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.CHECKPOINT:
            svc.on_checkpoint(meta.get('dirname'), tid,
                              seq=key, inc=inc)
            wire.write_msg(conn, wire.REPLY_OK, ack)
        elif msg_type == wire.REGISTER:
            out = svc.on_register(tid, inc=inc, seq=key)
            reply = dict(out or {})
            reply.update(ack)
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.GET_VARS:
            version, items = svc.on_get_vars(meta.get('names', ()),
                                             tid, inc=inc)
            entries, payload = wire.pack_vars_body(items)
            reply = dict(ack)
            reply['version'] = int(version)
            reply['vars'] = entries
            # one REPLY_VAR frame for the whole shard pull: the 'vars'
            # meta makes the client decode it as a value list, and a
            # chaos-plan 'corrupt' rule on REPLY_VAR hits exactly this
            # reply (the refresh-path fault surface)
            wire.write_msg(conn, wire.REPLY_VAR, reply, payload=payload)
        elif msg_type == wire.GET_VERSION:
            out = svc.on_get_version(
                tid, inc=inc, with_manifest=bool(meta.get('manifest')))
            reply = dict(out or {})
            reply.update(ack)
            wire.write_msg(conn, wire.REPLY_OK, reply)
        elif msg_type == wire.COMPLETE:
            all_done = svc.on_complete(tid, inc=inc)
            wire.write_msg(conn, wire.REPLY_OK, ack)
            if all_done:
                self.shutdown()
        else:
            err = dict(ack)
            err.update({'error': 'bad msg type %d' % msg_type,
                        'retryable': False})
            wire.write_msg(conn, wire.REPLY_ERR, err)
