"""Distributed runtime: parameter-server RPC layer + services.

Two distinct distributed modes exist in the framework, mirroring the
reference's split (SURVEY §2.11):

- **Collective mode** (`paddle_tpu.parallel`): XLA collectives over
  ICI/DCN via the JAX coordination service — dense data/model parallel
  training (the NCCL path analog).
- **Parameter-server mode** (this package): host-CPU parameter services
  over TCP, TPU trainers pushing gradients / pulling parameters — the
  sparse/CTR half (the gRPC `operators/distributed/` analog:
  rpc_client.h:30, grpc_server.h:46, listen_and_serv_op.cc:39).

The wire format ships SelectedRows natively (rows + values) so sparse
embedding gradients cost O(touched rows), not O(vocab) — the bandwidth
win that motivates the parameter-server design for CTR models.
"""
from .rpc import (PSClient, PSServer, get_client, close_all_clients,
                  RetryableRPCError, FatalRPCError)
from .resilience import FaultPlan, RetryPolicy, StaleIncarnationError
from .param_service import ParameterService
from .supervisor import Supervisor
from .env import ClusterEnv, cluster_from_env

__all__ = ['PSClient', 'PSServer', 'ParameterService', 'get_client',
           'close_all_clients', 'ClusterEnv', 'cluster_from_env',
           'RetryableRPCError', 'FatalRPCError',
           'StaleIncarnationError', 'FaultPlan', 'RetryPolicy',
           'Supervisor']
