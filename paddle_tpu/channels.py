"""CSP channels (reference fluid's Go-style concurrency ops:
channel_create/channel_send/channel_recv/channel_close + Select,
operators/concurrency/channel_util.cc).

Scope decision: in the reference these were Program ops so the C++
executor could run concurrent blocks (Go op). On TPU, intra-program
concurrency belongs to XLA's scheduler — there is nothing for a channel
op to coordinate INSIDE a compiled block. What survives is the
host-side capability: coordinating producer/consumer Python threads
around Executor.run calls (the same role the reader pipeline's blocking
queue plays, reader/pipeline.py). So channels here are host objects
with the reference's semantics: bounded or unbuffered rendezvous,
close-drains-then-raises, and a Select that commits to exactly one
ready case.
"""
from __future__ import annotations

import time
from collections import deque
import threading

__all__ = ['Channel', 'make_channel', 'ChannelClosed', 'Select']


class ChannelClosed(Exception):
    """Receive on a drained closed channel / send on a closed channel."""


class Channel(object):
    """capacity=0 gives Go-style unbuffered rendezvous (send blocks for
    a receiver); capacity>0 a bounded buffer.

    One Condition guards all state, so the Go contracts hold exactly:
    close() never blocks, a timed-out recv leaves no stale rendezvous
    ticket, and every sender blocked at close() wakes and raises."""

    def __init__(self, capacity=0):
        self._cap = int(capacity)
        self._buf = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._recv_waiting = 0     # receivers currently blocked in recv

    def _can_send(self):
        if self._cap > 0:
            return len(self._buf) < self._cap
        # rendezvous: an unmatched receiver is waiting (each buffered
        # item already has a claimant; both counters move under _cv)
        return self._recv_waiting > len(self._buf)

    def send(self, value, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed:
                    raise ChannelClosed('send on closed channel')
                if self._can_send():
                    self._buf.append(value)
                    self._cv.notify_all()
                    return
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError('send timed out')
                self._cv.wait(0.05)

    def try_send(self, value):
        """Non-blocking send: True if committed (Select's send case)."""
        with self._cv:
            if self._closed or not self._can_send():
                return False
            self._buf.append(value)
            self._cv.notify_all()
            return True

    def recv(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._recv_waiting += 1
            self._cv.notify_all()
            try:
                while True:
                    if self._buf:
                        value = self._buf.popleft()
                        self._cv.notify_all()
                        return value
                    if self._closed:
                        raise ChannelClosed(
                            'recv on closed empty channel')
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        raise TimeoutError('recv timed out')
                    self._cv.wait(0.05)
            finally:
                self._recv_waiting -= 1

    def poll(self):
        """Non-blocking receive: (True, value) or (False, None)."""
        with self._cv:
            if self._buf:
                value = self._buf.popleft()
                self._cv.notify_all()
                return True, value
            if self._closed:
                raise ChannelClosed('recv on closed empty channel')
            return False, None

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self):
        with self._cv:
            return self._closed

    def __iter__(self):
        """Drain until closed (Go's `for v := range ch`)."""
        while True:
            try:
                yield self.recv()
            except ChannelClosed:
                return


def make_channel(dtype=None, capacity=0):
    """(reference fluid.make_channel) dtype accepted for API parity;
    host channels are dynamically typed."""
    return Channel(capacity=capacity)


class Select(object):
    """Commit to exactly ONE ready case (reference Select op semantics).

    with Select() as sel:
        sel.case_recv(ch_a, on_a)        # on_a(value)
        sel.case_send(ch_b, v, on_b)     # on_b()
        sel.default(on_none)             # optional; else Select blocks
    """

    def __init__(self):
        self._cases = []
        self._default = None

    def __enter__(self):
        return self

    def case_recv(self, ch, handler):
        self._cases.append(('recv', ch, None, handler))

    def case_send(self, ch, value, handler):
        self._cases.append(('send', ch, value, handler))

    def default(self, handler):
        self._default = handler

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        while True:
            for kind, ch, value, handler in self._cases:
                if kind == 'recv':
                    ok, v = ch.poll()
                    if ok:
                        handler(v)
                        return False
                else:
                    if ch.try_send(value):
                        handler()
                        return False
            if self._default is not None:
                self._default()
                return False
            time.sleep(0.005)     # nothing ready: poll, don't spin hot
