"""imikolov / PTB n-gram LM data (reference python/paddle/dataset/
imikolov.py): build_dict() -> word->id; train(word_idx, n) yields n-gram
id tuples (the word2vec book config). Synthetic markov-ish id streams."""
from __future__ import annotations

from . import common

__all__ = ['build_dict', 'train', 'test', 'N']

N = 5
_VOCAB = 2074          # reference dict ~2074 after min_word_freq cutoff
_N_TRAIN, _N_TEST = 4096, 512


def build_dict(min_word_freq=50):
    d = {('w%04d' % i): i for i in range(_VOCAB - 2)}
    d['<s>'] = _VOCAB - 2
    d['<e>'] = _VOCAB - 1
    return d


def _creator(split, n_samples, word_idx, n):
    vocab = len(word_idx)

    def reader():
        rng = common.synthetic_rng('imikolov', split)
        for _ in range(n_samples):
            # strong sequential correlation (next id within +-3 of
            # previous): ~log(7) nats of conditional entropy, so n-gram
            # models show clear learning within one synthetic epoch
            ids = [int(rng.randint(0, vocab))]
            for _ in range(n - 1):
                step = int(rng.randint(-3, 4))
                ids.append(int((ids[-1] + step) % vocab))
            yield tuple(ids)
    return reader


def train(word_idx, n=N):
    return _creator('train', _N_TRAIN, word_idx, n)


def test(word_idx, n=N):
    return _creator('test', _N_TEST, word_idx, n)
