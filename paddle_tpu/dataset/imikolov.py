"""imikolov / PTB n-gram LM data (reference python/paddle/dataset/
imikolov.py): build_dict() -> word->id; train(word_idx, n) yields n-gram
id tuples (the word2vec book config). Synthetic markov-ish id streams."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['build_dict', 'train', 'test', 'N', 'convert']

N = 5
_VOCAB = 2074          # reference dict ~2074 after min_word_freq cutoff
_N_TRAIN, _N_TEST = 4096, 512


def build_dict(min_word_freq=50):
    d = {('w%04d' % i): i for i in range(_VOCAB - 2)}
    d['<s>'] = _VOCAB - 2
    d['<e>'] = _VOCAB - 1
    return d


def _creator(split, n_samples, word_idx, n):
    vocab = len(word_idx)

    def reader():
        rng = common.synthetic_rng('imikolov', split)
        # Zipfian marginal like real PTB text: unigram entropy well below
        # log(vocab), so an n-gram LM shows clear within-epoch learning by
        # fitting word frequencies alone (a uniform marginal has no such
        # signal and needs many epochs of per-word statistics), plus a
        # +-3 sequential walk for conditional signal.
        p = 1.0 / (np.arange(vocab) + 2.0)
        p /= p.sum()
        for _ in range(n_samples):
            ids = [int(rng.choice(vocab, p=p))]
            for _ in range(n - 1):
                step = int(rng.randint(-3, 4))
                ids.append(int((ids[-1] + step) % vocab))
            yield tuple(ids)
    return reader


def train(word_idx, n=N):
    return _creator('train', _N_TRAIN, word_idx, n)


def test(word_idx, n=N):
    return _creator('test', _N_TEST, word_idx, n)


def convert(path):
    """Write train/test (default dict) to RecordIO shards under `path`
    (reference imikolov.py:151)."""
    word_idx = build_dict()
    common.convert(path, train(word_idx), 1000, 'imikolov_train')
    common.convert(path, test(word_idx), 1000, 'imikolov_test')
