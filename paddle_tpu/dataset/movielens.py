"""MovieLens-1M (reference python/paddle/dataset/movielens.py): the
recommender book config. Samples: (user_id, gender_id, age_id, job_id,
movie_id, category_ids, title_ids, score). Synthetic with reference-shaped
vocab sizes."""
from __future__ import annotations

from . import common

__all__ = ['train', 'test', 'max_user_id', 'max_movie_id', 'max_job_id',
           'age_table', 'movie_categories', 'get_movie_title_dict']

_MAX_USER, _MAX_MOVIE, _MAX_JOB = 6040, 3952, 20
_N_CATEGORIES, _TITLE_VOCAB = 18, 1512
_N_TRAIN, _N_TEST = 4096, 512

age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _MAX_USER


def max_movie_id():
    return _MAX_MOVIE


def max_job_id():
    return _MAX_JOB


def movie_categories():
    return {('cat%02d' % i): i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {('t%04d' % i): i for i in range(_TITLE_VOCAB)}


def _creator(split, n):
    def reader():
        rng = common.synthetic_rng('movielens', split)
        for _ in range(n):
            user_id = int(rng.randint(1, _MAX_USER + 1))
            gender_id = int(rng.randint(0, 2))
            age_id = int(rng.randint(0, len(age_table)))
            job_id = int(rng.randint(0, _MAX_JOB + 1))
            movie_id = int(rng.randint(1, _MAX_MOVIE + 1))
            n_cat = int(rng.randint(1, 4))
            categories = rng.randint(0, _N_CATEGORIES, n_cat)
            n_title = int(rng.randint(1, 6))
            title = rng.randint(0, _TITLE_VOCAB, n_title)
            # score correlates with (user+movie) parity so models can learn
            base = 1.0 + 4.0 * (((user_id + movie_id) % 97) / 96.0)
            score = float(min(5.0, max(1.0, base + 0.3 * rng.randn())))
            yield (user_id, gender_id, age_id, job_id, movie_id,
                   categories.astype('int64').tolist(),
                   title.astype('int64').tolist(), score)
    return reader


def train():
    return _creator('train', _N_TRAIN)


def test():
    return _creator('test', _N_TEST)
