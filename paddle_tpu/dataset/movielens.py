"""MovieLens-1M (reference python/paddle/dataset/movielens.py): the
recommender book config. Samples: (user_id, gender_id, age_id, job_id,
movie_id, category_ids, title_ids, score). Synthetic with reference-shaped
vocab sizes."""
from __future__ import annotations

from . import common

__all__ = ['train', 'test', 'max_user_id', 'max_movie_id', 'max_job_id',
           'age_table', 'movie_categories', 'get_movie_title_dict', 'user_info', 'movie_info', 'convert']

_MAX_USER, _MAX_MOVIE, _MAX_JOB = 6040, 3952, 20
_N_CATEGORIES, _TITLE_VOCAB = 18, 1512
_N_TRAIN, _N_TEST = 4096, 512

age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    return _MAX_USER


def max_movie_id():
    return _MAX_MOVIE


def max_job_id():
    return _MAX_JOB


def movie_categories():
    return {('cat%02d' % i): i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {('t%04d' % i): i for i in range(_TITLE_VOCAB)}


def _creator(split, n):
    def reader():
        rng = common.synthetic_rng('movielens', split)
        for _ in range(n):
            user_id = int(rng.randint(1, _MAX_USER + 1))
            gender_id = int(rng.randint(0, 2))
            age_id = int(rng.randint(0, len(age_table)))
            job_id = int(rng.randint(0, _MAX_JOB + 1))
            movie_id = int(rng.randint(1, _MAX_MOVIE + 1))
            n_cat = int(rng.randint(1, 4))
            categories = rng.randint(0, _N_CATEGORIES, n_cat)
            n_title = int(rng.randint(1, 6))
            title = rng.randint(0, _TITLE_VOCAB, n_title)
            # score correlates with (user+movie) parity so models can learn
            base = 1.0 + 4.0 * (((user_id + movie_id) % 97) / 96.0)
            score = float(min(5.0, max(1.0, base + 0.3 * rng.randn())))
            yield (user_id, gender_id, age_id, job_id, movie_id,
                   categories.astype('int64').tolist(),
                   title.astype('int64').tolist(), score)
    return reader


def train():
    return _creator('train', _N_TRAIN)


def test():
    return _creator('test', _N_TEST)


class MovieInfo(object):
    """Movie catalog entry (reference movielens.py:36)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        cat_ids = movie_categories()
        title_ids = get_movie_title_dict()
        return [self.index,
                [cat_ids[c] for c in self.categories],
                [title_ids[w] for w in self.title.split()]]

    def __repr__(self):
        return '<MovieInfo id(%d), title(%s), categories(%s)>' % (
            self.index, self.title, self.categories)


class UserInfo(object):
    """User catalog entry (reference movielens.py:66)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return '<UserInfo id(%d), gender(%s), age(%d), job(%d)>' % (
            self.index, 'M' if self.is_male else 'F',
            age_table[self.age], self.job_id)


def movie_info():
    """id -> MovieInfo for the synthetic catalog (reference
    movielens.py:241). Deterministic across calls. Divergence from the
    reference: samples draw their category/title features from the
    per-split streams, not from this catalog, so joining samples to
    the catalog by movie_id gives independent features."""
    rng = common.synthetic_rng('movielens', 'catalog')
    cats = sorted(movie_categories())
    out = {}
    for mid in range(1, _MAX_MOVIE + 1):
        n_cat = int(rng.randint(1, 4))
        cat = [cats[int(c)]
               for c in rng.randint(0, _N_CATEGORIES, n_cat)]
        n_title = int(rng.randint(1, 6))
        title = ' '.join('t%04d' % t
                         for t in rng.randint(0, _TITLE_VOCAB, n_title))
        out[mid] = MovieInfo(mid, cat, title)
    return out


def user_info():
    """id -> UserInfo for the synthetic catalog (reference
    movielens.py:233)."""
    rng = common.synthetic_rng('movielens', 'users')
    out = {}
    for uid in range(1, _MAX_USER + 1):
        gender = 'M' if int(rng.randint(0, 2)) else 'F'
        age = age_table[int(rng.randint(0, len(age_table)))]
        job = int(rng.randint(0, _MAX_JOB + 1))
        out[uid] = UserInfo(uid, gender, age, job)
    return out


def convert(path):
    """Write train/test to RecordIO shards under `path`."""
    common.convert(path, train(), 1000, 'movielens_train')
    common.convert(path, test(), 1000, 'movielens_test')
