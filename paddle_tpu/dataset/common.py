"""Dataset plumbing (reference python/paddle/dataset/common.py): cache dir,
md5 checks, and the synthetic-data convention used by every module here."""
from __future__ import annotations

import hashlib
import os

import numpy as np

__all__ = ['DATA_HOME', 'md5file', 'synthetic_rng',
           'split', 'cluster_files_reader', 'convert']

DATA_HOME = os.path.expanduser('~/.cache/paddle_tpu/dataset')


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """No-egress environment: if the file was pre-placed under DATA_HOME it
    is used; otherwise callers fall back to synthetic data."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split('/')[-1])
    if os.path.exists(filename):
        return filename
    raise IOError(
        'no network egress: %s not cached under %s (synthetic data is '
        'served instead by the dataset module)' % (url, dirname))


def synthetic_rng(module_name, split):
    """Deterministic per-(dataset, split) generator."""
    seed = int(hashlib.md5(
        ('%s/%s' % (module_name, split)).encode()).hexdigest()[:8], 16)
    return np.random.RandomState(seed)


def split(reader, line_count, suffix='%05d.pickle', dumper=None):
    """Chunk a reader's samples into pickled files of `line_count`
    samples each (reference dataset/common.py:135 — modernized to
    binary mode; the reference's text-mode pickle was a py2 relic).
    `suffix` must contain a %d-style slot for the chunk index."""
    import pickle
    dumper = dumper if dumper is not None else pickle.dump
    if not callable(dumper):
        raise TypeError('dumper should be callable.')
    lines = []
    indx_f = 0

    def flush():
        nonlocal lines, indx_f
        with open(suffix % indx_f, 'wb') as f:
            dumper(lines, f)
        lines = []
        indx_f += 1

    for d in reader():
        lines.append(d)
        if len(lines) >= line_count:
            flush()
    if lines:
        flush()


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader over the chunk files written by split(), taking every
    trainer_count-th file starting at trainer_id (reference
    dataset/common.py:173 — the file-level sharding contract the
    cluster launcher relies on)."""
    import glob
    import pickle
    loader = loader if loader is not None else pickle.load

    def reader():
        if not callable(loader):
            raise TypeError('loader should be callable.')
        file_list = sorted(glob.glob(files_pattern))
        for idx, fn in enumerate(file_list):
            if idx % trainer_count == trainer_id:
                with open(fn, 'rb') as f:
                    for line in loader(f):
                        yield line

    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Write a reader's samples to RecordIO shard files of
    `line_count` pickled records each: `<output_path>/<prefix>-00000`…
    (reference dataset/common.py:214; every dataset module's
    convert(path) delegates here). Readable back with
    reader.creator.recordio(paths)."""
    import pickle
    from ..recordio import RecordIOWriter
    assert line_count >= 1
    must_mkdirs(output_path)
    indx_f = 0
    lines = []

    def write_chunk():
        nonlocal lines, indx_f
        filename = '%s/%s-%05d' % (output_path, name_prefix, indx_f)
        w = RecordIOWriter(filename)
        try:
            for l in lines:
                w.append_record(pickle.dumps(
                    l, protocol=pickle.HIGHEST_PROTOCOL))
        finally:
            w.close()
        lines = []
        indx_f += 1

    for d in reader():
        lines.append(d)
        if len(lines) >= line_count:
            write_chunk()
    if lines:
        write_chunk()
