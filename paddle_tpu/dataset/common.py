"""Dataset plumbing (reference python/paddle/dataset/common.py): cache dir,
md5 checks, and the synthetic-data convention used by every module here."""
from __future__ import annotations

import hashlib
import os

import numpy as np

__all__ = ['DATA_HOME', 'md5file', 'synthetic_rng']

DATA_HOME = os.path.expanduser('~/.cache/paddle_tpu/dataset')


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """No-egress environment: if the file was pre-placed under DATA_HOME it
    is used; otherwise callers fall back to synthetic data."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split('/')[-1])
    if os.path.exists(filename):
        return filename
    raise IOError(
        'no network egress: %s not cached under %s (synthetic data is '
        'served instead by the dataset module)' % (url, dirname))


def synthetic_rng(module_name, split):
    """Deterministic per-(dataset, split) generator."""
    seed = int(hashlib.md5(
        ('%s/%s' % (module_name, split)).encode()).hexdigest()[:8], 16)
    return np.random.RandomState(seed)
