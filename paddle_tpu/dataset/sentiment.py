"""Movie-review sentiment (reference python/paddle/dataset/sentiment.py).
Same sample format as imdb; kept as its own module for API parity."""
from __future__ import annotations

from . import imdb

from . import common

__all__ = ['get_word_dict', 'train', 'test', 'convert']


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb.train()


def test():
    return imdb.test()


def convert(path):
    """Write train/test to RecordIO shards under `path`."""
    common.convert(path, train(), 1000, 'sentiment_train')
    common.convert(path, test(), 1000, 'sentiment_test')
