"""Movie-review sentiment (reference python/paddle/dataset/sentiment.py).
Same sample format as imdb; kept as its own module for API parity."""
from __future__ import annotations

from . import imdb

__all__ = ['get_word_dict', 'train', 'test']


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb.train()


def test():
    return imdb.test()
