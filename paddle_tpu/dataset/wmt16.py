"""WMT-16 en<->de (reference python/paddle/dataset/wmt16.py). Same synthetic
scheme as wmt14 with the reference's (src, trg, trg_next) sample format."""
from __future__ import annotations

from . import common

__all__ = ['train', 'test', 'get_dict', 'validation', 'fetch', 'convert']


def get_dict(lang, dict_size, reverse=False):
    d = {('%s%05d' % (lang, i)): i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _creator(split, n_samples, src_dict_size, trg_dict_size):
    def reader():
        rng = common.synthetic_rng('wmt16', split)
        for _ in range(n_samples):
            slen = int(rng.randint(3, 12))
            src = rng.randint(3, src_dict_size, slen).astype('int64')
            trg = ((src[::-1] + 11) % trg_dict_size)
            trg = [max(3, int(t)) for t in trg]
            yield (src.tolist(), [0] + trg, trg + [1])
    return reader


def train(src_dict_size, trg_dict_size, src_lang='en'):
    return _creator('train', 2048, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang='en'):
    return _creator('test', 256, src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang='en'):
    """Validation split reader (reference wmt16.py:243)."""
    return _creator('val', 256, src_dict_size, trg_dict_size)


def fetch():
    """Prefetch hook (reference wmt16.py:320 downloads the tar). The
    synthetic corpus needs no fetch; kept so scripts calling
    dataset.wmt16.fetch() run unmodified."""
    return None


def convert(path, src_dict_size=30000, trg_dict_size=30000,
            src_lang='en'):
    """Write train/test/validation to RecordIO shards under `path`
    (reference wmt16.py:330)."""
    common.convert(path, train(src_dict_size, trg_dict_size, src_lang),
                   1000, 'wmt16_train')
    common.convert(path, test(src_dict_size, trg_dict_size, src_lang),
                   1000, 'wmt16_test')
    common.convert(path,
                   validation(src_dict_size, trg_dict_size, src_lang),
                   1000, 'wmt16_validation')
