"""CIFAR-10/100 (reference python/paddle/dataset/cifar.py): samples are
(3072 float32 in [0,1] laid out CHW, int label). Synthetic class-blob data."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train10', 'test10', 'train100', 'test100', 'convert']

_N_TRAIN, _N_TEST = 4096, 512


def _creator(nclass, split, n):
    rng_m = common.synthetic_rng('cifar%d' % nclass, 'means')
    means = rng_m.rand(nclass, 3072).astype('float32')

    def reader():
        rng = common.synthetic_rng('cifar%d' % nclass, split)
        for _ in range(n):
            label = int(rng.randint(0, nclass))
            img = means[label] + 0.2 * rng.randn(3072).astype('float32')
            yield np.clip(img, 0.0, 1.0).astype('float32'), label
    return reader


def train10():
    return _creator(10, 'train', _N_TRAIN)


def test10():
    return _creator(10, 'test', _N_TEST)


def train100():
    return _creator(100, 'train', _N_TRAIN)


def test100():
    return _creator(100, 'test', _N_TEST)


def convert(path):
    """Write the four CIFAR series to RecordIO shards under `path`
    (reference cifar.py:149)."""
    common.convert(path, train100(), 1000, 'cifar_train100')
    common.convert(path, test100(), 1000, 'cifar_test100')
    common.convert(path, train10(), 1000, 'cifar_train10')
    common.convert(path, test10(), 1000, 'cifar_test10')
