"""UCI housing (reference python/paddle/dataset/uci_housing.py): samples are
(13-float feature vector, 1-float price). Synthetic: features ~ N(0,1),
price = x @ w + noise with a fixed hidden w, so fit_a_line genuinely
converges like the real data."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train', 'test', 'feature_names']

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

_N_TRAIN, _N_TEST = 404, 102


def _hidden_w():
    rng = common.synthetic_rng('uci_housing', 'w')
    return rng.randn(13, 1).astype('float32')


def _make(split, n):
    rng = common.synthetic_rng('uci_housing', split)
    x = rng.randn(n, 13).astype('float32')
    w = _hidden_w()
    y = (x @ w + 0.1 * rng.randn(n, 1)).astype('float32')

    def reader():
        for i in range(n):
            yield x[i], y[i]
    return reader


def train():
    return _make('train', _N_TRAIN)


def test():
    return _make('test', _N_TEST)
