"""Flowers-102 (reference python/paddle/dataset/flowers.py): samples are
(3*224*224 float32 CHW, int label). Synthetic class-blob images at reduced
spatial detail (noise over per-class base colors)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train', 'test', 'valid']

_N_CLASS = 102
_N_TRAIN, _N_TEST = 1024, 256


def _creator(split, n, use_xmap=True):
    rng_m = common.synthetic_rng('flowers', 'means')
    base = rng_m.rand(_N_CLASS, 3).astype('float32')

    def reader():
        rng = common.synthetic_rng('flowers', split)
        for _ in range(n):
            label = int(rng.randint(0, _N_CLASS))
            img = np.repeat(base[label], 224 * 224).astype('float32')
            img += 0.1 * rng.randn(3 * 224 * 224).astype('float32')
            yield np.clip(img, 0, 1), label
    return reader


def train(use_xmap=True):
    return _creator('train', _N_TRAIN, use_xmap)


def test(use_xmap=True):
    return _creator('test', _N_TEST, use_xmap)


def valid(use_xmap=True):
    return _creator('valid', _N_TEST, use_xmap)
