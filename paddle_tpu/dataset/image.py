"""Image preprocessing utilities (reference python/paddle/dataset/
image.py — cv2-based there; numpy+PIL here, no OpenCV dependency).
All functions take/return HWC uint8-or-float numpy arrays except where
noted; `simple_transform` is the train/test pipeline the reference's
image models feed through. The heavy-throughput path for training is
the native decode stage (native/prefetcher.cc image_norm); these are
the host-side utility spellings scripts use.
"""
from __future__ import annotations

import io

import numpy as np

__all__ = [
    'load_image_bytes', 'load_image', 'resize_short', 'to_chw',
    'center_crop', 'random_crop', 'left_right_flip', 'simple_transform',
    'load_and_transform', 'batch_images_from_tar'
]


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:                      # pragma: no cover
        raise ImportError(
            'dataset.image needs Pillow for decode/resize; raw-array '
            'transforms (crop/flip/to_chw) work without it')


def load_image_bytes(data, is_color=True):
    """Decode an encoded image (jpeg/png/... bytes) to an HWC uint8
    array; grayscale HW when is_color=False."""
    img = _pil().open(io.BytesIO(data))
    img = img.convert('RGB' if is_color else 'L')
    return np.asarray(img)


def load_image(file_path, is_color=True):
    """Load an image file to an HWC uint8 array (HW if not color)."""
    with open(file_path, 'rb') as f:
        return load_image_bytes(f.read(), is_color=is_color)


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size`, keeping aspect."""
    h, w = im.shape[:2]
    if h > w:
        new_w, new_h = size, int(round(h * size / float(w)))
    else:
        new_w, new_h = int(round(w * size / float(h))), size
    Image = _pil()
    mode = 'RGB' if im.ndim == 3 else 'L'
    arr = im if im.dtype == np.uint8 else \
        np.clip(im, 0, 255).astype(np.uint8)
    out = Image.fromarray(arr, mode=mode).resize((new_w, new_h),
                                                 Image.BILINEAR)
    return np.asarray(out)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (the layout the reference's conv stack feeds)."""
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = int(rng.uniform(0, h - size + 1))
    w_start = int(rng.uniform(0, w - size + 1))
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None, rng=None):
    """resize_short -> (random crop + coin-flip LR flip | center crop)
    -> CHW float32 -> optional mean subtraction (per-channel 3-vector
    or full array) — the reference's standard train/eval pipeline."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if rng.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype('float32')
    if mean is not None:
        mean = np.array(mean, dtype='float32')
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pre-batch a tar of images into pickled (data, label) block files
    (reference image.py:48 — the out-of-core preprocessing helper).
    Returns the meta-file path listing the batch files."""
    import os
    import pickle
    import tarfile

    out_path = data_file + '_batch'
    meta = '%s/batch_meta' % out_path
    if os.path.exists(meta):
        return meta
    os.makedirs(out_path, exist_ok=True)
    tf = tarfile.open(data_file)
    data, labels, file_id, batch_files = [], [], 0, []
    for mem in tf.getmembers():
        if mem.name not in img2label:
            continue
        data.append(tf.extractfile(mem).read())
        labels.append(img2label[mem.name])
        if len(data) == num_per_batch:
            bf = '%s/batch_%d' % (out_path, file_id)
            with open(bf, 'wb') as f:
                pickle.dump({'data': data, 'label': labels}, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            batch_files.append(bf)
            data, labels, file_id = [], [], file_id + 1
    if data:
        bf = '%s/batch_%d' % (out_path, file_id)
        with open(bf, 'wb') as f:
            pickle.dump({'data': data, 'label': labels}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        batch_files.append(bf)
    tf.close()
    with open(meta, 'w') as f:
        f.write('\n'.join(batch_files))
    return meta
