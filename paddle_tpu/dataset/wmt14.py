"""WMT-14 fr->en (reference python/paddle/dataset/wmt14.py): the
machine_translation book config. Samples: (src_ids, trg_ids_with_<s>,
trg_ids_with_<e>). Synthetic id sequences where trg is a noisy transform of
src, so seq2seq attention genuinely learns."""
from __future__ import annotations

from . import common

__all__ = ['train', 'test', 'N', 'get_dict', 'convert']

N = 30000               # reference dict size per side


def _creator(split, n_samples, dict_size):
    def reader():
        rng = common.synthetic_rng('wmt14', split)
        for _ in range(n_samples):
            slen = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, slen).astype('int64')
            # target: reversed source with small perturbation (learnable)
            trg = ((src[::-1] + 7) % dict_size)
            trg = [max(3, int(t)) for t in trg]
            yield (src.tolist(),
                   [0] + trg,        # <s> prefix
                   trg + [1])        # <e> suffix
    return reader


def train(dict_size):
    return _creator('train', 2048, dict_size)


def test(dict_size):
    return _creator('test', 256, dict_size)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict) id maps (reference wmt14.py:155; reverse
    gives id->word, matching the reference default)."""
    src = {('s%05d' % i): i for i in range(dict_size)}
    trg = {('t%05d' % i): i for i in range(dict_size)}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def convert(path):
    """Write train/test (dict_size 30000 — the reference tar's size)
    to RecordIO shards under `path`."""
    dict_size = 30000
    common.convert(path, train(dict_size), 1000, 'wmt14_train')
    common.convert(path, test(dict_size), 1000, 'wmt14_test')
