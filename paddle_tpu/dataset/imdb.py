"""IMDB sentiment (reference python/paddle/dataset/imdb.py): samples are
(list of word ids, 0/1 label). Synthetic: two vocab regions are biased by
class so sentiment models genuinely learn; word_dict() matches the
reference contract (word -> id, '<unk>' included)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train', 'test', 'word_dict', 'build_dict', 'convert']

_VOCAB = 5147          # smallish; reference's is ~5147 after cutoff
_N_TRAIN, _N_TEST = 2048, 512


def word_dict():
    d = {('w%04d' % i): i for i in range(_VOCAB - 1)}
    d['<unk>'] = _VOCAB - 1
    return d


def _creator(split, n):
    def reader():
        rng = common.synthetic_rng('imdb', split)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 60))
            # positive reviews draw 70% from the upper vocab half
            biased = rng.rand(length) < 0.7
            ids = np.where(
                biased == bool(label),
                rng.randint(half, _VOCAB - 1, length),
                rng.randint(0, half, length))
            yield ids.astype('int64').tolist(), label
    return reader


def train(word_idx=None):
    return _creator('train', _N_TRAIN)


def test(word_idx=None):
    return _creator('test', _N_TEST)


def build_dict(pattern=None, cutoff=0):
    """Vocabulary builder (reference imdb.py:58 walks the review tar
    with a frequency cutoff). The synthetic corpus has a fixed
    catalog, so every (pattern, cutoff) returns the same dict —
    documented divergence, same contract shape."""
    return word_dict()


def convert(path):
    """Write train/test to RecordIO shards under `path`."""
    w = word_dict()
    common.convert(path, train(w), 1000, 'imdb_train')
    common.convert(path, test(w), 1000, 'imdb_test')
