"""MNIST (reference python/paddle/dataset/mnist.py): samples are
(784 float32 in [-1, 1], int label). Synthetic: each class k draws from a
distinct gaussian blob pattern so classifiers genuinely learn."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train', 'test', 'convert']

_N_TRAIN, _N_TEST = 8192, 1024


def _class_means():
    rng = common.synthetic_rng('mnist', 'means')
    return rng.randn(10, 784).astype('float32') * 0.5


def reader_creator(split, n):
    means = _class_means()

    def reader():
        rng = common.synthetic_rng('mnist', split)
        for _ in range(n):
            label = int(rng.randint(0, 10))
            img = means[label] + 0.3 * rng.randn(784).astype('float32')
            img = np.clip(img, -1.0, 1.0).astype('float32')
            yield img, label
    return reader


def train():
    return reader_creator('train', _N_TRAIN)


def test():
    return reader_creator('test', _N_TEST)


def convert(path):
    """Write train/test to RecordIO shards under `path` (reference
    mnist.py:133)."""
    common.convert(path, train(), 1000, 'minist_train')
    common.convert(path, test(), 1000, 'minist_test')
