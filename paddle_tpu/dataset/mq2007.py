"""MQ2007 learning-to-rank (reference python/paddle/dataset/mq2007.py):
LETOR query-document features with relevance labels, servable in
pointwise / pairwise / listwise formats. Synthetic generator with the
reference's feature contract (46-dim vectors, relevance in {0,1,2},
grouped by query)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train', 'test']

FEATURE_DIM = 46
_N_QUERIES_TRAIN, _N_QUERIES_TEST = 128, 32


def _gen_query(rng):
    n_docs = int(rng.randint(5, 20))
    # latent direction makes relevance learnable from features
    w = rng.randn(FEATURE_DIM)
    feats, rels = [], []
    for _ in range(n_docs):
        f = rng.rand(FEATURE_DIM).astype('float32')
        score = float(f @ w)
        feats.append(f)
        rels.append(score)
    cut = np.percentile(rels, [60, 90])
    labels = [int(0 if r < cut[0] else (1 if r < cut[1] else 2))
              for r in rels]
    return feats, labels


def _creator(split, n_queries, format):
    def pointwise():
        rng = common.synthetic_rng('mq2007', split)
        for _ in range(n_queries):
            feats, labels = _gen_query(rng)
            for f, l in zip(feats, labels):
                yield f, l

    def pairwise():
        rng = common.synthetic_rng('mq2007', split)
        for _ in range(n_queries):
            feats, labels = _gen_query(rng)
            for i in range(len(feats)):
                for j in range(len(feats)):
                    if labels[i] > labels[j]:
                        yield labels[i], labels[j], feats[i], feats[j]

    def listwise():
        rng = common.synthetic_rng('mq2007', split)
        for _ in range(n_queries):
            feats, labels = _gen_query(rng)
            yield labels, feats

    return {'pointwise': pointwise, 'pairwise': pairwise,
            'listwise': listwise}[format]


def train(format='pairwise'):
    return _creator('train', _N_QUERIES_TRAIN, format)


def test(format='pairwise'):
    return _creator('test', _N_QUERIES_TEST, format)
