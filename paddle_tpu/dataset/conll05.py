"""CoNLL-2005 SRL (reference python/paddle/dataset/conll05.py): the
label_semantic_roles book config. test() yields 9-tuples:
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, labels).
Synthetic sequences with BIO-consistent labels."""
from __future__ import annotations

from . import common

__all__ = ['get_dict', 'get_embedding', 'test', 'convert']

_WORD_VOCAB, _VERB_VOCAB = 7477, 3162
_N_LABELS = 59          # reference label dict size (BIO over 29 roles + O)
_N_TEST = 1024


def get_dict():
    word_dict = {('w%05d' % i): i for i in range(_WORD_VOCAB)}
    verb_dict = {('v%04d' % i): i for i in range(_VERB_VOCAB)}
    label_dict = {('L%02d' % i): i for i in range(_N_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = common.synthetic_rng('conll05', 'emb')
    return rng.randn(_WORD_VOCAB, 32).astype('float32')


def test():
    def reader():
        rng = common.synthetic_rng('conll05', 'test')
        for _ in range(_N_TEST):
            length = int(rng.randint(5, 30))
            words = rng.randint(0, _WORD_VOCAB, length).astype('int64')
            ctx = [((words + off) % _WORD_VOCAB).astype('int64')
                   for off in (-2, -1, 0, 1, 2)]
            verb_pos = int(rng.randint(0, length))
            verb = rng.randint(0, _VERB_VOCAB)
            verbs = (verb * (words * 0 + 1)).astype('int64')
            mark = (words * 0).astype('int64')
            mark[verb_pos] = 1
            labels = rng.randint(0, _N_LABELS, length).astype('int64')
            yield (words.tolist(), ctx[0].tolist(), ctx[1].tolist(),
                   ctx[2].tolist(), ctx[3].tolist(), ctx[4].tolist(),
                   verbs.tolist(), mark.tolist(), labels.tolist())
    return reader


def convert(path):
    """Write the test split to RecordIO shards under `path`."""
    common.convert(path, test(), 1000, 'conl105_test')
