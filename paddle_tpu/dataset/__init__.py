"""Built-in datasets (reference python/paddle/dataset/: 15 modules with
download+cache). This environment has no network egress, so each module
serves DETERMINISTIC SYNTHETIC data with the exact sample format, dtypes,
vocab objects, and reader-creator API of the original -- training code is
source-compatible; only the underlying bytes differ. Real-data loading can
be re-enabled by dropping files into common.DATA_HOME."""
from . import common  # noqa: F401
from . import uci_housing  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import mq2007  # noqa: F401
from . import uci_housing as housing  # noqa: F401

__all__ = ['common', 'uci_housing', 'mnist', 'cifar', 'imdb', 'imikolov',
           'movielens', 'conll05', 'sentiment', 'wmt14', 'wmt16', 'flowers',
           'voc2012', 'mq2007']
