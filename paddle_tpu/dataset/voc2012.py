"""PASCAL VOC2012 segmentation (reference python/paddle/dataset/
voc2012.py): samples are (image CHW float32, segmentation label HW
int32) with 21 classes (20 objects + background) and the reference's
255 'void' border label. Synthetic generator with reference-shaped
data (offline image; same sample contract)."""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ['train', 'test', 'val']

_N_CLASSES = 21
_VOID = 255
_H = _W = 64            # reference images are variable-size; fixed here
_N_TRAIN, _N_TEST, _N_VAL = 512, 128, 128


def _creator(split, n):
    def reader():
        rng = common.synthetic_rng('voc2012', split)
        for _ in range(n):
            img = rng.rand(3, _H, _W).astype('float32')
            # blobby label map: a few rectangles of random classes on
            # background, with a 1px void border around each
            label = np.zeros((_H, _W), 'int32')
            for _k in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, _N_CLASSES))
                y0, x0 = rng.randint(0, _H - 8), rng.randint(0, _W - 8)
                h, w = rng.randint(4, 16), rng.randint(4, 16)
                y1, x1 = min(y0 + h, _H), min(x0 + w, _W)
                label[y0:y1, x0:x1] = cls
                if y0 > 0:
                    label[y0 - 1, x0:x1] = _VOID
                if y1 < _H:
                    label[y1, x0:x1] = _VOID
            yield img, label
    return reader


def train():
    return _creator('train', _N_TRAIN)


def test():
    return _creator('test', _N_TEST)


def val():
    return _creator('val', _N_VAL)
