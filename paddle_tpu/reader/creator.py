"""Reader creators (reference python/paddle/reader/creator.py): build
readers from in-memory arrays, text files, and the RecordIO shards
written by dataset.common.convert()."""
from __future__ import annotations

__all__ = ['np_array', 'text_file', 'recordio']


def np_array(x):
    """Reader over a numpy array: yields scalars of a vector, rows of
    a matrix — any sub-hyperplane indexed by the leading dim."""

    def reader():
        if x.ndim < 1:
            yield x
            return
        for e in x:
            yield e

    return reader


def text_file(path):
    """Reader yielding the file's lines with the trailing newline
    stripped."""

    def reader():
        with open(path, 'r') as f:
            for line in f:
                yield line.rstrip('\n')

    return reader


def recordio(paths, buf_size=100):
    """Reader over RecordIO files written by dataset.common.convert():
    yields unpickled samples with `buf_size` read-ahead (the
    reference wraps in reader.buffered the same way). `paths` is a
    path or a comma-separated list / sequence of paths."""
    import pickle

    from ..recordio import RecordIOScanner
    from .decorator import buffered

    if isinstance(paths, str):
        path_list = paths.split(',')
    else:
        path_list = list(paths)

    def reader():
        for path in path_list:
            s = RecordIOScanner(path)
            try:
                for rec in s:
                    yield pickle.loads(rec)
            finally:
                s.close()

    return buffered(reader, buf_size)
